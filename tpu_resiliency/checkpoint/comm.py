"""Host-side group communication for checkpoint coordination and replication.

The reference rides ``torch.distributed`` for three distinct things the checkpoint layer
needs (SURVEY §2.1/§2.6): small-object collectives (``all_gather_object`` for ckpt-ID
coverage, 1-int all-reduce for async-done agreement), process-group barriers, and
point-to-point tensor sends for shard retrieval (``group_utils.py:394-465``). On TPU the
accelerator interconnect is reserved for the training program; checkpoint coordination is
**host-side control plane**, so both live here, over TCP:

- :class:`StoreComm` — object collectives + barriers on the coordination KV store
  (``platform/store.py``). Fine for metadata (IDs, plans, flags): bytes to KBs.
- :class:`PeerExchange` — direct rank↔rank TCP links for tensor payloads (checkpoint
  shards are MBs–GBs and must not transit the KV server). Each rank listens on an
  ephemeral port published in the store under ``p2p/{rank}``; frames carry raw array
  bytes via the checkpoint container encoding (``checkpoint/format.py``).
"""

from __future__ import annotations

import hmac
import itertools
import os
import pickle
import secrets
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional, Sequence

from tpu_resiliency.exceptions import CheckpointError, StoreTimeoutError
from tpu_resiliency.platform import chaos, framing
from tpu_resiliency.platform.store import AUTH_KEY_ENV, StoreView, _hmac
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

# Checkpoint shards can be large; allow 16 GB frames on p2p links.
P2P_MAX_FRAME = 16 * 1024**3

#: length prefix framing inside a ranged-read reply payload (header pickle).
_RR_LEN = struct.Struct("<Q")


def _transfer_event(direction: str, nbytes: int, dt: float, **extra) -> None:
    """One ``p2p_transfer`` event per shard move — the volume is one per peer
    per replication round (minutes apart), so per-transfer events are cheap and
    feed both the live metrics sink and post-hoc aggregation
    (``utils/metrics.py:observe_record`` maps them to
    ``tpu_ckpt_replication_bytes_total`` and ``tpu_replication_mbps``)."""
    record_event(
        "checkpoint", "p2p_transfer",
        direction=direction, bytes=nbytes, duration_s=dt,
        mbps=(nbytes / dt / 1e6) if dt > 0 else 0.0, **extra,
    )


def _reachable_host() -> str:
    """Best-effort address peers on other hosts can dial: the address the kernel
    would route external traffic from, falling back to hostname resolution, then
    loopback (single-host case)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packets sent; just picks a route
            return s.getsockname()[0]
    except OSError:
        pass
    try:
        addr = socket.gethostbyname(socket.gethostname())
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    return "127.0.0.1"


class StoreComm:
    """Object collectives over the coordination store, scoped to a rank group.

    Every member must call each collective the same number of times in the same order
    (the usual collective contract). Data keys are namespaced by a per-tag round
    counter and deleted by the leader once every member has read them; barriers use
    **fixed** names per tag — the server's generation-counted reentrant barriers exist
    precisely so a steady-state poll loop doesn't mint unbounded server state.
    """

    def __init__(
        self,
        store: StoreView,
        rank: int,
        ranks: list[int],
        timeout: float = 300.0,
        generation: int = 0,
        tree_fanout: Optional[int] = None,
        tree_min_world: Optional[int] = None,
    ):
        if rank not in ranks:
            raise ValueError(f"rank {rank} not in group {ranks}")
        # ``generation`` isolates server-side barrier/round state across restart
        # rounds: a gather that timed out against a dead peer leaves its barrier
        # arrivals in place, and a later comm over the SAME membership (the peer
        # rejoined) would collide with them. Pass the restart iteration when
        # rebuilding groups after reassignment.
        self.store = store.scoped(
            f"comm/g{generation}/{'-'.join(map(str, sorted(ranks)))}"
        )
        self.rank = rank
        self.ranks = sorted(ranks)
        self.timeout = timeout
        self._rounds: dict[str, int] = {}
        # Tree collectives above a world-size floor (platform/treecomm.py):
        # the flat shapes put O(world) work on one store event loop per round;
        # the tree's critical path is O(fanout · log_fanout world) and its
        # edge keys hash across a sharded clique. Small groups stay flat —
        # fewer round trips, and identical behavior to every pre-tree build.
        # Every member MUST resolve the same fanout/floor (the env pair is
        # launcher-exported, same as the store address).
        from tpu_resiliency.platform import treecomm

        self.tree_fanout = int(
            tree_fanout
            if tree_fanout is not None
            else os.environ.get(treecomm.TREE_FANOUT_ENV, treecomm.DEFAULT_FANOUT)
        )
        self.tree_min_world = int(
            tree_min_world
            if tree_min_world is not None
            else os.environ.get(treecomm.TREE_MIN_ENV, treecomm.DEFAULT_TREE_MIN)
        )
        self._tree: Optional[treecomm.TreeComm] = None
        if len(self.ranks) >= self.tree_min_world:
            self._tree = treecomm.TreeComm(
                self.store.scoped("tree"),
                self.ranks.index(rank),
                len(self.ranks),
                fanout=self.tree_fanout,
            )

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    @property
    def is_leader(self) -> bool:
        return self.rank == self.ranks[0]

    def _round(self, tag: str) -> int:
        r = self._rounds.get(tag, 0)
        self._rounds[tag] = r + 1
        return r

    def barrier(self, tag: str = "barrier", timeout: Optional[float] = None) -> None:
        if self._tree is not None:
            self._tree.barrier(tag, timeout or self.timeout)
            return
        self.store.barrier_join(tag, self.rank, self.world_size, timeout or self.timeout)

    def all_gather(self, obj: Any, tag: str = "ag", timeout: Optional[float] = None) -> list:
        """Returns ``[obj_from_rank]`` ordered by group rank index.

        Flat shape (small groups): exactly one value-fetch round trip per
        collective — the entry barrier guarantees every member's value is
        set, so a single server-side ``prefix_get`` scan replaces N
        sequential polled ``get``\\ s (whose round-trip latency dominated the
        collective at any real group size). Two barriers total — entry
        (values complete) and exit (the leader's batched ``prefix_clear``
        only runs after everyone has read).

        Tree shape (world ≥ ``tree_min_world``): fan-in/fan-out through
        ``platform/treecomm.py`` — O(fanout · log world) critical-path hops,
        edge keys sharded across a store clique. Same return value, same
        ordering, same timeout-is-fatal contract.
        """
        t = timeout or self.timeout
        if self._tree is not None:
            return self._tree.all_gather(obj, tag=tag, timeout=t)
        r = self._round(tag)
        base = f"{tag}/{r}"
        self.store.set(f"{base}/{self.rank}", obj)
        self.store.barrier_join(f"{tag}/b0", self.rank, self.world_size, t)
        vals = self.store.prefix_get(f"{base}/")
        try:
            out = [vals[f"{base}/{peer}"] for peer in self.ranks]
        except KeyError as e:
            # Every member set its value before joining b0; a hole means the
            # store lost state (restarted mid-collective) — surface it.
            raise CheckpointError(
                f"all_gather {tag!r} round {r}: missing value for key {e} "
                f"(got {sorted(vals)})"
            ) from None
        # Exit barrier so the leader only deletes after everyone has read.
        self.store.barrier_join(f"{tag}/b1", self.rank, self.world_size, t)
        if self.is_leader:
            self.store.prefix_clear(f"{base}/")
        return out

    def broadcast(self, obj: Any, src: int, tag: str = "bc", timeout: Optional[float] = None) -> Any:
        """One value from ``src`` to every member.

        Flat shape (small groups): one source ``set``, everyone parks on the
        same key, one exit barrier. Tree shape (world ≥ ``tree_min_world``):
        the value fans out parent→child on per-child keys
        (``treecomm.broadcast``) so a reshard header broadcast at 4096 ranks
        is O(fanout · log N) hops instead of N waiters parked on one shard's
        event loop."""
        t = timeout or self.timeout
        if self._tree is not None:
            return self._tree.broadcast(
                obj, self.ranks.index(src), tag=tag, timeout=t
            )
        r = self._round(tag)
        base = f"{tag}/{r}"
        if self.rank == src:
            self.store.set(f"{base}/v", obj)
        value = self.store.get(f"{base}/v", timeout=t)
        self.store.barrier_join(f"{tag}/b", self.rank, self.world_size, t)
        if self.is_leader:
            self.store.delete(f"{base}/v")
        return value

    def all_reduce_and(self, value: bool, tag: str = "and") -> bool:
        """The reference's 1-int "is everyone done" agreement (``core.py:152-164``)."""
        return all(self.all_gather(bool(value), tag=tag))

    def all_reduce_max(self, value, tag: str = "max"):
        return max(self.all_gather(value, tag=tag))

    def all_reduce_min(self, value, tag: str = "min"):
        """Group-wide minimum — the recovery ladder's fallback-iteration
        agreement: every rank proposes its newest passing iteration and all
        adopt the smallest, so no rank can resume ahead of a peer whose disk
        lost more."""
        return min(self.all_gather(value, tag=tag))

    def make_sync_fn(self):
        """Adapter for :class:`AsyncCallsQueue`'s ``sync_fn``."""

        def sync_fn(local_done: bool) -> bool:
            return self.all_reduce_and(local_done, tag="ckpt-done")

        return sync_fn


class PeerExchange:
    """Rank↔rank bulk transfer channel for checkpoint shards.

    ``start()`` binds an ephemeral listener and publishes its address in the store;
    ``send(dst, tag, blob)`` pushes raw bytes to a peer; ``recv(src, tag)`` blocks for a
    matching frame. Message matching is (src, tag) so concurrent replication rounds with
    distinct tags don't cross. Analogue of the reference's isend/irecv shard routing
    (``checkpointing/local/replication/group_utils.py:394-465``).

    **Wire protocol (v2).** The hello each side already exchanges carries ``v``;
    a v2→v2 link moves payloads as raw bulk frames (small pickled header + raw
    bytes, ``framing.send_bulk``): the sender scatter-gathers the caller's
    buffers straight onto the socket (:meth:`send_parts`) or splices a file with
    ``os.sendfile`` (:meth:`send_file`); the receiver lands the payload in ONE
    preallocated buffer — a registered :meth:`recv_into` destination when the
    caller provided one. Talking to a v1 peer (hello ``v`` < 2, or this side
    constructed with ``protocol=1``) transparently falls back to the pickled
    ``{"src", "tag", "blob"}`` object frame, and a v2 receiver accepts both
    kinds on one stream — mixed-version cliques round-trip byte-identically.
    """

    def __init__(
        self,
        store: StoreView,
        rank: int,
        timeout: float = 300.0,
        auth_key: Optional[str] = None,
        protocol: Optional[int] = None,
        send_retries: int = 3,
        wire_checksums: bool = False,
    ):
        self.store = store.scoped("p2p")
        self.rank = rank
        self.timeout = timeout
        #: Stamp a payload CRC into every ``send_parts`` bulk-frame header;
        #: the receiving side (``framing.recv_any``) verifies it and drops a
        #: mismatching frame like any malformed one (the sender-side retry /
        #: degraded-peer machinery then owns recovery). Off by default: v2
        #: checkpoint containers already carry end-to-end trailer checksums
        #: that cover the wire for free, and the extra CRC pass costs a full
        #: memory read per send. Turn on for non-container payloads or
        #: belt-and-braces wire auditing. ``send_file``/streamed sends never
        #: stamp one (the header is gone before the payload is known).
        self.wire_checksums = bool(wire_checksums)
        #: dial-and-send attempts per peer before a send surfaces
        #: :class:`CheckpointError`. Each retry re-resolves the peer's address
        #: from the store and re-runs the hello handshake, so a peer that
        #: restarted (new ephemeral port) is picked up mid-round.
        self.send_retries = max(1, send_retries)
        if auth_key is None:
            auth_key = os.environ.get(AUTH_KEY_ENV) or None
        self.auth_key = auth_key
        #: Advertised/spoken protocol version; ``protocol=1`` pins this end to
        #: the legacy pickled-blob frames (rolling upgrades, benchmarks).
        self.protocol = min(framing.PROTO_VERSION, protocol or framing.PROTO_VERSION)
        self._sock: Optional[socket.socket] = None
        self._inbox: dict[tuple[int, str], list] = {}
        #: (src, tag) → caller-registered receive buffers (``recv_into``): the
        #: accept thread lands a matching bulk payload directly in one of these
        #: instead of allocating.
        self._pending: dict[tuple[int, str], list[memoryview]] = {}
        self._cond = threading.Condition()
        self._shutdown = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._addr_cache: dict[int, tuple[str, int]] = {}
        #: ranged-read server: ``handler(request) -> (extra_header, parts)``
        #: registered by :meth:`serve_ranges` (the local checkpoint manager
        #: wires its shard files in); requests arrive as ``op: range_read``
        #: frames and are answered by dialing the requester back.
        self._range_handler: Optional[Callable] = None
        self._rr_counter = itertools.count()

    def start(self, host: Optional[str] = None, advertise_host: Optional[str] = None) -> None:
        """Bind the listener and publish its address.

        Frames are pickled, so an unauthenticated off-host listener would be remote
        code execution. The rules mirror :class:`KVServer`: with an auth key (arg or
        ``$TPU_RESILIENCY_STORE_KEY``) the default bind is ``0.0.0.0`` and every
        accepted connection must pass an HMAC challenge; without one the default is
        loopback, and an explicit non-loopback bind raises.
        """
        if host is None:
            host = "0.0.0.0" if self.auth_key else "127.0.0.1"
            if not self.auth_key:
                log.warning(
                    "PeerExchange: no auth key set — binding loopback only; "
                    f"cross-host replication requires ${AUTH_KEY_ENV}"
                )
        elif host not in ("127.0.0.1", "localhost", "::1") and not self.auth_key:
            raise ValueError(
                f"refusing to bind PeerExchange on non-loopback {host!r} without an "
                f"auth key (frames are pickled; unauthenticated exposure is remote "
                f"code execution). Pass auth_key= or set ${AUTH_KEY_ENV}."
            )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(128)
        port = self._sock.getsockname()[1]
        if advertise_host is None:
            # Replication cliques span hosts by design (replication_jump), so the
            # advertised address must be reachable off-host: a wildcard bind
            # advertises this host's resolvable name, a specific bind advertises
            # itself.
            advertise_host = _reachable_host() if host == "0.0.0.0" else host
        self.store.set(f"addr/{self.rank}", (advertise_host, port))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"p2p-accept-{self.rank}", daemon=True
        )
        self._accept_thread.start()

    def close(self) -> None:
        self._shutdown.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._cond:
            self._cond.notify_all()

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if chaos.check_accept("p2p"):
                conn.close()  # injected EOF-on-accept; the sender retries
                continue
            conn = chaos.wrap(conn, "p2p")
            threading.Thread(
                target=self._recv_conn, args=(conn,), daemon=True, name="p2p-recv"
            ).start()

    def _claim_buffer(self, header: dict) -> Optional[memoryview]:
        """Accept-thread side of :meth:`recv_into`: pop a registered destination
        buffer for this frame's (src, tag) if one fits, else None (fresh alloc)."""
        try:
            key = (header["src"], header["tag"])
            nbytes = int(header["nbytes"])
        except (KeyError, TypeError, ValueError):
            return None
        with self._cond:
            bufs = self._pending.get(key)
            if not bufs:
                return None
            for i, view in enumerate(bufs):
                if view.nbytes >= nbytes:
                    return bufs.pop(i)
            log.warning(
                f"p2p: registered recv_into buffer(s) for {key} too small for "
                f"{nbytes} B frame; receiving into a fresh buffer"
            )
            return None

    def _recv_conn(self, conn: socket.socket) -> None:
        try:
            if not self._handshake_server(conn):
                return
            t0 = time.perf_counter()
            kind, msg, payload = framing.recv_any(
                conn, max_frame=P2P_MAX_FRAME, alloc=self._claim_buffer
            )
            if kind == "bulk":
                src, tag = msg["src"], msg["tag"]
            elif isinstance(msg, dict) and msg.get("op") == "range_read":
                # Request/response op, not inbox traffic: serve it on this
                # connection's thread (the reply dials the requester back).
                self._handle_range_read(msg)
                return
            else:
                src, tag, payload = msg["src"], msg["tag"], msg["blob"]
            nbytes = memoryview(payload).cast("B").nbytes if payload is not None else 0
            _transfer_event(
                "recv", nbytes, time.perf_counter() - t0, src=src, frame=kind,
                tag=tag,
            )
            with self._cond:
                self._inbox.setdefault((src, tag), []).append(payload)
                self._cond.notify_all()
        except (ConnectionError, EOFError, OSError, KeyError, TypeError, ValueError):
            log.warning("p2p: dropped malformed incoming frame", exc_info=True)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handshake_server(self, conn: socket.socket) -> bool:
        """Challenge/response before any pickled payload is parsed (same hello
        protocol as ``KVServer`` — see its ``_accept``/``_parse`` auth path). No-op
        when auth is off (loopback-only bind). The hello's ``v`` advertises this
        end's protocol ceiling; the connecting sender picks the frame format."""
        nonce = secrets.token_bytes(16)
        framing.send_obj(
            conn, {"v": self.protocol, "auth": self.auth_key is not None, "nonce": nonce}
        )
        if self.auth_key is None:
            return True
        conn.settimeout(30.0)
        reply = framing.recv_obj(conn, max_frame=1024)
        ok = isinstance(reply, dict) and hmac.compare_digest(
            reply.get("mac", b""), _hmac(self.auth_key, nonce)
        )
        if not ok:
            log.warning("p2p: rejected connection with bad auth")
        conn.settimeout(None)
        return ok

    def _handshake_client(self, conn: socket.socket) -> int:
        """Returns the peer's advertised protocol version (1 for pre-versioned
        hellos — every peer has sent ``v`` since v1, but default defensively)."""
        hello = framing.recv_obj(conn, max_frame=1024)
        peer_v = 1
        if isinstance(hello, dict):
            try:
                peer_v = int(hello.get("v", 1))
            except (TypeError, ValueError):
                peer_v = 1
            if hello.get("auth"):
                if self.auth_key is None:
                    raise CheckpointError(
                        f"p2p peer requires authentication; set ${AUTH_KEY_ENV}"
                    )
                framing.send_obj(conn, {"mac": _hmac(self.auth_key, hello["nonce"])})
        return peer_v

    def _peer_addr(self, peer: int) -> tuple[str, int]:
        if peer not in self._addr_cache:
            try:
                self._addr_cache[peer] = tuple(
                    self.store.get(f"addr/{peer}", timeout=self.timeout)
                )
            except StoreTimeoutError as e:
                raise CheckpointError(f"p2p: no address published for rank {peer}") from e
        return self._addr_cache[peer]

    def _dial(self, dst: int) -> tuple[socket.socket, int]:
        """Connect + handshake; returns ``(socket, peer_protocol_version)``."""
        host, port = self._peer_addr(dst)
        chaos.check_connect("p2p", peer=str(dst))
        conn = socket.create_connection((host, port), timeout=self.timeout)
        conn = chaos.wrap(conn, "p2p", peer=str(dst))
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer_v = self._handshake_client(conn)
        except BaseException:
            conn.close()
            raise
        return conn, peer_v

    def _use_bulk(self, peer_v: int) -> bool:
        return peer_v >= framing.PROTO_V2 and self.protocol >= framing.PROTO_V2

    def _retry_send(self, dst: int, what: str, attempt_fn):
        """Run one dial-and-send attempt factory under the per-peer retry
        policy: a transport fault (reset, EOF mid-handshake, truncated frame)
        invalidates the cached peer address — the peer may have restarted on a
        new port — backs off, re-dials with a fresh hello, and reissues the
        whole send. Frames are delivered whole or not at all (a truncated bulk
        frame reads as EOF and is dropped by the receiver), so a re-send can
        duplicate a frame but never corrupt one; receivers treat a duplicate
        (src, tag) frame as inbox surplus that ``purge`` reclaims.
        """
        delay = 0.05
        last: Exception | None = None
        for attempt in range(self.send_retries):
            try:
                return attempt_fn()
            except (OSError, EOFError) as e:
                last = e
                self._addr_cache.pop(dst, None)
                if attempt + 1 >= self.send_retries:
                    break
                log.warning(
                    f"p2p: {what} to rank {dst} failed ({e!r}); "
                    f"retry {attempt + 1}/{self.send_retries - 1}"
                )
                record_event(
                    "checkpoint", "p2p_retry", dst=dst, what=what,
                    attempt=attempt + 1, error=repr(e),
                )
                time.sleep(delay)
                delay = min(delay * 2.0, 1.0)
        raise CheckpointError(
            f"p2p: {what} to rank {dst} failed after "
            f"{self.send_retries} attempt(s): {last!r}"
        ) from last

    def send(self, dst: int, tag: str, blob) -> None:
        """Push one bytes-like payload to a peer (sugar over :meth:`send_parts`)."""
        self.send_parts(dst, tag, [blob])

    def send_parts(self, dst: int, tag: str, parts: Sequence[Any]) -> int:
        """Send a payload as its constituent buffers, never joining them.

        On a v2 link the parts go out as one bulk frame, scatter-gathered from
        the caller's buffers (``socket.sendmsg``) — zero userspace copies. A v1
        peer gets the legacy pickled ``{"src", "tag", "blob"}`` frame (one join,
        the price of compatibility). Transient transport faults are absorbed by
        the per-peer retry policy (``send_retries``). Returns payload bytes sent.
        """
        return self._retry_send(
            dst, f"send({tag!r})", lambda: self._send_parts_once(dst, tag, parts)
        )

    def _send_parts_once(self, dst: int, tag: str, parts: Sequence[Any]) -> int:
        conn, peer_v = self._dial(dst)
        t0 = time.perf_counter()
        with conn:
            if self._use_bulk(peer_v):
                header = {"src": self.rank, "tag": tag}
                if self.wire_checksums:
                    from tpu_resiliency.checkpoint import format as ckpt_format

                    crc = 0
                    for p in parts:
                        crc = ckpt_format.crc32c(p, crc)
                    # The algo rides along so a receiver built with the OTHER
                    # checksum implementation skips verification instead of
                    # dropping every frame as a false mismatch.
                    header["crc32c"] = crc
                    header["crc_algo"] = ckpt_format.CRC_ALGO
                nbytes = framing.send_bulk(conn, header, parts)
                frame = "bulk"
            else:
                blob = b"".join(bytes(memoryview(p).cast("B")) for p in parts)
                framing.send_obj(conn, {"src": self.rank, "tag": tag, "blob": blob})
                nbytes = len(blob)
                frame = "obj"
        _transfer_event("send", nbytes, time.perf_counter() - t0, dst=dst,
                        frame=frame, tag=tag)
        return nbytes

    def open_send_stream(self, dst: int, tag: str, nbytes: int) -> "StreamSend":
        """Open a send whose payload is pushed in chunks as it materializes —
        the pipelined-save primitive: the bulk preamble (total ``nbytes``)
        goes out immediately, then each checkpoint leaf hits the socket the
        moment its D2H transfer lands, overlapping device copies with the wire.

        On a v2 link the chunks stream straight onto the open connection; a v1
        peer can only accept whole pickled frames, so chunks are buffered and
        sent as one legacy frame at ``close()`` (compatibility, not speed).
        Always ``close()`` (success) or ``abort()`` (failure) the handle — an
        under-sent bulk frame otherwise desyncs the peer's stream. The open
        (dial + preamble) is retried like any send; once chunks are flowing a
        fault aborts the stream (the caller's leaves are transient — replaying
        them is the save engine's call, not this layer's)."""

        def attempt():
            conn, peer_v = self._dial(dst)
            use_bulk = self._use_bulk(peer_v)
            try:
                if use_bulk:
                    framing.send_bulk_start(
                        conn, {"src": self.rank, "tag": tag}, nbytes
                    )
            except BaseException:
                conn.close()
                raise
            return StreamSend(self, conn, use_bulk, dst, tag, nbytes)

        return self._retry_send(dst, f"stream open({tag!r})", attempt)

    def send_file(self, dst: int, tag: str, path: str) -> int:
        """Stream an on-disk payload to a peer.

        On a v2 link the file is spliced kernel-side with ``os.sendfile`` — the
        shard never enters userspace. A v1 peer forces the legacy whole-blob
        frame (read + pickle). Transient transport faults are absorbed by the
        per-peer retry policy (the file is still there — a re-send is free).
        Returns payload bytes sent.
        """
        return self._retry_send(
            dst, f"send_file({path!r})", lambda: self._send_file_once(dst, tag, path)
        )

    def _send_file_once(self, dst: int, tag: str, path: str) -> int:
        conn, peer_v = self._dial(dst)
        t0 = time.perf_counter()
        with conn:
            if self._use_bulk(peer_v):
                nbytes = framing.send_bulk_file(
                    conn, {"src": self.rank, "tag": tag}, path
                )
                frame = "file"
            else:
                with open(path, "rb") as f:
                    blob = f.read()
                framing.send_obj(conn, {"src": self.rank, "tag": tag, "blob": blob})
                nbytes = len(blob)
                frame = "obj"
        _transfer_event("send", nbytes, time.perf_counter() - t0, dst=dst,
                        frame=frame, tag=tag)
        return nbytes

    def recv(self, src: int, tag: str, timeout: Optional[float] = None):
        """Block for a matching frame; returns its payload (bytes-like: ``bytes``
        from a v1 frame, a ``memoryview`` over the receive buffer from a bulk
        frame — pass it to ``format.deserialize_from_buffer`` / ``write_parts``
        without copying)."""
        deadline = time.monotonic() + (timeout or self.timeout)
        key = (src, tag)
        with self._cond:
            while not self._inbox.get(key):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CheckpointError(f"p2p: timed out waiting for {tag!r} from rank {src}")
                self._cond.wait(timeout=min(remaining, 1.0))
            return self._inbox[key].pop(0)

    def recv_into(self, src: int, tag: str, buf, timeout: Optional[float] = None) -> int:
        """Receive a matching frame directly into ``buf``; returns payload size.

        Registering ``buf`` before the frame arrives lets the accept thread
        ``recv_into`` the wire payload straight into it — zero extra allocation
        and zero copies. If the frame raced ahead of the registration (already
        in the inbox) or came from a v1 peer, the payload lands with one copy.
        At most one in-flight frame per (src, tag) is supported on this path —
        the per-round unique-tag discipline the replication layer follows.
        """
        base = buf.obj if isinstance(buf, memoryview) else buf
        view = memoryview(buf).cast("B")
        key = (src, tag)
        with self._cond:
            self._pending.setdefault(key, []).append(view)
        try:
            got = self.recv(src, tag, timeout)
        finally:
            with self._cond:
                bufs = self._pending.get(key)
                if bufs is not None:
                    try:
                        bufs.remove(view)
                    except ValueError:
                        pass  # claimed by the accept thread — the fast path
                    if not bufs:
                        self._pending.pop(key, None)
        gv = memoryview(got).cast("B")
        n = gv.nbytes
        if gv.obj is base:
            return n  # landed in place
        if n > view.nbytes:
            raise CheckpointError(
                f"p2p: recv_into buffer too small for {tag!r} from rank {src}: "
                f"{view.nbytes} < {n}"
            )
        view[:n] = gv
        return n

    # -- ranged reads (the elastic-reshard wire op) ------------------------

    def serve_ranges(self, handler: Optional[Callable]) -> None:
        """Register (or clear, with ``None``) the ranged-read server.

        ``handler(request: dict) -> (extra_header: dict, parts: list)`` runs
        on a p2p connection thread for every incoming ``range_read`` frame:
        it resolves the request (for checkpoints: an ``(owner, iteration)``
        container plus leaf-relative byte ranges — see
        ``LocalCheckpointManager``) and returns the byte parts to ship back.
        Exceptions become structured error replies, never dropped requests.
        """
        self._range_handler = handler

    def fetch_ranges(
        self, dst: int, request: dict, timeout: Optional[float] = None
    ) -> tuple[dict, list[memoryview]]:
        """Read byte ranges from a peer: one small request frame out, one bulk
        reply back, each part CRC-verified (the PR-5 checksummer) before it is
        returned. The reshard load path fetches ONLY the ranges a rank newly
        owns this way, instead of retrieving whole mirror containers.

        Returns ``(reply_header, parts)`` — parts are zero-copy views over the
        reply's receive buffer, ordered like ``request["ranges"]``. Raises
        :class:`CheckpointError` on a structured error reply, a checksum
        mismatch, or transport failure (after the per-peer retry policy).
        """
        tag = f"rread/{self.rank}/{next(self._rr_counter)}"
        frame = {"op": "range_read", "src": self.rank, "reply_tag": tag,
                 "req": request}

        def attempt():
            conn, _ = self._dial(dst)
            with conn:
                framing.send_obj(conn, frame)

        self._retry_send(dst, f"range_read({tag!r})", attempt)
        payload = self.recv(dst, tag, timeout)
        return self._parse_range_reply(payload, dst)

    def _parse_range_reply(
        self, payload, src: int
    ) -> tuple[dict, list[memoryview]]:
        from tpu_resiliency.checkpoint import format as ckpt_format

        mv = memoryview(payload).cast("B")
        try:
            (hlen,) = _RR_LEN.unpack(mv[: _RR_LEN.size])
            header = pickle.loads(mv[_RR_LEN.size : _RR_LEN.size + hlen])
        except Exception as e:
            raise CheckpointError(
                f"p2p: malformed range_read reply from rank {src} ({e!r})"
            ) from e
        if not header.get("ok"):
            raise CheckpointError(
                f"p2p: range_read against rank {src} failed: "
                f"{header.get('error', 'unknown error')}"
            )
        parts: list[memoryview] = []
        off = _RR_LEN.size + hlen
        lengths = header.get("lengths") or []
        crcs = header.get("crc32c") or []
        verify = header.get("crc_algo") == ckpt_format.CRC_ALGO and len(
            crcs
        ) == len(lengths)
        for i, n in enumerate(lengths):
            n = int(n)
            if off + n > mv.nbytes:
                raise CheckpointError(
                    f"p2p: truncated range_read reply from rank {src} "
                    f"(part {i} wants {n} bytes past the frame)"
                )
            window = mv[off : off + n]
            # Per-range verification: each range is checksummed by the sender
            # and re-checked here before the caller ever sees the bytes.
            if verify and ckpt_format.crc32c(window) != crcs[i]:
                raise CheckpointError(
                    f"p2p: range_read part {i} from rank {src} failed its "
                    f"checksum (range corrupted in flight)"
                )
            parts.append(window)
            off += n
        return header, parts

    def _handle_range_read(self, msg: dict) -> None:
        from tpu_resiliency.checkpoint import format as ckpt_format

        try:
            src, tag = int(msg["src"]), str(msg["reply_tag"])
        except (KeyError, TypeError, ValueError):
            log.warning("p2p: dropped malformed range_read request")
            return
        handler = self._range_handler
        views: list[memoryview] = []
        try:
            if handler is None:
                raise CheckpointError(
                    f"rank {self.rank} serves no ranged reads (no local "
                    f"checkpoint manager registered)"
                )
            extra, parts = handler(msg.get("req") or {})
            views = [memoryview(p).cast("B") for p in parts]
            header = {
                "ok": True,
                "lengths": [v.nbytes for v in views],
                "crc32c": [ckpt_format.crc32c(v) for v in views],
                "crc_algo": ckpt_format.CRC_ALGO,
                **(extra or {}),
            }
        except Exception as e:
            header, views = {"ok": False, "error": str(e)}, []
        blob = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            self.send_parts(src, tag, [_RR_LEN.pack(len(blob)), blob, *views])
        except CheckpointError as e:
            # The requester timed out / died; it owns its own recovery.
            log.warning(f"p2p: range_read reply to rank {src} failed: {e}")

    def purge(self, tag_prefix: str) -> int:
        """Drop queued frames (and pending ``recv_into`` registrations) whose tag
        starts with ``tag_prefix``; returns the number of frames dropped.

        Frames nobody ever ``recv``\\ s — a peer restarted mid-round, an
        abandoned replication round — would otherwise pin their multi-GB
        payloads in ``_inbox`` for the process's lifetime, and stale frames
        under a reused tag would be mis-delivered to the next round.
        ``CliqueReplicationStrategy.rebuild`` calls this when it resets its
        round counter.
        """
        with self._cond:
            dead = [k for k in self._inbox if k[1].startswith(tag_prefix)]
            n = sum(len(self._inbox[k]) for k in dead)
            for k in dead:
                del self._inbox[k]
            for k in [k for k in self._pending if k[1].startswith(tag_prefix)]:
                del self._pending[k]
        if n:
            log.info(f"p2p: purged {n} stale frame(s) under tag prefix {tag_prefix!r}")
        return n


class StreamSend:
    """One open, chunked payload send (see :meth:`PeerExchange.open_send_stream`).

    ``send_chunk`` pushes raw bytes onto the wire (v2) or buffers them (v1
    peer). ``close()`` completes the frame — it verifies exactly the promised
    byte count was sent (an under-sent bulk frame would desync the receiver's
    stream) and emits the ``p2p_transfer`` event. ``abort()`` tears the
    connection down so the peer sees EOF instead of a stuck partial frame.
    """

    def __init__(
        self,
        ex: PeerExchange,
        conn: socket.socket,
        use_bulk: bool,
        dst: int,
        tag: str,
        nbytes: int,
    ):
        self._ex = ex
        self._conn = conn
        self._use_bulk = use_bulk
        self.dst = dst
        self.tag = tag
        self.nbytes = nbytes
        self._sent = 0
        self._t0 = time.perf_counter()
        self._chunks: list[bytes] = []  # v1 fallback buffer
        self._closed = False

    def send_chunk(self, chunk) -> int:
        v = memoryview(chunk).cast("B")
        if self._sent + v.nbytes > self.nbytes:
            raise CheckpointError(
                f"p2p: stream to rank {self.dst} overran its declared size "
                f"({self._sent + v.nbytes} > {self.nbytes})"
            )
        try:
            if self._use_bulk:
                self._conn.sendall(v)
            else:
                self._chunks.append(bytes(v))
        except OSError as e:
            self.abort()
            raise CheckpointError(
                f"p2p: stream send to rank {self.dst} failed: {e!r}"
            ) from e
        self._sent += v.nbytes
        return v.nbytes

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._sent != self.nbytes:
                raise CheckpointError(
                    f"p2p: stream to rank {self.dst} closed after {self._sent} of "
                    f"{self.nbytes} bytes"
                )
            if not self._use_bulk:
                framing.send_obj(
                    self._conn,
                    {"src": self._ex.rank, "tag": self.tag,
                     "blob": b"".join(self._chunks)},
                )
        except OSError as e:
            raise CheckpointError(
                f"p2p: stream close to rank {self.dst} failed: {e!r}"
            ) from e
        finally:
            self._chunks = []
            try:
                self._conn.close()
            except OSError:
                pass
        _transfer_event(
            "send", self.nbytes, time.perf_counter() - self._t0,
            dst=self.dst, frame="bulk" if self._use_bulk else "obj",
            tag=self.tag,
        )

    def abort(self) -> None:
        """Drop the connection mid-frame; the peer's receive loop sees EOF and
        discards the partial payload."""
        self._closed = True
        self._chunks = []
        try:
            self._conn.close()
        except OSError:
            pass

    def __enter__(self) -> "StreamSend":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            self.abort()
