"""Host-side group communication for checkpoint coordination and replication.

The reference rides ``torch.distributed`` for three distinct things the checkpoint layer
needs (SURVEY §2.1/§2.6): small-object collectives (``all_gather_object`` for ckpt-ID
coverage, 1-int all-reduce for async-done agreement), process-group barriers, and
point-to-point tensor sends for shard retrieval (``group_utils.py:394-465``). On TPU the
accelerator interconnect is reserved for the training program; checkpoint coordination is
**host-side control plane**, so both live here, over TCP:

- :class:`StoreComm` — object collectives + barriers on the coordination KV store
  (``platform/store.py``). Fine for metadata (IDs, plans, flags): bytes to KBs.
- :class:`PeerExchange` — direct rank↔rank TCP links for tensor payloads (checkpoint
  shards are MBs–GBs and must not transit the KV server). Each rank listens on an
  ephemeral port published in the store under ``p2p/{rank}``; frames carry raw array
  bytes via the checkpoint container encoding (``checkpoint/format.py``).
"""

from __future__ import annotations

import hmac
import os
import secrets
import socket
import threading
from typing import Any, Optional

from tpu_resiliency.exceptions import CheckpointError, StoreTimeoutError
from tpu_resiliency.platform import framing
from tpu_resiliency.platform.store import AUTH_KEY_ENV, StoreView, _hmac
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

# Checkpoint shards can be large; allow 16 GB frames on p2p links.
P2P_MAX_FRAME = 16 * 1024**3


def _reachable_host() -> str:
    """Best-effort address peers on other hosts can dial: the address the kernel
    would route external traffic from, falling back to hostname resolution, then
    loopback (single-host case)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packets sent; just picks a route
            return s.getsockname()[0]
    except OSError:
        pass
    try:
        addr = socket.gethostbyname(socket.gethostname())
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    return "127.0.0.1"


class StoreComm:
    """Object collectives over the coordination store, scoped to a rank group.

    Every member must call each collective the same number of times in the same order
    (the usual collective contract). Data keys are namespaced by a per-tag round
    counter and deleted by the leader once every member has read them; barriers use
    **fixed** names per tag — the server's generation-counted reentrant barriers exist
    precisely so a steady-state poll loop doesn't mint unbounded server state.
    """

    def __init__(
        self,
        store: StoreView,
        rank: int,
        ranks: list[int],
        timeout: float = 300.0,
        generation: int = 0,
    ):
        if rank not in ranks:
            raise ValueError(f"rank {rank} not in group {ranks}")
        # ``generation`` isolates server-side barrier/round state across restart
        # rounds: a gather that timed out against a dead peer leaves its barrier
        # arrivals in place, and a later comm over the SAME membership (the peer
        # rejoined) would collide with them. Pass the restart iteration when
        # rebuilding groups after reassignment.
        self.store = store.scoped(
            f"comm/g{generation}/{'-'.join(map(str, sorted(ranks)))}"
        )
        self.rank = rank
        self.ranks = sorted(ranks)
        self.timeout = timeout
        self._rounds: dict[str, int] = {}

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    @property
    def is_leader(self) -> bool:
        return self.rank == self.ranks[0]

    def _round(self, tag: str) -> int:
        r = self._rounds.get(tag, 0)
        self._rounds[tag] = r + 1
        return r

    def barrier(self, tag: str = "barrier", timeout: Optional[float] = None) -> None:
        self.store.barrier_join(tag, self.rank, self.world_size, timeout or self.timeout)

    def all_gather(self, obj: Any, tag: str = "ag", timeout: Optional[float] = None) -> list:
        """Returns ``[obj_from_rank]`` ordered by group rank index."""
        t = timeout or self.timeout
        r = self._round(tag)
        base = f"{tag}/{r}"
        self.store.set(f"{base}/{self.rank}", obj)
        self.store.barrier_join(f"{tag}/b0", self.rank, self.world_size, t)
        out = [self.store.get(f"{base}/{peer}", timeout=t) for peer in self.ranks]
        # Exit barrier so the leader only deletes after everyone has read.
        self.store.barrier_join(f"{tag}/b1", self.rank, self.world_size, t)
        if self.is_leader:
            for peer in self.ranks:
                self.store.delete(f"{base}/{peer}")
        return out

    def broadcast(self, obj: Any, src: int, tag: str = "bc", timeout: Optional[float] = None) -> Any:
        t = timeout or self.timeout
        r = self._round(tag)
        base = f"{tag}/{r}"
        if self.rank == src:
            self.store.set(f"{base}/v", obj)
        value = self.store.get(f"{base}/v", timeout=t)
        self.store.barrier_join(f"{tag}/b", self.rank, self.world_size, t)
        if self.is_leader:
            self.store.delete(f"{base}/v")
        return value

    def all_reduce_and(self, value: bool, tag: str = "and") -> bool:
        """The reference's 1-int "is everyone done" agreement (``core.py:152-164``)."""
        return all(self.all_gather(bool(value), tag=tag))

    def all_reduce_max(self, value, tag: str = "max"):
        return max(self.all_gather(value, tag=tag))

    def make_sync_fn(self):
        """Adapter for :class:`AsyncCallsQueue`'s ``sync_fn``."""

        def sync_fn(local_done: bool) -> bool:
            return self.all_reduce_and(local_done, tag="ckpt-done")

        return sync_fn


class PeerExchange:
    """Rank↔rank bulk transfer channel for checkpoint shards.

    ``start()`` binds an ephemeral listener and publishes its address in the store;
    ``send(dst, tag, blob)`` pushes raw bytes to a peer; ``recv(src, tag)`` blocks for a
    matching frame. Message matching is (src, tag) so concurrent replication rounds with
    distinct tags don't cross. Analogue of the reference's isend/irecv shard routing
    (``checkpointing/local/replication/group_utils.py:394-465``).
    """

    def __init__(
        self,
        store: StoreView,
        rank: int,
        timeout: float = 300.0,
        auth_key: Optional[str] = None,
    ):
        self.store = store.scoped("p2p")
        self.rank = rank
        self.timeout = timeout
        if auth_key is None:
            auth_key = os.environ.get(AUTH_KEY_ENV) or None
        self.auth_key = auth_key
        self._sock: Optional[socket.socket] = None
        self._inbox: dict[tuple[int, str], list[bytes]] = {}
        self._cond = threading.Condition()
        self._shutdown = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._addr_cache: dict[int, tuple[str, int]] = {}

    def start(self, host: Optional[str] = None, advertise_host: Optional[str] = None) -> None:
        """Bind the listener and publish its address.

        Frames are pickled, so an unauthenticated off-host listener would be remote
        code execution. The rules mirror :class:`KVServer`: with an auth key (arg or
        ``$TPU_RESILIENCY_STORE_KEY``) the default bind is ``0.0.0.0`` and every
        accepted connection must pass an HMAC challenge; without one the default is
        loopback, and an explicit non-loopback bind raises.
        """
        if host is None:
            host = "0.0.0.0" if self.auth_key else "127.0.0.1"
            if not self.auth_key:
                log.warning(
                    "PeerExchange: no auth key set — binding loopback only; "
                    f"cross-host replication requires ${AUTH_KEY_ENV}"
                )
        elif host not in ("127.0.0.1", "localhost", "::1") and not self.auth_key:
            raise ValueError(
                f"refusing to bind PeerExchange on non-loopback {host!r} without an "
                f"auth key (frames are pickled; unauthenticated exposure is remote "
                f"code execution). Pass auth_key= or set ${AUTH_KEY_ENV}."
            )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(128)
        port = self._sock.getsockname()[1]
        if advertise_host is None:
            # Replication cliques span hosts by design (replication_jump), so the
            # advertised address must be reachable off-host: a wildcard bind
            # advertises this host's resolvable name, a specific bind advertises
            # itself.
            advertise_host = _reachable_host() if host == "0.0.0.0" else host
        self.store.set(f"addr/{self.rank}", (advertise_host, port))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"p2p-accept-{self.rank}", daemon=True
        )
        self._accept_thread.start()

    def close(self) -> None:
        self._shutdown.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._cond:
            self._cond.notify_all()

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._recv_conn, args=(conn,), daemon=True, name="p2p-recv"
            ).start()

    def _recv_conn(self, conn: socket.socket) -> None:
        try:
            if not self._handshake_server(conn):
                return
            msg = framing.recv_obj(conn, max_frame=P2P_MAX_FRAME)
            src, tag, blob = msg["src"], msg["tag"], msg["blob"]
            with self._cond:
                self._inbox.setdefault((src, tag), []).append(blob)
                self._cond.notify_all()
        except (ConnectionError, EOFError, OSError, KeyError, TypeError, ValueError):
            log.warning("p2p: dropped malformed incoming frame", exc_info=True)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handshake_server(self, conn: socket.socket) -> bool:
        """Challenge/response before any pickled payload is parsed (same hello
        protocol as ``KVServer`` — see its ``_accept``/``_parse`` auth path). No-op
        when auth is off (loopback-only bind)."""
        nonce = secrets.token_bytes(16)
        framing.send_obj(conn, {"v": 1, "auth": self.auth_key is not None, "nonce": nonce})
        if self.auth_key is None:
            return True
        conn.settimeout(30.0)
        reply = framing.recv_obj(conn, max_frame=1024)
        ok = isinstance(reply, dict) and hmac.compare_digest(
            reply.get("mac", b""), _hmac(self.auth_key, nonce)
        )
        if not ok:
            log.warning("p2p: rejected connection with bad auth")
        conn.settimeout(None)
        return ok

    def _handshake_client(self, conn: socket.socket) -> None:
        hello = framing.recv_obj(conn, max_frame=1024)
        if isinstance(hello, dict) and hello.get("auth"):
            if self.auth_key is None:
                raise CheckpointError(
                    f"p2p peer requires authentication; set ${AUTH_KEY_ENV}"
                )
            framing.send_obj(conn, {"mac": _hmac(self.auth_key, hello["nonce"])})

    def _peer_addr(self, peer: int) -> tuple[str, int]:
        if peer not in self._addr_cache:
            try:
                self._addr_cache[peer] = tuple(
                    self.store.get(f"addr/{peer}", timeout=self.timeout)
                )
            except StoreTimeoutError as e:
                raise CheckpointError(f"p2p: no address published for rank {peer}") from e
        return self._addr_cache[peer]

    def send(self, dst: int, tag: str, blob: bytes) -> None:
        host, port = self._peer_addr(dst)
        with socket.create_connection((host, port), timeout=self.timeout) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._handshake_client(conn)
            framing.send_obj(conn, {"src": self.rank, "tag": tag, "blob": blob})

    def recv(self, src: int, tag: str, timeout: Optional[float] = None) -> bytes:
        import time as _time

        deadline = _time.monotonic() + (timeout or self.timeout)
        key = (src, tag)
        with self._cond:
            while not self._inbox.get(key):
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise CheckpointError(f"p2p: timed out waiting for {tag!r} from rank {src}")
                self._cond.wait(timeout=min(remaining, 1.0))
            return self._inbox[key].pop(0)
