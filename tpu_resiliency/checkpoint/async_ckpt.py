"""Whole-pytree async checkpointing to durable storage.

The reference wraps ``torch.save`` in pinned-memory preload + an ``AsyncRequest``
(``checkpointing/async_ckpt/torch_ckpt.py:31-76``) and splits torch-DCP's save into a
foreground plan/metadata phase and a background write phase with plan caching
(``state_dict_saver.py:53-231``). The TPU-native equivalent below:

- Foreground (fast): split the pytree (``PyTreeStateDict``), one batched D2H.
- Background: stream the container file (``checkpoint/format.py``) to the target dir.
- The reference's ``CheckpointMetadataCache`` exists to skip *collectives* (plan +
  metadata exchange). This design has no per-save collectives to skip — the hollow
  skeleton is pickled fresh each save (it is KBs and may contain changing non-array
  leaves like step counters, so caching it would write stale values).

Sharded arrays: each rank saves its own addressable shards; ``rank`` lands in the
filename, and load reassembles per-rank files. (Full global-array gather/scatter is the
job of orbax-style global checkpointing; local resiliency needs the per-rank form.)
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.async_core import AsyncCallsQueue, AsyncRequest
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


def _write_containers(writes) -> None:
    """Async-part worker (module-level: picklable). Order matters for
    separation_hint pairs: the LAST write's rename is the commit point."""
    for path, hollow_bytes, tensors, meta in writes:
        ckpt_format.write_payload(path, hollow_bytes, tensors, meta=meta)


class AsyncCheckpointer:
    """Asynchronous whole-tree save/load with structure caching.

    ``async_save`` returns immediately after D2H; call ``maybe_finalize()`` from the
    train loop (the reference's ``maybe_finalize_async_calls``, ``core.py:541``) or
    ``finalize_all()`` before exit.
    """

    def __init__(self, caller: str = "thread", sync_fn=None):
        self.queue = AsyncCallsQueue(caller=caller, sync_fn=sync_fn)

    @staticmethod
    def _hollow_bytes(sd: PyTreeStateDict) -> bytes:
        # Always pickled fresh: the skeleton carries non-array leaves (step counters,
        # schedules) whose values change between saves with an identical treedef.
        return pickle.dumps(sd.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL)

    def async_save(
        self,
        tree: Any,
        path: str,
        meta: Optional[dict] = None,
        rank: Optional[int] = None,
        separation_hint: Optional[str] = None,
    ) -> AsyncRequest:
        """``tree`` may be a raw pytree or an already-hollowed ``PyTreeStateDict``
        (lets a caller saving to several tiers pay the D2H copy once).

        ``separation_hint``: name of a top-level mapping key (e.g.
        ``"opt_state"``) routed to its OWN container file ``<base>.<hint><ext>``
        — the reference's ``separation_hint`` (``filesystem_async.py:558``),
        letting storage policy differ per content class (keep every model file,
        prune optimizer files early; put optimizer state on cheaper storage).
        Requires a raw mapping tree; pass the same hint to :meth:`load`.
        """
        if separation_hint is not None:
            if isinstance(tree, PyTreeStateDict) or not isinstance(tree, dict):
                raise CheckpointError(
                    "separation_hint requires a raw mapping tree (got "
                    f"{type(tree).__name__})"
                )
            if separation_hint not in tree:
                raise CheckpointError(
                    f"separation_hint {separation_hint!r} not a top-level key "
                    f"of {sorted(tree)}"
                )
            # Hinted file FIRST: the main file's rename is the commit point, so
            # a crash between the two leaves old-main + new-hinted (stale hinted
            # is detected at load by the meta cross-check; a NEW main merged
            # with an OLD optimizer file would be silent corruption).
            parts = [
                (
                    {separation_hint: tree[separation_hint]},
                    self._hint_path(path, separation_hint),
                ),
                ({k: v for k, v in tree.items() if k != separation_hint}, path),
            ]
        else:
            parts = [(tree, path)]
        writes = []
        for part_tree, part_path in parts:
            if isinstance(part_tree, PyTreeStateDict):
                sd = part_tree
                if not sd.is_hollow:
                    sd.pop_tensors()
                sd.copy_tensors_to_host()
            else:
                sd = PyTreeStateDict(part_tree)
                sd.pop_tensors()
                sd.copy_tensors_to_host()
            writes.append(
                (
                    self._rank_path(part_path, rank),
                    self._hollow_bytes(sd),
                    sd.tensors(),
                    meta or {},
                )
            )
        req = AsyncRequest(async_fn=_write_containers, async_fn_args=(writes,))
        self.queue.schedule_async_request(req)
        return req

    def save(self, tree: Any, path: str, meta: Optional[dict] = None, rank: Optional[int] = None) -> None:
        sd = PyTreeStateDict(tree)
        sd.pop_tensors()
        sd.copy_tensors_to_host()
        _write_containers(
            [
                (
                    self._rank_path(path, rank),
                    pickle.dumps(sd.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL),
                    sd.tensors(),
                    meta or {},
                )
            ]
        )

    @staticmethod
    def _rank_path(path: str, rank: Optional[int]) -> str:
        if rank is None:
            return path
        base, ext = os.path.splitext(path)
        return f"{base}.r{rank}{ext}"

    @staticmethod
    def _hint_path(path: str, hint: str) -> str:
        base, ext = os.path.splitext(path)
        return f"{base}.{hint}{ext}"

    @staticmethod
    def load(
        path: str,
        rank: Optional[int] = None,
        shardings=None,
        device=None,
        separation_hint: Optional[str] = None,
    ) -> tuple[Any, dict]:
        """Returns (tree, meta); arrays placed per ``shardings``/``device`` if given.

        Pass the ``separation_hint`` the save used to also read the routed file
        and merge it back under its key (with ``shardings`` as a mapping — keys
        missing from it, including the hint, get default placement; the flat
        per-tensor-sequence form cannot be split across two files)."""
        if separation_hint is not None:
            shard_rest = shard_hint = None
            if shardings is not None:
                if not isinstance(shardings, dict):
                    raise CheckpointError(
                        "separation_hint load needs shardings as a mapping "
                        "(flat per-tensor sequences cannot be split across the "
                        f"routed files); got {type(shardings).__name__}"
                    )
                shard_rest = {
                    k: v for k, v in shardings.items() if k != separation_hint
                } or None
                if separation_hint in shardings:
                    shard_hint = {separation_hint: shardings[separation_hint]}
            rest, meta = AsyncCheckpointer.load(
                path, rank=rank, shardings=shard_rest, device=device
            )
            hinted, hint_meta = AsyncCheckpointer.load(
                AsyncCheckpointer._hint_path(path, separation_hint),
                rank=rank,
                shardings=shard_hint,
                device=device,
            )
            if hint_meta != meta:
                # The pair is written hinted-first / main-last, so unequal metas
                # mean a torn save (crash between the two renames).
                raise CheckpointError(
                    f"separated checkpoint pair is torn: main meta {meta!r} != "
                    f"{separation_hint} meta {hint_meta!r}"
                )
            return {**rest, **hinted}, meta
        target = AsyncCheckpointer._rank_path(path, rank)
        if not os.path.exists(target):
            raise CheckpointError(f"no checkpoint at {target}")
        hollow_b, tensors, meta = ckpt_format.read_payload(target)
        sd = PyTreeStateDict.from_hollow(
            pickle.loads(hollow_b), tensors, shardings=shardings, device=device
        )
        return sd.tree, meta

    def maybe_finalize(self, blocking: bool = False) -> list[int]:
        return self.queue.maybe_finalize_async_calls(blocking=blocking)

    def finalize_all(self) -> list[int]:
        return self.queue.finalize_all()

    def close(self) -> None:
        self.queue.close()
