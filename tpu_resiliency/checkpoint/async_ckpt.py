"""Whole-pytree async checkpointing to durable storage.

The reference wraps ``torch.save`` in pinned-memory preload + an ``AsyncRequest``
(``checkpointing/async_ckpt/torch_ckpt.py:31-76``) and splits torch-DCP's save into a
foreground plan/metadata phase and a background write phase with plan caching
(``state_dict_saver.py:53-231``). The TPU-native equivalent below:

- Foreground (fast): split the pytree (``PyTreeStateDict``), one batched D2H.
- Background: stream the container file (``checkpoint/format.py``) to the target dir.
- The reference's ``CheckpointMetadataCache`` exists to skip *collectives* (plan +
  metadata exchange). This design has no per-save collectives to skip — the hollow
  skeleton is pickled fresh each save (it is KBs and may contain changing non-array
  leaves like step counters, so caching it would write stale values).

Sharded arrays: each rank saves its own addressable shards; ``rank`` lands in the
filename, and load reassembles per-rank files. (Full global-array gather/scatter is the
job of orbax-style global checkpointing; local resiliency needs the per-rank form.)
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Optional

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.async_core import AsyncCallsQueue, AsyncRequest
from tpu_resiliency.checkpoint.staging import HostStagingPool
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.timers import debug_time
from tpu_resiliency.utils.tracing import span

log = get_logger(__name__)


def _payload_bytes(writes) -> int:
    """Total bytes a write set will put on disk (hollow pickles + tensor data)."""
    total = 0
    for _, hollow_bytes, tensors, _, _ in writes:
        total += len(hollow_bytes)
        for t in tensors:
            total += int(getattr(t, "nbytes", 0) or 0)
    return total


def _prune_stale(cleanup) -> None:
    """``(glob_pattern, keep_path)`` pairs, processed only AFTER every write
    committed — prunes superseded token-named hint files. Best-effort: a crash
    mid-cleanup strands stale files (harmless; next save prunes them), never a
    loadable generation."""
    import glob as _glob

    for pattern, keep in cleanup:
        for stale in _glob.glob(pattern):
            if stale != keep:
                try:
                    os.unlink(stale)
                except OSError:
                    pass


def _write_containers(writes, cleanup=()) -> None:
    """Async-part worker (module-level: picklable). Order matters for
    separation_hint pairs: the LAST write's rename is the commit point.

    Emits one ``ckpt_write_file`` record per container (leaf count + bytes,
    labeled main/hint) so ``metrics_dump`` can attribute save volume to the
    separation-hint container vs the main one, plus the aggregate
    ``ckpt.async_write`` timing."""
    # One pass up front — the success and failure events report the same
    # volume, so computing it twice (once per event path) was pure waste.
    total_bytes = _payload_bytes(writes)
    t0 = time.perf_counter()
    try:
        for path, hollow_bytes, tensors, meta, container in writes:
            written = ckpt_format.write_payload(path, hollow_bytes, tensors, meta=meta)
            record_event(
                "checkpoint", "ckpt_write_file",
                file=os.path.basename(path), container=container,
                bytes=written, leaves=len(tensors),
            )
    except BaseException as e:
        record_event(
            "checkpoint", "timing", name="ckpt.async_write",
            duration_s=time.perf_counter() - t0, ok=False, error=repr(e),
            bytes=total_bytes, files=len(writes),
        )
        raise
    # The background-half latency + volume: with the foreground
    # ``ckpt.async_save`` timing this decomposes a save end to end.
    record_event(
        "checkpoint", "timing", name="ckpt.async_write",
        duration_s=time.perf_counter() - t0, ok=True,
        bytes=total_bytes, files=len(writes),
    )
    _prune_stale(cleanup)


def _write_containers_stream(writes, snapshot, cleanup=()) -> None:
    """Pipelined async-part worker: leaf-STREAMING container writes.

    ``writes`` entries carry leaf INDICES into ``snapshot`` instead of
    materialized tensors; each leaf hits the file the moment its D2H transfer
    resolves (``HostSnapshot.resolve_view``), so device copies and disk IO
    overlap instead of serializing behind a full-tree ``device_get`` barrier.
    Write order still commits separation-hint pairs correctly (last rename is
    the commit point). Thread-caller only — the snapshot holds live device
    references and pool-leased buffers, neither of which crosses a process
    boundary."""
    total_bytes = sum(
        len(hollow_bytes) + sum(snapshot.specs[i]["nbytes"] for i in indices)
        for _, hollow_bytes, indices, _, _ in writes
    )

    def chunks(prefix, indices):
        # One pass feeds both the file and the integrity trailer: each leaf's
        # CRC is taken from the same resolved view the writer streams, so the
        # v2 checksums cost no extra payload read.
        ck = ckpt_format.Checksummer(prefix)
        yield prefix
        for i in indices:
            view = snapshot.resolve_view(i)
            ck.add_leaf(view)
            yield view
        yield ck.trailer()

    t0 = time.perf_counter()
    try:
        for path, hollow_bytes, indices, meta, container in writes:
            prefix = ckpt_format.header_prefix(
                hollow_bytes, [snapshot.specs[i] for i in indices], meta
            )
            written = ckpt_format.write_stream(path, chunks(prefix, indices))
            record_event(
                "checkpoint", "ckpt_write_file",
                file=os.path.basename(path), container=container,
                bytes=written, leaves=len(indices),
            )
    except BaseException as e:
        record_event(
            "checkpoint", "timing", name="ckpt.async_write",
            duration_s=time.perf_counter() - t0, ok=False, error=repr(e),
            bytes=total_bytes, files=len(writes),
        )
        raise
    record_event(
        "checkpoint", "timing", name="ckpt.async_write",
        duration_s=time.perf_counter() - t0, ok=True,
        bytes=total_bytes, files=len(writes),
    )
    _prune_stale(cleanup)


def _split_hollow(full: dict, tensors: list, hint: str):
    """Split a hollowed mapping tree into ``(hinted, rest)`` parts with
    re-indexed placeholders — ONE batched D2H serves both container files."""
    import dataclasses as _dc

    import jax

    from tpu_resiliency.checkpoint.state_dict import TensorPlaceholder

    parts = []
    for subtree in ({hint: full[hint]}, {k: v for k, v in full.items() if k != hint}):
        leaves, treedef = jax.tree_util.tree_flatten(subtree)
        part_tensors: list = []
        new_leaves = []
        for leaf in leaves:
            if isinstance(leaf, TensorPlaceholder):
                new_leaves.append(_dc.replace(leaf, index=len(part_tensors)))
                part_tensors.append(tensors[leaf.index])
            else:
                new_leaves.append(leaf)
        parts.append(
            (jax.tree_util.tree_unflatten(treedef, new_leaves), part_tensors)
        )
    return parts


class AsyncCheckpointer:
    """Asynchronous whole-tree save/load with structure caching.

    ``async_save`` returns immediately after D2H; call ``maybe_finalize()`` from the
    train loop (the reference's ``maybe_finalize_async_calls``, ``core.py:541``) or
    ``finalize_all()`` before exit.
    """

    #: Bounded-backoff schedule for :meth:`_serialize_conflicting`: start at
    #: 1 ms (a local write usually clears within a few), cap at 250 ms so a
    #: long cross-rank finalize isn't hammered with all-reduces.
    CONFLICT_BACKOFF_INITIAL = 0.001
    CONFLICT_BACKOFF_MAX = 0.25

    def __init__(
        self,
        caller: str = "thread",
        sync_fn=None,
        pipelined: Optional[bool] = None,
        staging: Optional[HostStagingPool] = None,
        conflict_timeout: float = 600.0,
    ):
        """``pipelined`` (default: auto — on for the thread caller) runs the
        snapshot engine: ``async_save``'s caller-visible window is enqueue +
        skeleton pickle; D2H resolution and container writes stream leaf by
        leaf in the background, staged through ``staging`` (a
        :class:`HostStagingPool`, created double-buffered when omitted) so
        steady-state saves allocate no large host buffers. Process/fork
        callers can't share the snapshot (live device refs + pooled buffers)
        and keep the materialize-then-schedule path.

        ``conflict_timeout``: seconds :meth:`async_save` will wait for an
        in-flight save to the same path before raising ``CheckpointError``.
        """
        self.queue = AsyncCallsQueue(caller=caller, sync_fn=sync_fn)
        self.pipelined = caller == "thread" if pipelined is None else pipelined
        if self.pipelined and caller != "thread":
            raise CheckpointError(
                "pipelined snapshots require caller='thread' (the snapshot "
                "holds live device references and pool-leased buffers that "
                "cannot cross a process boundary)"
            )
        self.staging = staging if staging is not None else HostStagingPool()
        self.conflict_timeout = conflict_timeout
        #: schedule idx → the file paths that save touches. Two in-flight saves
        #: to one path would race on the shared ``.dirty`` tmp file AND the
        #: hint-file cleanup (one save pruning the other's just-written hint),
        #: so overlapping targets serialize on the earlier save.
        self._inflight_paths: dict[int, frozenset] = {}

    def _serialize_conflicting(
        self, targets: frozenset, timeout: Optional[float] = None
    ) -> None:
        timeout = self.conflict_timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        delay = self.CONFLICT_BACKOFF_INITIAL
        while True:
            live = set(self.queue.unfinalized_indices)
            self._inflight_paths = {
                i: p for i, p in self._inflight_paths.items() if i in live
            }
            conflicting = sorted(
                set().union(
                    *(targets & paths for paths in self._inflight_paths.values()),
                    frozenset(),
                )
            )
            if not conflicting:
                return
            if time.monotonic() >= deadline:
                # A save that can never clear (peer rank dead mid-finalize, a
                # wedged writer) must surface, not spin the train loop forever.
                raise CheckpointError(
                    f"timed out after {timeout:g}s waiting for in-flight save(s) "
                    f"to finalize before reusing path(s): {conflicting}"
                )
            self.queue.maybe_finalize_async_calls(blocking=True)
            # One blocking call need not drain: a cross-rank sync_fn vetoes
            # finalization until EVERY rank's write finished, so keep retrying
            # until the conflicting save is truly gone — scheduling anyway
            # would race on the shared .dirty tmp file. Exponential backoff
            # (1 ms → 250 ms cap) instead of a hot 10 ms spin: the all-reduce
            # behind a cross-rank sync_fn is not free to hammer.
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2, self.CONFLICT_BACKOFF_MAX)

    @staticmethod
    def _hollow_bytes(sd: PyTreeStateDict) -> bytes:
        # Always pickled fresh: the skeleton carries non-array leaves (step counters,
        # schedules) whose values change between saves with an identical treedef.
        return pickle.dumps(sd.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL)

    def async_save(
        self,
        tree: Any,
        path: str,
        meta: Optional[dict] = None,
        rank: Optional[int] = None,
        separation_hint: Optional[str] = None,
    ) -> AsyncRequest:
        """``tree`` may be a raw pytree or an already-hollowed ``PyTreeStateDict``
        (lets a caller saving to several tiers pay the D2H copy once).

        ``separation_hint``: name of a top-level mapping key (e.g.
        ``"opt_state"``) routed to its OWN container file
        ``<base>.<hint>.<token><ext>`` — the reference's ``separation_hint``
        (``filesystem_async.py:558``), letting storage policy differ per content
        class (keep every model file, prune optimizer files early; put optimizer
        state on cheaper storage). The tree's top level must be a mapping
        containing the key; pass the same hint to :meth:`load`. The
        hollow/payload split happens once (one batched D2H).

        Durability contract: the hint file is named by the save's unique pair
        token and written FIRST; the main file (whose meta records the token)
        renames LAST and is the sole commit point. A crash anywhere in between
        leaves the previous generation's main+hint pair fully loadable — the old
        token-named hint file is pruned only after the new main file committed.
        """
        # Foreground half: the caller-visible stall a train loop pays per
        # save. Pipelined, that is enqueue + skeleton pickle + schedule (D2H
        # resolution happens leaf-streaming in the background); legacy, it
        # includes the blocking whole-tree D2H. Both are measured here — the
        # ``ckpt.save.enqueue`` span and ``ckpt_foreground_blocked`` record are
        # what the foreground-window regression gate and
        # ``tpu_ckpt_foreground_blocked_seconds`` aggregate.
        t0 = time.perf_counter()
        with span("checkpoint", "ckpt.save.enqueue", path=os.path.basename(path)):
            with debug_time("ckpt.async_save", source="checkpoint"):
                req = self._async_save(tree, path, meta, rank, separation_hint)
        record_event(
            "checkpoint", "ckpt_foreground_blocked",
            duration_s=time.perf_counter() - t0,
            engine="pipelined" if self.pipelined else "sync",
            path=os.path.basename(path),
        )
        return req

    def _async_save(
        self,
        tree: Any,
        path: str,
        meta: Optional[dict],
        rank: Optional[int],
        separation_hint: Optional[str],
    ) -> AsyncRequest:
        if isinstance(tree, PyTreeStateDict):
            sd = tree
            if not sd.is_hollow:
                sd.pop_tensors()
        else:
            sd = PyTreeStateDict(tree)
            sd.pop_tensors()
        if self.pipelined:
            # Enqueue every leaf's D2H without blocking; the background worker
            # resolves + writes leaf by leaf out of the pooled staging buffers.
            snapshot = sd.copy_tensors_to_host_async(pool=self.staging)
            payload = list(range(len(snapshot)))
        else:
            sd.copy_tensors_to_host()
            snapshot = None
            payload = sd.tensors()
        if separation_hint is None:
            writes = [
                (
                    self._rank_path(path, rank),
                    self._hollow_bytes(sd),
                    payload,
                    meta or {},
                    "main",
                )
            ]
            cleanup = ()
        else:
            full = sd.hollow_tree
            if not isinstance(full, dict) or separation_hint not in full:
                if snapshot is not None:
                    snapshot.release()
                raise CheckpointError(
                    f"separation_hint {separation_hint!r} is not a top-level "
                    f"mapping key of the tree "
                    f"({sorted(full) if isinstance(full, dict) else type(full).__name__})"
                )
            import secrets

            # The token both NAMES the hint file and rides in each meta: the
            # main file commits last and points at exactly one hint file, so a
            # crash between the two renames can never shadow or tear the
            # previous generation — user-supplied meta alone can't carry this
            # (meta=None is the common case).
            token = secrets.token_hex(8)
            meta_w = {**(meta or {}), "_pair_token": token}
            # Hinted file FIRST: the main file's rename is the commit point.
            # Splitting over the identity payload (pipelined: leaf indices)
            # routes each file's leaves without materializing anything.
            (hint_tree, hint_payload), (rest_tree, rest_payload) = _split_hollow(
                full, payload, separation_hint
            )
            hint_target = self._rank_path(
                self._hint_path(path, separation_hint, token), rank
            )
            writes = [
                (
                    hint_target,
                    pickle.dumps(hint_tree, protocol=pickle.HIGHEST_PROTOCOL),
                    hint_payload,
                    meta_w,
                    "hint",
                ),
                (
                    self._rank_path(path, rank),
                    pickle.dumps(rest_tree, protocol=pickle.HIGHEST_PROTOCOL),
                    rest_payload,
                    meta_w,
                    "main",
                ),
            ]
            cleanup = ((self._hint_glob(path, separation_hint, rank), hint_target),)
        if snapshot is not None:
            req = AsyncRequest(
                async_fn=_write_containers_stream,
                async_fn_args=(writes, snapshot, cleanup),
                cleanup_fns=(snapshot.release,),
            )
        else:
            req = AsyncRequest(
                async_fn=_write_containers, async_fn_args=(writes, cleanup)
            )
        targets = frozenset(w[0] for w in writes)
        try:
            self._serialize_conflicting(targets)
            idx = self.queue.schedule_async_request(req)
        except BaseException:
            if snapshot is not None:
                snapshot.release()
            raise
        self._inflight_paths[idx] = targets
        return req

    @debug_time("ckpt.save_sync", source="checkpoint")
    def save(self, tree: Any, path: str, meta: Optional[dict] = None, rank: Optional[int] = None) -> None:
        sd = PyTreeStateDict(tree)
        sd.pop_tensors()
        sd.copy_tensors_to_host()
        _write_containers(
            [
                (
                    self._rank_path(path, rank),
                    pickle.dumps(sd.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL),
                    sd.tensors(),
                    meta or {},
                    "main",
                )
            ]
        )

    @staticmethod
    def _rank_path(path: str, rank: Optional[int]) -> str:
        if rank is None:
            return path
        base, ext = os.path.splitext(path)
        return f"{base}.r{rank}{ext}"

    @staticmethod
    def _hint_path(path: str, hint: str, token: str) -> str:
        base, ext = os.path.splitext(path)
        return f"{base}.{hint}.{token}{ext}"

    @staticmethod
    def _hint_glob(path: str, hint: str, rank: Optional[int]) -> str:
        """Glob matching every generation's hint file for this (path, hint,
        rank) — 16 lowercase-hex chars, the exact shape of ``token_hex(8)``,
        so sibling ranks and other hints never match. The user-controlled parts
        are glob-escaped: metacharacters in a sweep dir like ``run[1]/`` must
        match literally, not as character classes."""
        import glob as _glob

        base, ext = os.path.splitext(path)
        rank_sfx = "" if rank is None else f".r{rank}"
        return (
            _glob.escape(f"{base}.{hint}.")
            + "[0-9a-f]" * 16
            + _glob.escape(f"{rank_sfx}{ext}")
        )

    @staticmethod
    def load(
        path: str,
        rank: Optional[int] = None,
        shardings=None,
        device=None,
        separation_hint: Optional[str] = None,
    ) -> tuple[Any, dict]:
        """Returns (tree, meta); arrays placed per ``shardings``/``device`` if given.

        Pass the ``separation_hint`` the save used to also read the routed file
        and merge it back under its key. ``shardings`` must then be a mapping
        that mirrors the saved tree minus-or-plus the hint key: the hint entry
        may be omitted (its file gets default placement), every other key must
        match the main file's tree exactly (the flat per-tensor-sequence form
        cannot be split across two files)."""
        # Restore latency is half the recovery-time story — record it like save.
        with debug_time("ckpt.load", source="checkpoint"):
            return AsyncCheckpointer._load(
                path, rank, shardings, device, separation_hint
            )

    @staticmethod
    def _load(
        path: str,
        rank: Optional[int],
        shardings,
        device,
        separation_hint: Optional[str],
    ) -> tuple[Any, dict]:
        if separation_hint is not None:
            shard_rest = shard_hint = None
            if shardings is not None:
                if not isinstance(shardings, dict):
                    raise CheckpointError(
                        "separation_hint load needs shardings as a mapping "
                        "(flat per-tensor sequences cannot be split across the "
                        f"routed files); got {type(shardings).__name__}"
                    )
                shard_rest = {
                    k: v for k, v in shardings.items() if k != separation_hint
                } or None
                if separation_hint in shardings:
                    shard_hint = {separation_hint: shardings[separation_hint]}
            # The committed main file names its pair: its meta token selects
            # the one hint file written in the same save, so a crash between
            # the two renames (new hint landed, old main still committed)
            # resolves to the OLD, complete pair instead of a torn merge.
            rest, meta_raw = AsyncCheckpointer._load_file(
                AsyncCheckpointer._rank_path(path, rank), shard_rest, device
            )
            token = meta_raw.get("_pair_token")
            if not isinstance(token, str):
                raise CheckpointError(
                    f"{path} was not written with separation_hint="
                    f"{separation_hint!r} (no pair token in its meta)"
                )
            hint_file = AsyncCheckpointer._rank_path(
                AsyncCheckpointer._hint_path(path, separation_hint, token), rank
            )
            hinted, hint_raw = AsyncCheckpointer._load_file(
                hint_file, shard_hint, device
            )
            # Compare ONLY the tokens: they are unique per save, so equality is
            # sufficient — and user meta may hold numpy arrays, whose dict
            # inequality raises instead of answering.
            if hint_raw.get("_pair_token") != token:
                raise CheckpointError(
                    f"separated checkpoint pair is torn: {hint_file} carries "
                    f"token {hint_raw.get('_pair_token')!r}, main expects {token!r}"
                )
            meta = {k: v for k, v in meta_raw.items() if k != "_pair_token"}
            return {**rest, **hinted}, meta
        tree, meta_raw = AsyncCheckpointer._load_file(
            AsyncCheckpointer._rank_path(path, rank), shardings, device
        )
        # The pair token is save-internal plumbing; user meta stays clean even
        # when one file of a separated pair is loaded directly.
        return tree, {k: v for k, v in meta_raw.items() if k != "_pair_token"}

    @staticmethod
    def _load_file(target: str, shardings, device) -> tuple[Any, dict]:
        """One container read; returns the RAW meta (token intact — the hint
        path's torn-pair comparison needs it)."""
        if not os.path.exists(target):
            raise CheckpointError(f"no checkpoint at {target}")
        hollow_b, tensors, meta = ckpt_format.read_payload(target)
        sd = PyTreeStateDict.from_hollow(
            pickle.loads(hollow_b), tensors, shardings=shardings, device=device
        )
        return sd.tree, meta

    def maybe_finalize(self, blocking: bool = False) -> list[int]:
        return self.queue.maybe_finalize_async_calls(blocking=blocking)

    def finalize_all(self) -> list[int]:
        return self.queue.finalize_all()

    def close(self) -> None:
        self.queue.close()
