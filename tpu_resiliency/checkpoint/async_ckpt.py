"""Whole-pytree async checkpointing to durable storage.

The reference wraps ``torch.save`` in pinned-memory preload + an ``AsyncRequest``
(``checkpointing/async_ckpt/torch_ckpt.py:31-76``) and splits torch-DCP's save into a
foreground plan/metadata phase and a background write phase with plan caching
(``state_dict_saver.py:53-231``). The TPU-native equivalent below:

- Foreground (fast): split the pytree (``PyTreeStateDict``), one batched D2H.
- Background: stream the container file (``checkpoint/format.py``) to the target dir.
- The reference's ``CheckpointMetadataCache`` exists to skip *collectives* (plan +
  metadata exchange). This design has no per-save collectives to skip — the hollow
  skeleton is pickled fresh each save (it is KBs and may contain changing non-array
  leaves like step counters, so caching it would write stale values).

Sharded arrays: each rank saves its own addressable shards; ``rank`` lands in the
filename, and load reassembles per-rank files. (Full global-array gather/scatter is the
job of orbax-style global checkpointing; local resiliency needs the per-rank form.)
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.async_core import AsyncCallsQueue, AsyncRequest
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


def _write_container(path: str, hollow_bytes: bytes, tensors, meta: dict) -> None:
    ckpt_format.write_payload(path, hollow_bytes, tensors, meta=meta)


class AsyncCheckpointer:
    """Asynchronous whole-tree save/load with structure caching.

    ``async_save`` returns immediately after D2H; call ``maybe_finalize()`` from the
    train loop (the reference's ``maybe_finalize_async_calls``, ``core.py:541``) or
    ``finalize_all()`` before exit.
    """

    def __init__(self, caller: str = "thread", sync_fn=None):
        self.queue = AsyncCallsQueue(caller=caller, sync_fn=sync_fn)

    @staticmethod
    def _hollow_bytes(sd: PyTreeStateDict) -> bytes:
        # Always pickled fresh: the skeleton carries non-array leaves (step counters,
        # schedules) whose values change between saves with an identical treedef.
        return pickle.dumps(sd.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL)

    def async_save(
        self, tree: Any, path: str, meta: Optional[dict] = None, rank: Optional[int] = None
    ) -> AsyncRequest:
        """``tree`` may be a raw pytree or an already-hollowed ``PyTreeStateDict``
        (lets a caller saving to several tiers pay the D2H copy once)."""
        if isinstance(tree, PyTreeStateDict):
            sd = tree
            if not sd.is_hollow:
                sd.pop_tensors()
            sd.copy_tensors_to_host()
        else:
            sd = PyTreeStateDict(tree)
            sd.pop_tensors()
            sd.copy_tensors_to_host()
        hollow_bytes = self._hollow_bytes(sd)
        target = self._rank_path(path, rank)
        req = AsyncRequest(
            async_fn=_write_container,
            async_fn_args=(target, hollow_bytes, sd.tensors(), meta or {}),
        )
        self.queue.schedule_async_request(req)
        return req

    def save(self, tree: Any, path: str, meta: Optional[dict] = None, rank: Optional[int] = None) -> None:
        sd = PyTreeStateDict(tree)
        sd.pop_tensors()
        sd.copy_tensors_to_host()
        _write_container(
            self._rank_path(path, rank),
            pickle.dumps(sd.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL),
            sd.tensors(),
            meta or {},
        )

    @staticmethod
    def _rank_path(path: str, rank: Optional[int]) -> str:
        if rank is None:
            return path
        base, ext = os.path.splitext(path)
        return f"{base}.r{rank}{ext}"

    @staticmethod
    def load(path: str, rank: Optional[int] = None, shardings=None, device=None) -> tuple[Any, dict]:
        """Returns (tree, meta); arrays placed per ``shardings``/``device`` if given."""
        target = AsyncCheckpointer._rank_path(path, rank)
        if not os.path.exists(target):
            raise CheckpointError(f"no checkpoint at {target}")
        hollow_b, tensors, meta = ckpt_format.read_payload(target)
        sd = PyTreeStateDict.from_hollow(
            pickle.loads(hollow_b), tensors, shardings=shardings, device=device
        )
        return sd.tree, meta

    def maybe_finalize(self, blocking: bool = False) -> list[int]:
        return self.queue.maybe_finalize_async_calls(blocking=blocking)

    def finalize_all(self) -> list[int]:
        return self.queue.finalize_all()

    def close(self) -> None:
        self.queue.close()
