"""Checkpointing: async saves, local (node-storage) checkpoints, clique replication.

TPU-native re-design of the reference's ``checkpointing/`` package (SURVEY §2.6):

- :mod:`~tpu_resiliency.checkpoint.state_dict` — pytree hollow/payload split
  (``TensorAwareStateDict`` contract).
- :mod:`~tpu_resiliency.checkpoint.format` — atomic single-file container.
- :mod:`~tpu_resiliency.checkpoint.async_core` — ``AsyncRequest`` / callers /
  ``AsyncCallsQueue`` with distributed finalization.
- :mod:`~tpu_resiliency.checkpoint.async_ckpt` — whole-pytree async checkpointer.
- :mod:`~tpu_resiliency.checkpoint.comm` — store-backed object collectives + p2p
  bulk links.
- :mod:`~tpu_resiliency.checkpoint.replication` — clique replication + exchange plans.
- :mod:`~tpu_resiliency.checkpoint.local_manager` — per-rank local checkpoint manager
  with coverage-based ``find_latest``.
- :mod:`~tpu_resiliency.checkpoint.reshard` — elastic resharding: repartition
  plans mapping any saved world's shards onto any target world/topology.
- :mod:`~tpu_resiliency.checkpoint.coding` — byte economy: Reed-Solomon
  erasure replication (k-of-n blocks instead of full mirrors) and delta
  checkpoints (chunk-diff frames between keyframes).
- :mod:`~tpu_resiliency.checkpoint.coldtier` — durable cold tier: async
  spill of finalized keyframe containers to a pluggable object store,
  manifest-verified restore-anywhere bootstrap.
"""

from tpu_resiliency.checkpoint.async_ckpt import AsyncCheckpointer
from tpu_resiliency.checkpoint.coldtier import (
    ColdTier,
    FilesystemStore,
    ObjectStore,
    cold_from_env,
)
from tpu_resiliency.checkpoint.async_core import (
    AsyncCallsQueue,
    AsyncRequest,
    ForkAsyncCaller,
    ProcessAsyncCaller,
    ThreadAsyncCaller,
)
from tpu_resiliency.checkpoint.coding import (
    DeltaTracker,
    ErasureReplicationStrategy,
    replication_from_env,
)
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import CkptID, LocalCheckpointManager
from tpu_resiliency.checkpoint.replication import (
    CliqueReplicationStrategy,
    ExchangePlan,
    LazyCliqueReplicationStrategy,
    group_sequence_for,
    parse_group_sequence,
)
from tpu_resiliency.checkpoint.reshard import (
    LeafSpec,
    ReshardPlan,
    TreeLayout,
    build_plan,
)
from tpu_resiliency.checkpoint.staging import HostStagingPool, StagingLease
from tpu_resiliency.checkpoint.state_dict import (
    HostSnapshot,
    PyTreeStateDict,
    TensorPlaceholder,
)

__all__ = [
    "AsyncCheckpointer",
    "AsyncCallsQueue",
    "AsyncRequest",
    "ThreadAsyncCaller",
    "ProcessAsyncCaller",
    "ForkAsyncCaller",
    "StoreComm",
    "PeerExchange",
    "CkptID",
    "LocalCheckpointManager",
    "ColdTier",
    "FilesystemStore",
    "ObjectStore",
    "cold_from_env",
    "CliqueReplicationStrategy",
    "LazyCliqueReplicationStrategy",
    "ErasureReplicationStrategy",
    "DeltaTracker",
    "replication_from_env",
    "ExchangePlan",
    "group_sequence_for",
    "parse_group_sequence",
    "HostSnapshot",
    "HostStagingPool",
    "StagingLease",
    "PyTreeStateDict",
    "TensorPlaceholder",
    "TreeLayout",
    "LeafSpec",
    "ReshardPlan",
    "build_plan",
]
