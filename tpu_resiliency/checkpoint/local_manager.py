"""Local (node-storage) checkpoint manager with replication and coverage tracking.

Re-design of the reference's local checkpointing
(``checkpointing/local/ckpt_managers/base_manager.py:35-318`` and
``local_manager.py:38-178``): each rank persists its shard to node-local storage (NVMe /
ramdisk) every few minutes; cliques mirror shards across hosts; after a restart —
possibly with ranks moved between hosts — ``find_latest`` agrees on the newest iteration
whose shards **cover every rank**, and ``load`` routes missing shards from their mirrors.

Checkpoint identity is ``CkptID = (iteration, owner_rank, session)``
(``base_manager.py:86-101``). Files are ``iter_{it:07d}_{owner}_local.ckpt`` under
``root/s{session}/r{rank}/`` — the directory names the *holder*, the filename the
*owner*, so a rank's dir holds its own shard plus its clique mirrors. Writes are
``.dirty``-then-rename atomic (``local_manager.py:110-131``); saves run through
:class:`~tpu_resiliency.checkpoint.async_core.AsyncCallsQueue` with a finalize step
that re-checks cross-rank coverage and prunes superseded iterations
(``base_manager.py:277-304``).

**Recovery ladder.** ``load`` no longer trusts disk: every shard read is
checksum-verified (container format v2, ``checkpoint/format.py``), and a rank
whose copy fails climbs a ladder instead of raising —

1. **quarantine** the damaged file (rename to ``*.corrupt-<ts>``, one
   ``ckpt_quarantined`` event → ``tpu_ckpt_integrity_failures_total{stage}``),
   so retries and coverage math never re-trust it and forensics keep the bytes;
2. **peer retrieve**: the existing collective exchange routes the shard from a
   clique mirror, verify-on-receive (a corrupt mirror is treated like PR 4's
   degraded peer — dropped, not loaded);
3. **cold-tier fetch** (``checkpoint/coldtier.py``): when no live peer can
   serve the shard — including a FRESH job with an empty workdir after a
   correlated failure — the durable object-store archive supplies it, every
   fetched byte verified fail-closed against the ``tpu-coldtier-1`` manifest
   digests before the container's own verify;
4. **fall back** to the next older iteration whose shards pass, agreed across
   the group with a :class:`StoreComm` round (``all_reduce_min``) so every rank
   loads the SAME iteration instead of diverging.

Ladder depth is bounded by the ``keep`` retention knob (how many covered
iterations survive pruning; default 1 preserves the reference's
newest-only policy — set ``keep>=2`` to give the ladder a rung to fall to).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import os
import re
import shutil
import threading
import time
from typing import Any, Optional

from tpu_resiliency.checkpoint import coldtier as coldtier_mod
from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint import reshard as reshard_mod
from tpu_resiliency.checkpoint.async_core import AsyncCallsQueue, AsyncRequest
from tpu_resiliency.checkpoint.coding import delta as ckpt_delta
from tpu_resiliency.checkpoint.coding import strategy as ckpt_coding
from tpu_resiliency.checkpoint.comm import StoreComm
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.checkpoint.staging import HostStagingPool
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.timers import debug_time
from tpu_resiliency.utils.tracing import span
from tpu_resiliency.utils.logging import get_logger

import pickle

log = get_logger(__name__)

_FILE_RE = re.compile(r"^iter_(\d{7})_(\d+)_local\.ckpt$")
#: Erasure block artifact (``checkpoint/coding/strategy.py``): the filename
#: self-describes ``(iteration, owner, index, k, m)`` so coverage math and
#: retention never parse artifact headers.
_BLOCK_RE = re.compile(
    r"^iter_(\d{7})_(\d+)_b(\d+)k(\d+)m(\d+)_local\.ecblk$"
)
#: Quarantined container: ``<container-name>.corrupt-<hex-ts>`` (the suffix
#: orders same-id quarantines; cleanup keeps the newest per container name).
_CORRUPT_RE = re.compile(
    r"^(iter_\d{7}_\d+_local\.ckpt)\.corrupt(?:-[0-9a-f]+)?$"
)


def block_filename(iteration: int, owner: int, index: int, k: int, m: int) -> str:
    return f"iter_{iteration:07d}_{owner}_b{index}k{k}m{m}_local.ecblk"


@dataclasses.dataclass(frozen=True, order=True)
class CkptID:
    iteration: int
    owner: int
    session: int = 0

    def filename(self) -> str:
        return f"iter_{self.iteration:07d}_{self.owner}_local.ckpt"


def _write_blobs(paths_and_blobs: list[tuple[str, Any]]) -> None:
    """Async-part worker: write each payload atomically (module-level: picklable).

    Each value is a single bytes-like (a receive buffer) or a list of parts (a
    ``serialize_parts`` result) — either way the payload streams to disk with no
    joined copy (``format.write_parts``). Writer parallelism for single blobs
    follows the ``$TPU_RESILIENCY_CKPT_STRIPES`` storage-class knob
    (``format.write_blob``); default is single-stream, the measured winner on
    plain host storage."""
    import time as _time

    t0 = _time.perf_counter()
    total = sum(
        sum(len(p) for p in b) if isinstance(b, list) else len(b)
        for _, b in paths_and_blobs
    )
    try:
        for path, blob in paths_and_blobs:
            if isinstance(blob, list):
                ckpt_format.write_parts(path, blob)
            else:
                ckpt_format.write_blob(path, blob)
    except BaseException as e:
        record_event(
            "checkpoint", "timing", name="ckpt.save.write",
            duration_s=_time.perf_counter() - t0, ok=False, error=repr(e),
            bytes=total, files=len(paths_and_blobs),
        )
        raise
    # Completes the save decomposition (d2h → serialize → replicate → write):
    # this is the disk-bound half, with the volume that explains its latency.
    record_event(
        "checkpoint", "timing", name="ckpt.save.write",
        duration_s=_time.perf_counter() - t0, ok=True,
        bytes=total, files=len(paths_and_blobs),
    )


def _persist_artifacts(items: list[tuple]) -> None:
    """Async-part worker for byte-economy payloads (module-level: picklable).

    ``items`` mix three shapes: ``("blob", path, payload)`` — a container or
    erasure-block artifact written verbatim; ``("parts", path, parts)`` — a
    ``serialize_parts`` result streamed with no join; ``("delta", out_path,
    frame, base_path, owner, iteration)`` — a delta frame applied against the
    held base container. A broken delta chain (missing/stale base) drops
    THAT mirror with a ``ckpt_delta_applied{outcome=broken}`` event instead
    of failing the save — the shard simply has fewer mirrors until the next
    keyframe re-bases the clique."""
    plain: list[tuple[str, Any]] = []
    for item in items:
        if item[0] == "delta":
            _, out_path, frame, base_path, owner, iteration = item
            try:
                written = ckpt_delta.apply_delta(frame, base_path, out_path)
                ckpt_delta.record_applied(
                    owner, iteration, "ok", bytes=written,
                    frame_bytes=memoryview(frame).nbytes,
                )
            except CheckpointError as e:
                log.warning(
                    f"delta mirror for owner {owner} @ iteration {iteration} "
                    f"dropped: {e}"
                )
                ckpt_delta.record_applied(
                    owner, iteration, "broken", error=repr(e)
                )
        else:
            plain.append((item[1], item[2]))
    if plain:
        _write_blobs(plain)


def _items_nbytes(items: list[tuple]) -> int:
    total = 0
    for item in items:
        payload = item[2]
        if isinstance(payload, list):
            total += sum(memoryview(p).cast("B").nbytes for p in payload)
        else:
            total += memoryview(payload).cast("B").nbytes
    return total


class LocalCheckpointManager:
    """Per-rank local checkpoint manager.

    Single-rank operation: pass ``comm=None`` (no coverage agreement, no replication).
    Distributed: pass a :class:`StoreComm` over all ranks, and optionally a
    :class:`CliqueReplicationStrategy` built on the same store.
    """

    def __init__(
        self,
        root: str,
        rank: int = 0,
        session: int = 0,
        comm: Optional[StoreComm] = None,
        replication: Optional[CliqueReplicationStrategy] = None,
        caller: str = "thread",
        pipelined: Optional[bool] = None,
        staging: Optional[HostStagingPool] = None,
        keep: int = 1,
        delta_interval: Optional[int] = None,
        cold: Optional[Any] = None,
    ):
        self.root = root
        self.rank = rank
        self.session = session
        self.comm = comm
        self.replication = replication
        self._caller_kind = caller
        #: Durable cold tier (``checkpoint/coldtier.py``): ``None`` wires from
        #: ``$TPU_RESILIENCY_COLD_DIR`` (off when unset), ``False`` forces off,
        #: or pass a :class:`~tpu_resiliency.checkpoint.coldtier.ColdTier`.
        #: Finalized keyframe saves spill asynchronously; coverage agreement
        #: and the recovery ladder gain a third rung below reconstruct-from-
        #: parity — fetch-from-cold-tier.
        if cold is None:
            cold = coldtier_mod.cold_from_env(session=session, rank=rank)
        self.cold = cold or None
        #: Delta-checkpoint chain state (``checkpoint/coding/delta.py``):
        #: ``delta_interval`` N > 1 ships up to N-1 chunk-diff replication
        #: rounds between full keyframes (default: ``$TPU_RESILIENCY_CKPT_DELTA``,
        #: off). Composes with erasure replication: a delta round codes the
        #: FRAME (not the container), so each peer holds a ``frame/k``-sized
        #: block — ~(dirty-fraction)/k of the payload — with 1-of-k loss
        #: tolerance on top. Reconstruction yields the frame, which is applied
        #: against this rank's own base container; a lost/stale base breaks
        #: the chain for that iteration and the agreed fallback ladder walks
        #: back to the newest loadable generation (keyframes every
        #: ``delta_interval`` saves bound the walk).
        self._delta = ckpt_delta.DeltaTracker(delta_interval)
        #: Covered iterations retained after a successful save. 1 = the
        #: reference's newest-only recovery buffer; >=2 additionally keeps
        #: older rungs for the recovery ladder to fall back to when the newest
        #: iteration's shards fail their checksums on every holder.
        self.keep = max(1, int(keep))
        #: Pipelined snapshot engine (default: on for the thread caller): the
        #: caller-visible window of an async save is enqueue + skeleton pickle;
        #: D2H resolution, the replication fan-out, and the shard write all
        #: stream leaf by leaf in the background, staged through the pool.
        self.pipelined = caller == "thread" if pipelined is None else pipelined
        if self.pipelined and caller != "thread":
            raise CheckpointError(
                "pipelined saves require caller='thread' (the snapshot holds "
                "live device references and pool-leased buffers)"
            )
        self.staging = staging if staging is not None else HostStagingPool()
        self.queue = AsyncCallsQueue(
            caller=caller, sync_fn=comm.make_sync_fn() if comm is not None else None
        )
        self._dir = os.path.join(root, f"s{session}", f"r{rank}")
        os.makedirs(self._dir, exist_ok=True)
        self._cleanup_dirty()
        #: (path, mtime, size) → parsed container geometry + verify verdict,
        #: shared by the reshard read path and the ranged-read server so each
        #: container pays its header parse + integrity pass once.
        self._reshard_cache: dict[str, tuple] = {}
        if self.replication is not None:
            # Serve ranged reads off this rank's shard files: the wire op the
            # elastic reshard load path fetches newly-owned byte ranges over.
            self.replication.exchange.serve_ranges(self._serve_ranges)

    # -- local inventory ---------------------------------------------------

    def _cleanup_dirty(self) -> None:
        """Sweep crash/corruption residue at startup: every ``.dirty`` temp
        file goes; of the ``.corrupt`` quarantine files, the NEWEST per
        container name is kept for forensics (the operator gets one exemplar
        of what storage did to each shard) and older duplicates go."""
        newest_corrupt: dict[str, tuple[float, str]] = {}
        doomed: list[str] = []
        for name in os.listdir(self._dir):
            if name.endswith(ckpt_format.DIRTY_SUFFIX):
                doomed.append(name)
                continue
            m = _CORRUPT_RE.match(name)
            if not m:
                continue
            try:
                mtime = os.path.getmtime(os.path.join(self._dir, name))
            except OSError:
                continue
            base = m.group(1)
            prev = newest_corrupt.get(base)
            if prev is None or (mtime, name) > prev:
                if prev is not None:
                    doomed.append(prev[1])
                newest_corrupt[base] = (mtime, name)
            else:
                doomed.append(name)
        for name in doomed:
            try:
                os.unlink(os.path.join(self._dir, name))
            except OSError:
                pass

    def _quarantine(
        self, path: str, stage: str, iteration: int, owner: int, error=None
    ) -> Optional[str]:
        """Move a checksum-failed/unreadable container out of the inventory
        (``*.corrupt-<ts>``): retries and coverage math must never re-trust
        it, and the bytes stay on disk for forensics. Returns the quarantine
        path (None when the rename itself failed — file already gone)."""
        suffix = f"{ckpt_format.CORRUPT_SUFFIX}-{int(time.time() * 1000):x}"
        qpath = path + suffix
        n = 0
        while os.path.exists(qpath):  # same-ms double quarantine
            n += 1
            qpath = f"{path}{suffix}{n:x}"
        try:
            os.replace(path, qpath)
        except OSError:
            qpath = None
        log.error(
            f"rank {self.rank}: quarantined corrupt checkpoint {path} "
            f"(stage={stage}, error={error!r}) -> {qpath}"
        )
        record_event(
            "checkpoint", "ckpt_quarantined",
            path=os.path.basename(path), stage=stage, iteration=iteration,
            owner=owner, rank=self.rank,
            **({"error": repr(error)} if error is not None else {}),
        )
        return qpath

    def local_ids(self) -> set[CkptID]:
        """Checkpoint IDs held in this rank's directory (own shard + mirrors)."""
        out = set()
        for name in os.listdir(self._dir):
            m = _FILE_RE.match(name)
            if m:
                out.add(CkptID(int(m.group(1)), int(m.group(2)), self.session))
        return out

    def block_ids(self) -> set[tuple[int, int, int, int, int]]:
        """Erasure block artifacts on this rank's disk:
        ``(iteration, owner, index, k, m)`` — the filenames self-describe."""
        out = set()
        for name in os.listdir(self._dir):
            m = _BLOCK_RE.match(name)
            if m:
                out.add(tuple(int(g) for g in m.groups()))
        return out

    def _block_path(
        self, iteration: int, owner: int, index: int, k: int, m: int
    ) -> str:
        return os.path.join(
            self._dir, block_filename(iteration, owner, index, k, m)
        )

    def _read_block(self, iteration: int, owner: int, index: int) -> bytes:
        """Load one held block artifact (code geometry resolved from the
        filename inventory)."""
        for it, o, idx, k, m in self.block_ids():
            if (it, o, idx) == (iteration, owner, index):
                path = self._block_path(it, o, idx, k, m)
                try:
                    with open(path, "rb") as f:
                        return f.read()
                except OSError as e:
                    raise CheckpointError(
                        f"{path}: unreadable block artifact ({e!r})"
                    ) from e
        raise CheckpointError(
            f"rank {self.rank} holds no block (owner {owner}, index {index}) "
            f"@ iteration {iteration}"
        )

    def _path(self, ckpt_id: CkptID) -> str:
        return os.path.join(self._dir, ckpt_id.filename())

    # -- save --------------------------------------------------------------

    def save(
        self,
        iteration: int,
        state_dict: PyTreeStateDict,
        is_async: bool = True,
        meta: Optional[dict] = None,
        layout: Optional["reshard_mod.TreeLayout"] = None,
    ) -> Optional[AsyncRequest]:
        """Replicate + persist this rank's shard for ``iteration``.

        ``layout`` (a :class:`~tpu_resiliency.checkpoint.reshard.TreeLayout`)
        embeds the saving world's partition map in the container header meta,
        which is what makes the checkpoint resumable on a DIFFERENT world via
        :meth:`load_resharded` — any single surviving container then describes
        every rank's blocks.

        Pipelined (default, async + thread caller): synchronous on the caller
        is only enqueue-D2H + skeleton pickle + replication-round bookkeeping;
        the background worker resolves each leaf as its DMA lands and streams
        it simultaneously to the local shard file and every clique peer — D2H,
        disk IO, and peer sockets overlap leaf by leaf. Legacy (sync saves,
        process/fork callers): pop tensors → one blocking batched D2H → clique
        exchange → async file writes. Finalization (all ranks) is identical:
        coverage verification + pruning of older iterations
        (``base_manager.py:236-318``).
        """
        if layout is not None:
            meta = {**(meta or {}), reshard_mod.LAYOUT_META_KEY: layout.to_meta()}
        if self.pipelined and is_async:
            return self._save_pipelined(iteration, state_dict, meta)
        return self._save_materialized(iteration, state_dict, is_async, meta)

    def _check_layout(self, meta: Optional[dict], specs: list) -> None:
        """Fail a layout-bearing save LOUDLY when the declared layout does not
        match the tensors actually being written (the classic mistake: layout
        leaves listed in tree-insertion order while pytrees flatten in
        sorted-key order). Catching it here turns a later unexplainable
        "no live holder" reshard failure into a save-time geometry error."""
        layout = reshard_mod.extract_layout(meta or {})
        if layout is None:
            return
        if len(layout.leaves) != len(specs):
            raise CheckpointError(
                f"save(layout=): layout describes {len(layout.leaves)} leaves "
                f"but the state dict has {len(specs)} tensor leaves (pytree "
                f"leaves flatten in sorted-key order)"
            )
        for i, spec in enumerate(specs):
            box = layout.box(i, self.rank)
            want_dtype = layout.leaves[i].dtype
            if tuple(spec["shape"]) != box.shape or str(spec["dtype"]) != want_dtype:
                raise CheckpointError(
                    f"save(layout=): leaf {i} is {tuple(spec['shape'])}/"
                    f"{spec['dtype']} but the layout puts rank {self.rank}'s "
                    f"block at {box.shape}/{want_dtype} — layout leaves must "
                    f"follow the pytree flatten (sorted-key) order"
                )

    def _save_pipelined(
        self, iteration: int, state_dict: PyTreeStateDict, meta: Optional[dict]
    ) -> AsyncRequest:
        t0 = time.perf_counter()
        with span("checkpoint", "ckpt.save.enqueue", iteration=iteration):
            if not state_dict.is_hollow:
                state_dict.pop_tensors()
            snapshot = state_dict.copy_tensors_to_host_async(pool=self.staging)
            self._check_layout(meta, snapshot.specs)
            hollow_bytes = pickle.dumps(
                state_dict.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL
            )
            prefix = ckpt_format.header_prefix(
                hollow_bytes, snapshot.specs,
                meta={"iteration": iteration, **(meta or {})},
            )
            # Total container size includes the integrity trailer — its size
            # is fixed by the leaf specs + chunk size, so the stream can
            # declare it before any D2H byte lands (the CRCs themselves
            # resolve leaf by leaf).
            total = (
                len(prefix) + snapshot.nbytes
                + ckpt_format.trailer_size_for(
                    [s["nbytes"] for s in snapshot.specs]
                )
            )
            # Round tag minted HERE, in save-call order, so concurrent
            # background rounds stay aligned across ranks — whether the round
            # is a leaf-streaming mirror fan-out (stream), an erasure block
            # exchange, or a delta frame (pending): all three consume the
            # same per-strategy round counter in foreground order.
            repl = (
                self.replication
                if self.replication is not None and self.replication.enabled
                else None
            )
            stream = pending = delta_base = None
            if repl is not None:
                if self._delta.enabled and not self.queue.unfinalized_indices:
                    delta_base = self._delta.eligible(
                        [int(s["nbytes"]) for s in snapshot.specs]
                    )
                if repl.coded or delta_base is not None:
                    pending = repl.start_round()
                    pending.iteration = iteration
                else:
                    stream = repl.start_stream(total)
            own_path = self._path(CkptID(iteration, self.rank, self.session))
            # The worker fills in the final on-disk volume (own shard +
            # received mirrors); finalize reads it after the async part is done.
            sizes: dict = {}
            req = AsyncRequest(
                async_fn=self._pipelined_worker,
                async_fn_args=(
                    own_path, prefix, snapshot, stream, pending, delta_base,
                    iteration, sizes,
                ),
                cleanup_fns=(snapshot.release,),
                finalize_fns=(
                    lambda: self._finalize_save(
                        iteration, sizes.get("bytes"),
                        keyframe=sizes.get("keyframe", True),
                    ),
                ),
            )
            try:
                self.queue.schedule_async_request(req)
            except BaseException:
                snapshot.release()
                if stream is not None:
                    stream.abort()
                raise
        record_event(
            "checkpoint", "ckpt_foreground_blocked",
            duration_s=time.perf_counter() - t0,
            engine="pipelined", iteration=iteration,
        )
        return req

    def _pipelined_worker(
        self, own_path: str, prefix: bytes, snapshot, stream, pending,
        delta_base, iteration: int, sizes: dict,
    ) -> None:
        """Background half of a pipelined save: one pass over the leaves in
        D2H order, each resolved leaf going to the local shard file (and, in
        mirror-stream mode, every clique peer) before the next is touched.
        The same pass feeds the
        :class:`~tpu_resiliency.checkpoint.format.Checksummer`, so the
        integrity trailer (leaf CRCs + chunk manifest) costs zero extra
        reads. ``pending`` rounds (erasure blocks / delta frames) run their
        exchange AFTER the local write, off the already-resolved staged
        views — the byte-economy payloads need the full manifest first."""
        t0 = time.perf_counter()
        total = (
            len(prefix) + snapshot.nbytes
            + ckpt_format.trailer_size_for([s["nbytes"] for s in snapshot.specs])
        )
        try:
            if stream is not None:
                stream.open()
            state: dict = {}
            encoder = None
            if (
                pending is not None
                and delta_base is None
                and getattr(self.replication, "coded", False)
            ):
                # Erasure parity accumulates on the SAME leaf pass the
                # Checksummer rides, so the block exchange after the local
                # write starts with its encode already done — no second
                # payload walk, no payload-sized split copy.
                encoder = self.replication.start_encode(pending, total)

            def chunks():
                ck = ckpt_format.Checksummer(prefix)
                state["ck"] = ck
                if stream is not None:
                    stream.send_chunk(prefix)
                if encoder is not None:
                    encoder.update(prefix)
                yield prefix
                for i in range(len(snapshot)):
                    view = snapshot.resolve_view(i)
                    ck.add_leaf(view)
                    if stream is not None:
                        stream.send_chunk(view)
                    if encoder is not None:
                        encoder.update(view)
                    yield view
                trailer = ck.trailer()
                state["trailer"] = trailer
                if stream is not None:
                    stream.send_chunk(trailer)
                if encoder is not None:
                    encoder.update(trailer)
                yield trailer

            ckpt_format.write_stream(own_path, chunks())
            sent_delta = False
            if stream is not None:
                received = stream.finish()
            elif pending is not None:
                views = [
                    snapshot.resolve_view(i) for i in range(len(snapshot))
                ]
                trailer = state["trailer"]
                payload: list = [prefix, *views, trailer]
                if delta_base is not None:
                    try:
                        frame, stats = ckpt_delta.encode_delta(
                            self.rank, iteration, delta_base, prefix, views,
                            trailer,
                        )
                        record_event(
                            "checkpoint", "ckpt_delta",
                            iteration=iteration, rank=self.rank,
                            base_iteration=delta_base["iteration"], **stats,
                        )
                        payload = [frame]
                        sent_delta = True
                    except CheckpointError as e:
                        log.warning(
                            f"rank {self.rank}: delta encode @ iteration "
                            f"{iteration} fell back to keyframe: {e}"
                        )
                if encoder is not None:
                    received = self.replication.exchange_round(
                        pending, payload, encoder=encoder
                    )
                else:
                    received = self.replication.exchange_round(pending, payload)
            else:
                received = {}
            if self._delta.enabled and self.replication is not None:
                ck = state["ck"]
                self._delta.note_saved(
                    iteration,
                    [int(s["nbytes"]) for s in snapshot.specs],
                    ck.chunk_size, ck.leaf_chunks,
                    ckpt_format._U32.unpack(state["trailer"][-4:])[0],
                    keyframe=not sent_delta,
                )
            sizes["keyframe"] = not sent_delta
            items = self._received_items(iteration, received)
            if items:
                _persist_artifacts(items)
        except BaseException as e:
            if stream is not None:
                stream.abort()
            record_event(
                "checkpoint", "timing", name="ckpt.save.stream",
                duration_s=time.perf_counter() - t0, ok=False, error=repr(e),
                bytes=total, files=1,
            )
            raise
        sizes["bytes"] = total + sum(
            memoryview(b).cast("B").nbytes for b in received.values()
        )
        # The whole pipelined background half (d2h-resolve + fan-out + writes):
        # with the foreground ``ckpt.save.enqueue`` span this decomposes a
        # pipelined save end to end; mirror writes inside still emit their own
        # ``ckpt.save.write``.
        record_event(
            "checkpoint", "timing", name="ckpt.save.stream",
            duration_s=time.perf_counter() - t0, ok=True,
            bytes=sizes["bytes"], files=1 + len(received),
        )

    def _save_materialized(
        self,
        iteration: int,
        state_dict: PyTreeStateDict,
        is_async: bool,
        meta: Optional[dict],
    ) -> Optional[AsyncRequest]:
        with debug_time("ckpt.save.d2h", source="checkpoint"):
            if not state_dict.is_hollow:
                state_dict.pop_tensors()
            state_dict.copy_tensors_to_host()
        if meta and reshard_mod.LAYOUT_META_KEY in meta:
            from tpu_resiliency.checkpoint.state_dict import leaf_specs

            self._check_layout(meta, leaf_specs(state_dict.tensors()))
        with debug_time("ckpt.save.serialize", source="checkpoint"):
            hollow_bytes = pickle.dumps(
                state_dict.hollow_tree, protocol=pickle.HIGHEST_PROTOCOL
            )
            # Parts, not a joined blob: the container exists only as the header
            # prefix plus views over the host tensors. Replication scatter-
            # gathers these straight onto the peer sockets and the writer
            # streams them to disk — the only whole-shard buffers ever
            # materialized are the peers' single receive buffers.
            prefix, views = ckpt_format.serialize_parts(
                hollow_bytes, state_dict.tensors(), meta={"iteration": iteration, **(meta or {})}
            )
            parts = [prefix, *views]
            if self._caller_kind != "thread":
                # Process/fork callers pickle the async args; materialize the
                # views (thread caller — the default — stays zero-copy).
                parts = [prefix] + [bytes(v) for v in views]
        repl = (
            self.replication
            if self.replication is not None and self.replication.enabled
            else None
        )
        frame = None
        with debug_time("ckpt.save.replicate", source="checkpoint"):
            if repl is None:
                received = {}
            else:
                pending = repl.start_round()
                pending.iteration = iteration
                payload: list[Any] = parts
                frame = self._maybe_delta_frame(iteration, prefix, views)
                if frame is not None:
                    payload = [frame]
                received = repl.exchange_round(pending, payload)
        self._note_delta_base(iteration, views, repl, keyframe=frame is None)
        items: list[tuple] = [
            ("parts", self._path(CkptID(iteration, self.rank, self.session)),
             parts)
        ]
        items += self._received_items(iteration, received)
        total_bytes = _items_nbytes(items)
        req = AsyncRequest(
            async_fn=_persist_artifacts,
            async_fn_args=(items,),
            finalize_fns=(
                lambda: self._finalize_save(
                    iteration, total_bytes, keyframe=frame is None
                ),
            ),
        )
        if is_async:
            self.queue.schedule_async_request(req)
            return req
        req.execute_sync()
        return None

    def _maybe_delta_frame(
        self, iteration: int, prefix: bytes, views: list
    ) -> Optional[bytes]:
        """Encode this save's replication payload as a delta frame when the
        chain allows (delta enabled, base manifest matches, previous save
        fully finalized — overlapping in-flight saves keyframe so a peer can
        never be asked to apply against a base it hasn't persisted). Under
        the mirror strategy peers apply the frame immediately; under erasure
        the frame itself is what gets coded into blocks. ``views`` is a
        ``serialize_parts`` view list (leaves then trailer)."""
        if not self._delta.enabled:
            return None
        if self.queue.unfinalized_indices:
            return None
        leaf_sizes = [memoryview(v).cast("B").nbytes for v in views[:-1]]
        base = self._delta.eligible(leaf_sizes)
        if base is None:
            return None
        try:
            frame, stats = ckpt_delta.encode_delta(
                self.rank, iteration, base, prefix, views[:-1],
                bytes(memoryview(views[-1]).cast("B")),
            )
        except CheckpointError as e:
            log.warning(
                f"rank {self.rank}: delta encode @ iteration {iteration} "
                f"fell back to keyframe: {e}"
            )
            return None
        record_event(
            "checkpoint", "ckpt_delta",
            iteration=iteration, rank=self.rank,
            base_iteration=base["iteration"], **stats,
        )
        return frame

    def _note_delta_base(
        self, iteration: int, views: list, repl, keyframe: bool
    ) -> None:
        """Record this save's chunk manifest as the next delta's base (the
        trailer part already carries it — pure metadata)."""
        if not self._delta.enabled or repl is None:
            return
        try:
            info = ckpt_format.parse_trailer_v3(
                memoryview(views[-1]).cast("B"), source="delta-base"
            )
        except CheckpointError:
            self._delta.reset()
            return
        leaf_sizes = [memoryview(v).cast("B").nbytes for v in views[:-1]]
        self._delta.note_saved(
            iteration, leaf_sizes, info.chunk_size,
            info.leaf_chunk_crcs(leaf_sizes), info.container_crc,
            keyframe=keyframe,
        )

    def _received_items(self, iteration: int, received: dict) -> list[tuple]:
        """Route a replication round's received payloads to persistence ops:
        mirrors by (iteration, owner) path, erasure blocks by their
        self-described identity, delta frames to an apply against the held
        base container."""
        items: list[tuple] = []
        for owner, blob in received.items():
            if self._caller_kind != "thread" and not isinstance(blob, bytes):
                blob = bytes(blob)
            if ckpt_coding.is_block(blob):
                try:
                    it, o, idx, k, m = ckpt_coding.block_identity(blob)
                except CheckpointError as e:
                    log.warning(
                        f"dropping malformed block artifact from owner "
                        f"{owner}: {e}"
                    )
                    continue
                items.append(("blob", self._block_path(it, o, idx, k, m), blob))
            elif ckpt_delta.is_delta(blob):
                try:
                    header, _ = ckpt_delta.parse_delta(blob)
                except CheckpointError as e:
                    log.warning(
                        f"dropping malformed delta frame from owner "
                        f"{owner}: {e}"
                    )
                    continue
                base_path = self._path(
                    CkptID(int(header["base_iteration"]), owner, self.session)
                )
                items.append((
                    "delta",
                    self._path(CkptID(iteration, owner, self.session)),
                    blob, base_path, owner, iteration,
                ))
            else:
                items.append((
                    "blob",
                    self._path(CkptID(iteration, owner, self.session)),
                    blob,
                ))
        return items

    def _finalize_save(
        self, iteration: int, total_bytes: Optional[int] = None,
        keyframe: bool = True,
    ) -> None:
        """Verify coverage of ``iteration`` across ranks, then prune older iterations."""
        covered = self._covered_iterations()
        if iteration not in covered:
            record_event(
                "checkpoint", "ckpt_save_incomplete", iteration=iteration,
                owner_rank=self.rank, covered=sorted(covered)[-3:],
            )
            raise CheckpointError(
                f"checkpoint iteration {iteration} incomplete after save "
                f"(covered: {sorted(covered)[-3:]})"
            )
        # Only after coverage verification: ckpt_saved is a durability signal.
        # ``bytes`` = this rank's on-disk volume for the iteration (own shard +
        # mirrors), the cost side of the replication policy.
        record_event(
            "checkpoint", "ckpt_saved", iteration=iteration, owner_rank=self.rank,
            held=sorted(i.owner for i in self.local_ids() if i.iteration == iteration),
            **({"bytes": total_bytes} if total_bytes is not None else {}),
        )
        # Cold-tier spill: enqueue-only (the spiller's daemon thread ships the
        # bytes), so the save path pays a queue put and nothing else. Own
        # shards are always self-contained containers; the keyframe flag
        # carries the delta chain's cadence — delta rounds skip the upload.
        if self.cold is not None:
            own = self._path(CkptID(iteration, self.rank, self.session))
            if os.path.exists(own):
                self.cold.spill(
                    iteration, self.rank, own, keyframe=keyframe,
                )
        # Keep the newest ``keep`` iterations (the reference's retention policy
        # is keep=1 — local ckpts are a recovery buffer, not an archive;
        # keep>=2 funds the recovery ladder's fallback rung).
        retained = sorted(
            {i.iteration for i in self.local_ids()}, reverse=True
        )[: self.keep]
        for ckpt_id in self.local_ids():
            if ckpt_id.iteration < iteration and ckpt_id.iteration not in retained:
                try:
                    os.unlink(self._path(ckpt_id))
                except OSError:
                    pass
        # Erasure block artifacts follow the same retention horizon.
        for it, owner, index, k, m in self.block_ids():
            if it < iteration and it not in retained:
                try:
                    os.unlink(self._block_path(it, owner, index, k, m))
                except OSError:
                    pass

    # -- coverage / find_latest -------------------------------------------

    def _cold_pairs(self) -> list[tuple[int, int]]:
        """``(iteration, owner)`` shards the cold tier archives — the
        coverage ladder's third rung input. Empty on any store failure (a
        dead backend degrades coverage to the local tiers, never raises)."""
        if self.cold is None:
            return []
        try:
            return sorted(
                (it, o)
                for it, owners in self.cold.coverage().items()
                for o in owners
            )
        except OSError as e:
            log.warning(f"cold tier: coverage scan failed: {e!r}")
            return []

    def _covered_iterations(self) -> set[int]:
        """Iterations for which the union of all ranks' holdings covers every
        rank — where "covers" means a full container somewhere OR enough
        erasure blocks (≥ k distinct indices of one generation) to
        reconstruct one, OR an archived cold-tier container (the third rung:
        fetchable by any rank, including a fresh workdir that holds
        nothing), so coverage math matches what the recovery ladder can
        actually deliver."""
        if self.comm is None:
            out = {i.iteration for i in self.local_ids() if i.owner == self.rank}
            out.update(
                it for it, o in self._cold_pairs() if o == self.rank
            )
            return out
        gathered = self.comm.all_gather(
            (
                sorted((i.iteration, i.owner) for i in self.local_ids()),
                sorted(self.block_ids()),
                self._cold_pairs(),
            ),
            tag="coverage",
        )
        by_iter: dict[int, set[int]] = {}
        blocks: dict[tuple[int, int], set[int]] = {}
        kof: dict[tuple[int, int], int] = {}
        for holdings, block_holdings, cold_pairs in gathered:
            for it, owner in holdings:
                by_iter.setdefault(it, set()).add(owner)
            for it, owner, index, k, m in (tuple(b) for b in block_holdings):
                blocks.setdefault((it, owner), set()).add(index)
                kof[(it, owner)] = k
            # Union across ranks: a manifest any ONE rank observed counts (the
            # store is shared; scans may race an in-flight upload).
            for it, owner in (tuple(p) for p in cold_pairs):
                by_iter.setdefault(it, set()).add(owner)
        for (it, owner), indices in blocks.items():
            if len(indices) >= kof[(it, owner)]:
                by_iter.setdefault(it, set()).add(owner)
        world = set(self.comm.ranks)  # the group's actual rank ids, not range(world)
        return {it for it, owners in by_iter.items() if world <= owners}

    def rebuild_group(self, comm: StoreComm, remirror: bool = True) -> None:
        """Adopt a new rank group after reassignment; re-mirror within new cliques.

        Collective over the NEW group (every surviving/joining rank calls this with
        the same comm — construct it with ``generation=<restart iteration>`` so
        server-side barrier state from a gather that timed out against the dead
        world can never collide with the new group's). After a restart round
        changes the active world — a rank died, a degraded rank was demoted, a
        spare was promoted — the old cliques are stale: coverage agreement would
        all-gather over a group containing dead peers, and a shard whose only
        mirror died is one failure away from loss.
        This rebuilds the clique math over the new membership and (by default)
        re-mirrors each rank's newest own shard so the NEXT failure is covered.
        The reference fixes groups for the job's lifetime and so never faces this
        (``strategies.py:76-140``); health-driven replication owns it.
        """
        # Saves in flight were scheduled against the OLD group: their collective
        # finalization would hang on dead peers (or wrongly judge coverage in the
        # new world). Keep their local writes, drop their finalization.
        self.queue.abandon()
        self.comm = comm
        self.queue.set_sync_fn(comm.make_sync_fn() if comm is not None else None)
        # The delta chain is clique-scoped: new membership means peers whose
        # base inventory this rank cannot reason about — next save keyframes.
        self._delta.reset()
        if self.replication is None:
            return
        self.replication.rebuild(comm)
        if not (remirror and self.replication.enabled):
            return
        own = [i.iteration for i in self.local_ids() if i.owner == self.rank]
        newest = max(own) if own else None
        kwargs = {}
        if getattr(self.replication, "coded", False):
            kwargs = dict(
                held_blocks={
                    (o, it, idx, k, m)
                    for it, o, idx, k, m in self.block_ids()
                },
                get_block=lambda o, it, idx: self._read_block(it, o, idx),
            )
        received = self.replication.remirror(
            newest,
            lambda owner, it: self._read_blob(it, owner),
            held={(i.owner, i.iteration) for i in self.local_ids()},
            # On-disk shards stream file→socket via sendfile (no userspace copy).
            get_path=lambda owner, it: self._path(CkptID(it, owner, self.session)),
            **kwargs,
        )
        items: list[tuple] = []
        for owner, (it, blob) in received.items():
            if ckpt_coding.is_block(blob):
                try:
                    bit, o, idx, k, m = ckpt_coding.block_identity(blob)
                except CheckpointError as e:
                    log.warning(f"remirror: dropping malformed block ({e})")
                    continue
                items.append(("blob", self._block_path(bit, o, idx, k, m), blob))
            else:
                items.append(
                    ("blob", self._path(CkptID(it, owner, self.session)), blob)
                )
        if items:
            _persist_artifacts(items)
        record_event(
            "checkpoint", "ckpt_group_rebuilt", rank=self.rank,
            group=self.replication.my_group, remirrored=sorted(received),
        )

    def find_latest(self) -> int:
        """Newest iteration fully covered by the group's holdings, or -1.

        Mirrors reference ``base_manager.py:156-203`` (all-gather available IDs, pick
        the max iteration every rank can be served for).
        """
        covered = self._covered_iterations()
        return max(covered) if covered else -1

    # -- load --------------------------------------------------------------

    def load(self, iteration: Optional[int] = None) -> tuple[Any, list, dict]:
        """Load this rank's shard for ``iteration`` (default: ``find_latest()``),
        climbing the recovery ladder on integrity failure (module docstring):
        quarantine → peer retrieve (verify-on-receive) → group-agreed fallback
        to the next older iteration whose shards pass.

        Returns ``(hollow_tree, host_tensors, meta)`` — caller re-inserts and restores
        device placement (shardings belong to the *new* mesh after a restart). Routes
        through clique retrieval when the shard isn't held locally
        (``base_manager.py:205-234``).
        """
        with debug_time("ckpt.local_load", source="checkpoint"):
            return self._load(iteration)

    def _load(self, iteration: Optional[int]) -> tuple[Any, list, dict]:
        if iteration is None:
            iteration = self.find_latest()
        if iteration < 0:
            raise CheckpointError("no fully-covered local checkpoint found")
        requested = iteration
        while True:
            result, ok = self._load_attempt(iteration)
            if self.comm is None:
                agreed_ok = ok
            else:
                # The ladder is collective: every rank reports its verdict and
                # either all return iteration's tree or all fall back together.
                agreed_ok = all(
                    self.comm.all_gather(ok, tag="ckpt-ladder")
                )
            if agreed_ok:
                return result
            fallback = self._agree_fallback(iteration)
            if fallback is None:
                detail = (
                    "" if self.replication is not None or self.comm is None
                    else " (replication is disabled)"
                )
                raise CheckpointError(
                    f"rank {self.rank}: no intact checkpoint at or below "
                    f"iteration {requested}{detail} — newest attempt "
                    f"{iteration} failed integrity on some rank and no older "
                    f"covered iteration remains"
                )
            record_event(
                "checkpoint", "ckpt_fallback", rank=self.rank,
                from_iteration=iteration, to_iteration=fallback,
            )
            log.warning(
                f"rank {self.rank}: checkpoint ladder falling back from "
                f"iteration {iteration} to {fallback}"
            )
            iteration = fallback

    def _load_attempt(self, iteration: int) -> tuple[Optional[tuple], bool]:
        """One collective rung of the ladder: verify the local shard (or
        quarantine it), run the group retrieve, verify whatever arrived.
        Returns ``(result, ok)``; never raises for integrity failures — the
        caller's agreement round owns the fallback decision."""
        path = self._path(CkptID(iteration, self.rank, self.session))
        get_path = lambda o: self._path(CkptID(iteration, o, self.session))  # noqa: E731
        result = None
        needed: Optional[int] = None
        if os.path.exists(path):
            try:
                result = self._read_local_shard(iteration, self.rank)
            except CheckpointError as e:
                self._quarantine(
                    path, stage="local-read", iteration=iteration,
                    owner=self.rank, error=e,
                )
                needed = self.rank
        else:
            needed = self.rank
        if self.comm is None or self.replication is None:
            # No group/no replication: the cold tier is the only rung below
            # the local verdict (a distributed-but-unreplicated group still
            # runs the agreement round in _load, so ranks fall back in
            # lockstep).
            if result is None:
                result = self._cold_restore(iteration)
            return result, result is not None
        try:
            # The coded strategy's retrieve runs the reconstruct-from-parity
            # rung first (quarantine → reconstruct → peer retrieve →
            # fallback); feed it this rank's block inventory for the
            # iteration. The mirror strategy keeps its classic signature.
            kwargs = {}
            if getattr(self.replication, "coded", False):
                kwargs = dict(
                    my_held_blocks={
                        (o, idx, k, m)
                        for it, o, idx, k, m in self.block_ids()
                        if it == iteration
                    },
                    get_block=lambda o, idx: self._read_block(iteration, o, idx),
                )
            blob = self.replication.retrieve(
                needed, self._held_owners(iteration),
                lambda o: self._read_blob(iteration, o), get_path=get_path,
                **kwargs,
            )
        except CheckpointError as e:
            # "No live holder" (raised on every rank, deterministically) or a
            # transfer failure: locally-satisfied ranks keep their result; a
            # needy rank reports failure into the agreement round.
            log.warning(
                f"rank {self.rank}: retrieve for iteration {iteration} "
                f"failed: {e}"
            )
            blob = None
        if needed is None:
            return result, result is not None
        if blob is None:
            # Third rung: no live holder and no reconstructible parity — a
            # cold-tier archive (verified fail-closed against its manifest)
            # still satisfies this rank before the group falls back.
            result = self._cold_restore(iteration)
            return result, result is not None
        if ckpt_delta.is_delta(blob):
            # A coded delta generation reconstructs to the FRAME; materialize
            # the container by applying it against this rank's own base
            # container. A missing/stale base is a broken chain: report
            # failure into the agreement round so the ladder falls back to
            # the newest loadable generation — a wrong base can never
            # assemble a container (apply_delta fails closed on the digest
            # chain link).
            try:
                header, _ = ckpt_delta.parse_delta(
                    blob, source=f"retrieve(iter={iteration})"
                )
                base_path = self._path(CkptID(
                    int(header["base_iteration"]), self.rank, self.session
                ))
                ckpt_delta.apply_delta(blob, base_path, path)
                ckpt_delta.record_applied(
                    self.rank, iteration, "ok", stage="retrieve",
                )
            except CheckpointError as e:
                ckpt_delta.record_applied(
                    self.rank, iteration, "broken", stage="retrieve",
                    error=repr(e),
                )
                log.warning(
                    f"rank {self.rank}: recovered delta frame for iteration "
                    f"{iteration} did not apply ({e}); falling back"
                )
                return None, False
            try:
                result = self._read_local_shard(iteration, self.rank)
            except CheckpointError as e:
                self._quarantine(
                    path, stage="delta-apply", iteration=iteration,
                    owner=self.rank, error=e,
                )
                return None, False
            return result, True
        # Verified on receive by the replication layer; deserialize without a
        # second checksum pass. Re-persist the recovered shard so the next
        # restart is served locally and the clique regains redundancy.
        try:
            hollow_b, tensors, meta = ckpt_format.deserialize_from_buffer(
                blob, verify=False, source=f"retrieve(iter={iteration})"
            )
            result = (self._loads_hollow(hollow_b, path), tensors, meta)
        except CheckpointError as e:
            record_event(
                "checkpoint", "ckpt_integrity_failure", stage="peer-retrieve",
                iteration=iteration, owner=self.rank, rank=self.rank,
                error=repr(e),
            )
            return None, False
        try:
            ckpt_format.write_blob(path, blob)
        except OSError as e:
            log.warning(f"could not re-persist recovered shard {path}: {e!r}")
        return result, True

    def _cold_restore(self, iteration: int) -> Optional[tuple]:
        """Fetch this rank's shard for ``iteration`` from the cold tier into
        the local directory and read it back through the normal verify path.
        Returns the ``(hollow, tensors, meta)`` result or ``None`` — never
        raises (the ladder's agreement round owns the fallback decision).
        Both gates are fail-closed: the fetch verifies the manifest's
        whole-file digest before a byte becomes visible, and the local read
        re-verifies the container's own integrity record."""
        if self.cold is None:
            return None
        path = self._path(CkptID(iteration, self.rank, self.session))
        try:
            if self.cold.manifest(iteration, self.rank) is None:
                return None
            self.cold.fetch(iteration, self.rank, path)
            return self._read_local_shard(iteration, self.rank)
        except (CheckpointError, OSError) as e:
            log.warning(
                f"rank {self.rank}: cold-tier restore of iteration "
                f"{iteration} failed: {e}"
            )
            if os.path.exists(path):
                self._quarantine(
                    path, stage="cold-fetch", iteration=iteration,
                    owner=self.rank, error=e,
                )
            return None

    def _agree_fallback(self, failed_iteration: int) -> Optional[int]:
        """The fallback rung every rank agrees on: the newest covered iteration
        older than the failed one, converged with an explicit ``StoreComm``
        agreement round so no rank can diverge on a stale coverage view."""
        if self.comm is None:
            covered = self._covered_iterations()
            older = [it for it in covered if it < failed_iteration]
            return max(older) if older else None
        covered = self._covered_iterations()
        older = [it for it in covered if it < failed_iteration]
        candidate = max(older) if older else -1
        agreed = self.comm.all_reduce_min(candidate, tag="ckpt-fallback")
        return agreed if agreed >= 0 else None

    def load_tree(
        self,
        iteration: Optional[int] = None,
        shardings=None,
        device=None,
    ) -> tuple[Any, dict]:
        """``load`` + rebuild: returns ``(tree, meta)`` with tensors re-inserted and
        placed per ``shardings``/``device`` (or the default device)."""
        from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict

        hollow, tensors, meta = self.load(iteration)
        sd = PyTreeStateDict.from_hollow(hollow, tensors, shardings=shardings, device=device)
        return sd.tree, meta

    def load_resharded_tree(
        self,
        target: Optional["reshard_mod.TreeLayout"] = None,
        iteration: Optional[int] = None,
        axes=None,
        shardings=None,
        device=None,
    ) -> tuple[Any, dict]:
        """``load_resharded`` + rebuild: the mesh-aware restore in one call.
        ``shardings`` belong to the NEW mesh (e.g.
        ``mesh.tree_shardings(new_mesh, specs)``); placeholder shapes are
        already synced to the target world, so shape-driven spec functions
        see the resharded truth."""
        from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict

        hollow, tensors, meta = self.load_resharded(
            target=target, iteration=iteration, axes=axes
        )
        sd = PyTreeStateDict.from_hollow(
            hollow, tensors, shardings=shardings, device=device
        )
        return sd.tree, meta

    def load_shard(
        self, owner: int, iteration: Optional[int] = None
    ) -> tuple[Any, list, dict]:
        """Load a locally-held shard belonging to ``owner`` (own shard or a clique
        mirror) — the reshard path after a world shrink: a survivor reconstructs a
        departed rank's state from the mirror its replication clique left on this
        rank's disk. Strictly local, no collective participation — including the
        default ``iteration``, which is the newest iteration whose ``owner`` shard
        is on this rank's disk (NOT ``find_latest()``, whose coverage agreement
        would all-gather over a group that may contain the dead peer). Returns
        ``(hollow_tree, host_tensors, meta)`` like :meth:`load`."""
        if iteration is None:
            held = [i.iteration for i in self.local_ids() if i.owner == owner]
            if not held:
                raise CheckpointError(
                    f"rank {self.rank} holds no shards for owner {owner}"
                )
            iteration = max(held)
        return self._read_local_shard(iteration, owner)

    def _read_local_shard(self, iteration: int, owner: int) -> tuple[Any, list, dict]:
        """Shared local-disk read tail for :meth:`load` / :meth:`load_shard`.

        Every failure mode of a damaged container — checksum mismatch,
        truncation, unreadable file, corrupt hollow pickle — surfaces as
        :class:`CheckpointError` naming the path, so the recovery ladder and
        callers classify disk damage uniformly."""
        path = self._path(CkptID(iteration, owner, self.session))
        if not os.path.exists(path):
            raise CheckpointError(
                f"rank {self.rank} holds no shard for owner {owner} @ iteration "
                f"{iteration} (held: {sorted(self._held_owners(iteration))})"
            )
        try:
            hollow_b, tensors, meta = ckpt_format.read_payload(path)
        except CheckpointError:
            raise
        except OSError as e:
            raise CheckpointError(f"{path}: unreadable shard ({e!r})") from e
        return self._loads_hollow(hollow_b, path), tensors, meta

    @staticmethod
    def _loads_hollow(hollow_b: bytes, source: str) -> Any:
        """Unpickle a hollow skeleton; damage surfaces as CheckpointError
        naming the source (pickle raises half a dozen exception types)."""
        try:
            return pickle.loads(hollow_b)
        except Exception as e:
            raise CheckpointError(
                f"{source}: corrupt hollow skeleton ({e!r})"
            ) from e

    def _held_owners(self, iteration: int) -> set[int]:
        return {i.owner for i in self.local_ids() if i.iteration == iteration}

    def _read_blob(self, iteration: int, owner: int) -> bytes:
        path = self._path(CkptID(iteration, owner, self.session))
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError as e:
            raise CheckpointError(f"{path}: unreadable shard ({e!r})") from e

    # -- elastic reshard ---------------------------------------------------

    def _container_geometry(self, iteration: int, owner: int) -> dict:
        """Parse (once per file version) a held container's geometry: header
        prefix length, per-leaf payload offsets/specs, hollow bytes and meta.

        Integrity is version-aware: a ``TPURES03`` container's chunk manifest
        loads here in O(trailer) — two small reads — and every byte the
        reshard path later serves or slices is verified CHUNK-GRANULAR on
        first touch (``_read_ranges``), so serving a 4 KB range never pays a
        whole-container CRC scan (the serve-side stall BENCH_reshard.json
        measured). Pre-chunk containers (``TPURES02``/v1/foreign algo) keep
        the one-time full streaming pass. A corrupt container is quarantined
        and surfaces as CheckpointError either way."""
        path = self._path(CkptID(iteration, owner, self.session))
        try:
            st = os.stat(path)
        except OSError as e:
            raise CheckpointError(f"{path}: unreadable shard ({e!r})") from e
        key = (st.st_mtime_ns, st.st_size)
        cached = self._reshard_cache.get(path)
        if cached is not None and cached[0] == key:
            return cached[1]
        header = info = None
        try:
            header, prefix_len, info = ckpt_format.read_trailer(path)
        except CheckpointError as e:
            self._quarantine(
                path, stage="reshard-verify", iteration=iteration, owner=owner,
                error=e,
            )
            self._reshard_cache.pop(path, None)
            raise CheckpointError(f"{path}: corrupt container ({e})") from e
        except OSError as e:
            raise CheckpointError(f"{path}: unreadable shard ({e!r})") from e
        chunked = (
            info is not None and info.chunk_crcs is not None and info.verifiable
        )
        if not chunked:
            # No chunk manifest to verify ranges against: fall back to the
            # one-time whole-file pass (old behavior, cached per file version).
            status, detail = ckpt_format.verify_file(path)
            if status == "corrupt":
                self._quarantine(
                    path, stage="reshard-verify", iteration=iteration,
                    owner=owner, error=detail,
                )
                self._reshard_cache.pop(path, None)
                raise CheckpointError(f"{path}: corrupt container ({detail})")
        offs, pos = [], prefix_len
        for spec in header["leaves"]:
            offs.append(pos)
            pos += int(spec["nbytes"])
        geom = {
            "path": path,
            "iteration": iteration,
            "owner": owner,
            "leaf_offsets": offs,
            "leaf_specs": header["leaves"],
            "hollow": header["hollow"],
            "meta": header.get("meta", {}),
            "verified": not chunked,
            "chunk_size": info.chunk_size if chunked else None,
            "chunk_crcs": (
                info.leaf_chunk_crcs(
                    [int(s["nbytes"]) for s in header["leaves"]]
                )
                if chunked else None
            ),
            #: (leaf, chunk) pairs that passed their CRC — chunk-granular
            #: verification state, grows as ranges are touched.
            "verified_chunks": set(),
            #: guards ``verified_chunks`` — ranges are served off a bounded
            #: worker pool and p2p connection threads concurrently.
            "lock": threading.Lock(),
        }
        self._reshard_cache[path] = (key, geom)
        return geom

    @staticmethod
    def _reshard_io_threads() -> int:
        """Bounded worker count for the reshard hot path (serve-side pread +
        chunk-verify fan-out, load-side peer-fetch overlap). Tunable via
        ``TPU_RESILIENCY_RESHARD_IO_THREADS``; ``1`` restores the serial
        path exactly."""
        try:
            n = int(os.environ.get("TPU_RESILIENCY_RESHARD_IO_THREADS", "4"))
        except ValueError:
            n = 4
        return max(1, n)

    def _read_ranges(
        self, iteration: int, owner: int, ranges: list
    ) -> list[bytes]:
        """pread leaf-relative byte ranges out of a locally-held container;
        ``ranges`` items are ``(leaf, src_off, nbytes)``.

        Verification is O(range) on chunked (``TPURES03``) containers: only
        the chunks covering each requested range are CRC-checked, on first
        touch (verdicts cached per file version). Pre-chunk containers were
        verified whole by ``_container_geometry``. A chunk that fails its CRC
        quarantines the container and raises — the caller's degraded-holder /
        recovery machinery owns the retry.

        Multi-range requests run over a bounded worker pool: pread and CRC
        passes for distinct ranges overlap (the CRC is pure compute, the
        pread is kernel time — both release the GIL), while the returned
        parts keep request order. Single ranges stay on the calling thread.
        """
        geom = self._container_geometry(iteration, owner)
        checked = []
        for leaf, off, nbytes in ranges:
            leaf, off, nbytes = int(leaf), int(off), int(nbytes)
            if not 0 <= leaf < len(geom["leaf_offsets"]):
                raise CheckpointError(
                    f"{geom['path']}: range names leaf {leaf} of "
                    f"{len(geom['leaf_offsets'])}"
                )
            limit = int(geom["leaf_specs"][leaf]["nbytes"])
            if off < 0 or nbytes < 0 or off + nbytes > limit:
                raise CheckpointError(
                    f"{geom['path']}: range [{off}, {off + nbytes}) outside "
                    f"leaf {leaf} payload of {limit} bytes"
                )
            checked.append((leaf, off, nbytes))
        with open(geom["path"], "rb") as f:
            fd = f.fileno()

            def read_one(rng: tuple) -> bytes:
                leaf, off, nbytes = rng
                if geom["chunk_size"] is not None:
                    return self._pread_chunk_verified(fd, geom, leaf, off, nbytes)
                buf = os.pread(fd, nbytes, geom["leaf_offsets"][leaf] + off)
                if len(buf) != nbytes:
                    raise CheckpointError(
                        f"{geom['path']}: short read in leaf {leaf} "
                        f"({len(buf)} of {nbytes} bytes)"
                    )
                return buf

            workers = min(self._reshard_io_threads(), len(checked))
            if workers > 1:
                with concurrent.futures.ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="reshard-io"
                ) as pool:
                    # map() preserves request order and re-raises the first
                    # worker exception (quarantine already happened inside).
                    out = list(pool.map(read_one, checked))
            else:
                out = [read_one(rng) for rng in checked]
        return out

    def _pread_chunk_verified(
        self, fd: int, geom: dict, leaf: int, off: int, nbytes: int
    ) -> bytes:
        """One leaf-relative range off a chunked container: pread the covering
        chunk span, CRC any not-yet-verified covering chunk against the
        manifest, slice the requested bytes out. Already-verified spans pread
        exactly the requested range."""
        if nbytes == 0:
            return b""
        cs = geom["chunk_size"]
        leaf_nbytes = int(geom["leaf_specs"][leaf]["nbytes"])
        base = geom["leaf_offsets"][leaf]
        first, last = ckpt_format.chunk_spans(leaf_nbytes, cs, off, nbytes)
        vset = geom["verified_chunks"]
        lock = geom["lock"]
        with lock:
            verified = all((leaf, c) in vset for c in range(first, last))
        if verified:
            buf = os.pread(fd, nbytes, base + off)
            if len(buf) != nbytes:
                raise CheckpointError(
                    f"{geom['path']}: short read in leaf {leaf} "
                    f"({len(buf)} of {nbytes} bytes)"
                )
            return buf
        span_start = first * cs
        span_end = min(last * cs, leaf_nbytes)
        blob = os.pread(fd, span_end - span_start, base + span_start)
        if len(blob) != span_end - span_start:
            raise CheckpointError(
                f"{geom['path']}: short read in leaf {leaf} chunk span "
                f"({len(blob)} of {span_end - span_start} bytes)"
            )
        mv = memoryview(blob)
        crcs = geom["chunk_crcs"][leaf]
        for c in range(first, last):
            with lock:
                if (leaf, c) in vset:
                    continue
            # CRC runs outside the lock (two workers may race on the same
            # chunk; the duplicate check is cheaper than serializing them).
            w = mv[c * cs - span_start : min((c + 1) * cs, leaf_nbytes) - span_start]
            if ckpt_format.crc32c(w) != crcs[c]:
                self._quarantine(
                    geom["path"], stage="chunk-verify",
                    iteration=geom["iteration"], owner=geom["owner"],
                    error=f"leaf {leaf} chunk {c} checksum mismatch",
                )
                self._reshard_cache.pop(geom["path"], None)
                raise CheckpointError(
                    f"{geom['path']}: leaf {leaf} chunk {c} checksum mismatch "
                    f"(payload corrupted)"
                )
            with lock:
                vset.add((leaf, c))
        return bytes(mv[off - span_start : off - span_start + nbytes])

    def _serve_ranges(self, request: dict) -> tuple[dict, list]:
        """``PeerExchange.serve_ranges`` handler: answer a peer's ranged read
        against a container this rank holds (own shard or clique mirror).
        Runs on a p2p connection thread; every reply range comes from a
        container that passed (or is re-verified through) the streaming
        integrity check, and the exchange stamps per-range CRCs on the way
        out."""
        try:
            session = int(request.get("session", self.session))
            iteration = int(request["iteration"])
            owner = int(request["owner"])
            ranges = list(request.get("ranges") or [])
        except (KeyError, TypeError, ValueError) as e:
            raise CheckpointError(f"malformed range request ({e!r})") from e
        if session != self.session:
            raise CheckpointError(
                f"rank {self.rank} serves session {self.session}, "
                f"not {session}"
            )
        parts = self._read_ranges(iteration, owner, ranges)
        workers = min(self._reshard_io_threads(), max(1, len(ranges)))
        record_event(
            "checkpoint", "reshard_serve", rank=self.rank, iteration=iteration,
            owner=owner, ranges=len(ranges),
            bytes=sum(len(p) for p in parts), workers=workers,
            mode="parallel" if workers > 1 else "serial",
        )
        extra = {"owner": owner, "iteration": iteration}
        if request.get("want_header"):
            geom = self._container_geometry(iteration, owner)
            extra["hollow"] = geom["hollow"]
            extra["meta"] = geom["meta"]
        return extra, parts

    def load_resharded(
        self,
        target: Optional["reshard_mod.TreeLayout"] = None,
        iteration: Optional[int] = None,
        axes=None,
    ) -> tuple[Any, list, dict]:
        """Load on a world that need NOT match the saving world's sharding.

        Collective over ``self.comm`` (the NEW world's group — after a shrink
        or grow, construct it over the surviving/joining ranks and
        ``rebuild_group`` first). The newest iteration whose containers carry
        a layout (saved with ``save(..., layout=...)``) and whose surviving
        copies cover the target world is chosen — older iterations are tried
        when a newer one's coverage is impossible; an explicitly requested
        ``iteration`` fails hard instead of falling back.

        ``target`` defaults to the SOURCE layout retargeted onto this comm's
        ranks (``axes`` overrides the dp-rescale rule — pass a dict like
        ``{"dp": 2, "tp": 2}`` for a changed model split). Bytes this rank
        already holds (its own shard, clique mirrors) are sliced locally;
        everything else is ranged-fetched from peers — strictly the byte
        ranges newly owned, never whole mirror containers.

        Returns ``(hollow_tree, host_tensors, meta)`` like :meth:`load`; the
        returned ``meta["layout"]`` describes the TARGET world, ready to pass
        back into the next ``save(..., layout=...)``.
        """
        with debug_time("ckpt.reshard_load", source="checkpoint"):
            return self._load_resharded(target, iteration, axes)

    def _load_resharded(self, target, iteration, axes) -> tuple[Any, list, dict]:
        t0 = time.perf_counter()
        held = sorted((i.iteration, i.owner) for i in self.local_ids())
        if self.comm is None:
            gathered = [(self.rank, held, self._cold_pairs())]
            world = [self.rank]
        else:
            gathered = self.comm.all_gather(
                (self.rank, held, self._cold_pairs()), tag="reshard-meta"
            )
            world = list(self.comm.ranks)
        holders: dict[tuple[int, int], list[int]] = {}
        # (iteration -> owners) archived in the cold tier, unioned across the
        # gather so every rank reasons from the same third-rung inventory —
        # this is what lets a FRESH world with empty workdirs bootstrap.
        cold_owners: dict[int, set[int]] = {}
        for r, pairs, cold_pairs in gathered:
            for it, owner in pairs:
                holders.setdefault((int(it), int(owner)), []).append(int(r))
            for it, owner in (tuple(p) for p in cold_pairs):
                cold_owners.setdefault(int(it), set()).add(int(owner))
        candidates = sorted(
            {it for it, _ in holders} | set(cold_owners), reverse=True
        )
        if iteration is not None:
            candidates = [it for it in candidates if it == iteration]
            if not candidates:
                raise CheckpointError(
                    f"reshard: no rank holds any container for iteration "
                    f"{iteration}"
                )
        errors: list[str] = []
        for it in candidates:
            picked = self._reshard_candidate(
                it, holders, world, target, axes, errors, cold_owners
            )
            if picked is None:
                if iteration is not None:
                    raise CheckpointError(
                        f"reshard: iteration {iteration} not resumable on "
                        f"world {world}: {'; '.join(errors)}"
                    )
                continue
            plan, tgt, hollow_b, meta = picked
            with span(
                "checkpoint", "reshard.plan",
                iteration=it, direction=plan.direction,
                source_world=plan.source.world_size,
                target_world=plan.target.world_size,
            ):
                summary = plan.summary(
                    rank=self.rank,
                    local_owners={
                        self.rank: {o for i2, o in held if i2 == it}
                    },
                )
            record_event(
                "checkpoint", "reshard_plan", iteration=it, rank=self.rank,
                direction=plan.direction,
                source_world=plan.source.world_size,
                target_world=plan.target.world_size,
                local_bytes=summary["local_bytes"],
                peer_bytes=summary["peer_bytes"],
                ranges=summary["ranges"],
            )
            try:
                tensors = self._execute_reshard(plan, it, holders, cold_owners)
                exec_err: Optional[CheckpointError] = None
            except CheckpointError as e:
                tensors, exec_err = None, e
            if self.comm is not None:
                # Exit barrier: a rank whose assembly was all-local must keep
                # serving ranged reads until every peer has fetched its share.
                self.comm.barrier(tag="reshard-done")
                # Commit agreement: assembly is all-or-nothing across the
                # group. A rank whose fetch failed fail-closed (a cold
                # artifact flunking its manifest digest, every holder of a
                # segment dead) votes no and EVERY rank discards and climbs
                # to the next older candidate — corrupt bytes are never
                # restored, and no rank diverges onto a different iteration.
                oks = self.comm.all_gather(exec_err is None, tag="reshard-commit")
                if not all(oks):
                    errors.append(
                        f"iter {it}: assembly failed on some rank"
                        + (f" ({exec_err})" if exec_err is not None else "")
                    )
                    if iteration is not None:
                        raise CheckpointError(
                            f"reshard: iteration {iteration} not assemblable "
                            f"on world {world}: {'; '.join(errors)}"
                        )
                    continue
            elif exec_err is not None:
                errors.append(f"iter {it}: {exec_err}")
                if iteration is not None:
                    raise CheckpointError(
                        f"reshard: iteration {iteration} not assemblable: "
                        f"{'; '.join(errors)}"
                    )
                continue
            meta = {
                **meta,
                "iteration": meta.get("iteration", it),
                reshard_mod.LAYOUT_META_KEY: tgt.to_meta(),
            }
            record_event(
                "checkpoint", "timing", name="ckpt.reshard_load",
                duration_s=time.perf_counter() - t0, ok=True,
                bytes=summary["total_bytes"],
            )
            hollow = self._loads_hollow(hollow_b, f"reshard(iter={it})")
            try:
                from tpu_resiliency.checkpoint.state_dict import (
                    sync_placeholder_shapes,
                )

                # Placeholders still carry the SAVING world's local shapes;
                # shape-driven restores (make_restore_shardings spec fns)
                # must see the target world's.
                sync_placeholder_shapes(hollow, tensors)
            except ImportError:  # pragma: no cover - jax-less tooling host
                pass
            return hollow, tensors, meta
        raise CheckpointError(
            "reshard: no resharded-resumable iteration found"
            + (f" ({'; '.join(errors)})" if errors else " (no layout-bearing "
               "containers on any rank — save with save(..., layout=...))")
        )

    def _reshard_candidate(
        self, it, holders, world, target, axes, errors, cold_owners=None
    ):
        """One collective attempt at iteration ``it``: the lowest holder rank
        (or, when NO rank holds a container — the fresh-bootstrap case — the
        lowest live rank) reads+broadcasts a container's layout/hollow/meta;
        every rank builds the same plan and the same coverage verdict. The
        designated rank sources the header from a held container first, then
        from a cold-tier ranged header fetch (manifest-digest verified, paid
        in O(header) bytes). Returns ``(plan, target, hollow, meta)`` or None
        (verdict recorded in ``errors``)."""
        cold = (cold_owners or {}).get(it, set())
        holder_ranks = sorted(
            {r for (i2, _), rs in holders.items() if i2 == it for r in rs}
        )
        designated = holder_ranks[0] if holder_ranks else min(world)
        payload: dict = {}
        if self.rank == designated:
            owned = sorted(
                o for (i2, o) in holders
                if i2 == it and self.rank in holders[(i2, o)]
            )
            last_err = "no held container"
            for owner in owned:
                # Any intact container describes the whole world; a corrupt
                # one was just quarantined — try the next held copy.
                try:
                    geom = self._container_geometry(it, owner)
                except CheckpointError as e:
                    last_err = str(e)
                    continue
                raw = geom["meta"].get(reshard_mod.LAYOUT_META_KEY)
                if raw is None:
                    last_err = (
                        f"iteration {it}: containers carry no layout meta"
                    )
                    continue
                mismatch = self._layout_header_mismatch(raw, geom, owner)
                if mismatch:
                    last_err = f"iteration {it}: {mismatch}"
                    continue
                payload = {
                    "layout": raw, "hollow": geom["hollow"],
                    "meta": geom["meta"],
                }
                break
            else:
                payload = self._cold_header_payload(it, sorted(cold), last_err)
        if self.comm is not None:
            payload = self.comm.broadcast(
                payload, src=designated, tag="reshard-hdr"
            )
        if payload.get("error"):
            errors.append(f"iter {it}: {payload['error']}")
            return None
        try:
            source = reshard_mod.TreeLayout.from_meta(payload["layout"])
            tgt = (
                target
                if target is not None
                else source.retarget(world, axes=axes)
            )
            plan = reshard_mod.build_plan(source, tgt)
            available = {o for (i2, o) in holders if i2 == it} | cold
            plan.require_available(available)
        except CheckpointError as e:
            errors.append(f"iter {it}: {e}")
            return None
        return plan, tgt, payload["hollow"], dict(payload.get("meta") or {})

    def _cold_header_payload(
        self, it: int, cold_sorted: list, last_err: str
    ) -> dict:
        """The designated rank's cold-tier header source: ranged-fetch one
        archived owner's container head, cross-check its layout meta against
        the manifest's leaf sizes. Returns the broadcast payload (or an
        ``{"error": ...}`` verdict)."""
        if self.cold is None or not cold_sorted:
            return {"error": last_err}
        for owner in cold_sorted:
            try:
                doc, header = self.cold.fetch_header(it, owner)
            except (CheckpointError, OSError) as e:
                last_err = f"iteration {it}: cold header fetch failed ({e})"
                continue
            raw = (header.get("meta") or {}).get(reshard_mod.LAYOUT_META_KEY)
            if raw is None:
                last_err = (
                    f"iteration {it}: cold containers carry no layout meta"
                )
                continue
            mismatch = self._layout_header_mismatch(
                raw, {"leaf_specs": header["leaves"]}, owner
            )
            if mismatch:
                last_err = f"iteration {it}: {mismatch}"
                continue
            return {
                "layout": raw, "hollow": header["hollow"],
                "meta": dict(header.get("meta") or {}),
            }
        return {"error": last_err}

    @staticmethod
    def _layout_header_mismatch(raw_layout, geom: dict, owner: int):
        """Cross-check an embedded layout against the container's OWN header
        leaf specs (save-time validation exists too, but metas written by
        older code — or hand-edited — must not send the executor chasing
        ranges outside real payloads). Returns a description or None."""
        try:
            layout = reshard_mod.TreeLayout.from_meta(raw_layout)
        except CheckpointError as e:
            return str(e)
        specs = geom["leaf_specs"]
        if len(layout.leaves) != len(specs):
            return (
                f"layout describes {len(layout.leaves)} leaves, container "
                f"has {len(specs)}"
            )
        for i, spec in enumerate(specs):
            box = layout.box(i, owner)
            if tuple(spec["shape"]) != box.shape or (
                str(spec["dtype"]) != layout.leaves[i].dtype
            ):
                return (
                    f"layout leaf {i} puts owner {owner}'s block at "
                    f"{box.shape}/{layout.leaves[i].dtype} but the container "
                    f"holds {tuple(spec['shape'])}/{spec['dtype']}"
                )
        return None

    def _execute_reshard(
        self, plan: "reshard_mod.ReshardPlan", it: int, holders: dict,
        cold_owners: Optional[dict] = None,
    ) -> list:
        """Assemble this rank's target-local leaves: local pread for ranges a
        held container covers, ranged peer fetch for the rest, ranged
        cold-tier fetch (manifest chunk CRCs verified per covering chunk —
        O(needed bytes)) when no live peer holds a source. The cold rung is
        how a fresh world with empty workdirs assembles at all: every
        segment routes to the archive.

        Peer fetches run over a bounded worker pool and OVERLAP the local
        pread/assembly pass — the wire drains while this thread slices its
        own containers, instead of back-to-back phases. Determinism survives
        the concurrency: assignment happens up front in plan order (same
        load-balanced ``min(pairs, ...)`` choice as the serial path, byte
        for byte), workers only move bytes into disjoint buffer slices, and
        failed holders are re-placed round-by-round in sorted batch order —
        never in wall-clock completion order. Cold batches ride the same
        pool under the sentinel holder ``-1``."""
        import numpy as np

        rp = plan.for_rank(self.rank)
        buffers = [
            np.empty(shape, dtype=ckpt_format.resolve_dtype(spec.dtype))
            for shape, spec in zip(rp.local_shapes, plan.target.leaves)
        ]
        flats = [b.reshape(-1).view(np.uint8) for b in buffers]
        my_owners = {
            o for (i2, o), rs in holders.items() if i2 == it and self.rank in rs
        }
        cold = set((cold_owners or {}).get(it, set())) if self.cold is not None else set()
        local_bytes = 0
        # (holder, owner) -> [segments]; holder -1 = the cold tier
        remote: dict[tuple[int, int], list] = {}
        load: dict[int, int] = {}
        dead: set[int] = set()
        dead_cold: set[int] = set()
        avoid = set(
            self.replication.last_degraded if self.replication is not None else ()
        )

        def assign(seg) -> bool:
            """Route one segment: local queue when a held container covers it,
            the deterministic load-balanced holder choice when a live peer
            has one, else the cold tier. No I/O — returns True for local,
            False for remote/cold."""
            if set(seg.owners) & my_owners:
                return True
            pairs = sorted(
                (h, o)
                for o in seg.owners
                for h in holders.get((it, o), [])
                if h != self.rank and h not in dead
            ) if self.replication is not None else []
            if not pairs:
                cold_avail = sorted((set(seg.owners) & cold) - dead_cold)
                if cold_avail:
                    o = cold_avail[0]
                    load[-1] = load.get(-1, 0) + len(seg.ranges)
                    remote.setdefault((-1, o), []).append(seg)
                    return False
                if self.replication is None and any(
                    holders.get((it, o)) for o in seg.owners
                ):
                    raise CheckpointError(
                        f"reshard: leaf {seg.leaf} cell owned by "
                        f"{list(seg.owners)} is only on peer ranks and this "
                        f"manager has no replication exchange to fetch over"
                    )
                raise CheckpointError(
                    f"reshard: no live holder left for leaf {seg.leaf} cell "
                    f"owned by {list(seg.owners)} @ iteration {it} (cold "
                    f"tier: {'exhausted' if dead_cold else 'no copy'})"
                )
            h, o = min(
                pairs, key=lambda p: (p[0] in avoid, load.get(p[0], 0), p)
            )
            load[h] = load.get(h, 0) + len(seg.ranges)
            remote.setdefault((h, o), []).append(seg)
            return False

        def read_local(seg) -> bool:
            """Fill one locally-covered segment; False when every held copy
            failed (those owners are discarded — the caller re-assigns)."""
            nonlocal local_bytes
            for owner in sorted(set(seg.owners) & my_owners):
                try:
                    got = self._read_ranges(
                        it, owner,
                        [(seg.leaf, r.src_off, r.nbytes) for r in seg.ranges],
                    )
                except CheckpointError as e:
                    # Local copy corrupt/unreadable (already quarantined by
                    # the geometry pass): stop trusting it and fall through
                    # to the peer path for this and every later segment.
                    log.warning(
                        f"rank {self.rank}: local reshard read of owner "
                        f"{owner} @ iter {it} failed: {e}"
                    )
                    my_owners.discard(owner)
                    continue
                for r, buf in zip(seg.ranges, got):
                    flats[seg.leaf][r.dst_off : r.dst_off + r.nbytes] = (
                        np.frombuffer(buf, dtype=np.uint8)
                    )
                    local_bytes += r.nbytes
                return True
            return False

        def fetch_batch(holder: int, owner: int, segs: list) -> list:
            ranges = [
                (seg.leaf, r.src_off, r.nbytes)
                for seg in segs for r in seg.ranges
            ]
            if holder < 0:
                # Cold rung: every covering chunk verified against the
                # manifest before its slice comes back — fail-closed.
                return self.cold.fetch_ranges(it, owner, ranges)
            _, parts = self.replication.fetch_ranges(
                holder,
                {"session": self.session, "iteration": it, "owner": owner,
                 "ranges": ranges},
            )
            return parts

        local_q = [seg for seg in rp.segments if assign(seg)]
        t0 = time.perf_counter()
        fetches = 0
        pool = None
        workers = 0
        try:
            while local_q or remote:
                batches = sorted(remote.items())
                remote.clear()
                futs = []
                if batches:
                    if pool is None:
                        workers = min(self._reshard_io_threads(), len(batches))
                        pool = concurrent.futures.ThreadPoolExecutor(
                            max_workers=max(1, workers),
                            thread_name_prefix="reshard-fetch",
                        )
                    futs = [
                        ((h, o), segs, pool.submit(fetch_batch, h, o, segs))
                        for (h, o), segs in batches
                    ]
                    fetches += len(futs)
                # Local pread/assembly overlaps the in-flight fetches.
                while local_q:
                    seg = local_q.pop(0)
                    if not read_local(seg):
                        # All held copies failed — their owners were just
                        # discarded, so assign() now routes this to a peer
                        # (fetched next round).
                        assign(seg)
                for (holder, owner), segs, fut in futs:
                    try:
                        parts = fut.result()
                    except CheckpointError as e:
                        log.warning(
                            f"rank {self.rank}: reshard fetch from "
                            f"{'cold tier' if holder < 0 else f'holder {holder}'}"
                            f" (owner {owner}) failed: {e}; trying "
                            f"another source"
                        )
                        record_event(
                            "checkpoint", "ckpt_integrity_failure",
                            stage="cold-reshard-fetch" if holder < 0
                            else "reshard-fetch",
                            iteration=it, owner=owner,
                            rank=self.rank, error=repr(e),
                        )
                        if holder < 0:
                            dead_cold.add(owner)
                        else:
                            dead.add(holder)
                        for seg in segs:
                            if assign(seg):
                                local_q.append(seg)
                        continue
                    i = 0
                    nbytes = 0
                    for seg in segs:
                        for r in seg.ranges:
                            buf = memoryview(parts[i]).cast("B")
                            i += 1
                            if buf.nbytes != r.nbytes:
                                raise CheckpointError(
                                    f"reshard: holder {holder} returned "
                                    f"{buf.nbytes} bytes for a "
                                    f"{r.nbytes}-byte range"
                                )
                            flats[seg.leaf][r.dst_off : r.dst_off + r.nbytes] = (
                                np.frombuffer(buf, dtype=np.uint8)
                            )
                            nbytes += r.nbytes
                    record_event(
                        "checkpoint", "reshard_fetch",
                        via="cold" if holder < 0 else "peer",
                        rank=self.rank, iteration=it, holder=holder,
                        owner=owner, bytes=nbytes,
                    )
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
        if local_bytes:
            record_event(
                "checkpoint", "reshard_fetch", via="local", rank=self.rank,
                iteration=it, bytes=local_bytes,
            )
        if fetches:
            record_event(
                "checkpoint", "reshard_overlap", rank=self.rank, iteration=it,
                fetches=fetches, workers=workers, local_bytes=local_bytes,
                duration_s=time.perf_counter() - t0,
            )
        return buffers

    # -- lifecycle ---------------------------------------------------------

    def maybe_finalize(self, blocking: bool = False) -> list[int]:
        return self.queue.maybe_finalize_async_calls(blocking=blocking)

    def close(self) -> None:
        # NOTE: the ranged-read registration outlives close() on purpose —
        # serving only needs the shard files, and a peer mid-reshard must not
        # lose its source because this rank assembled (and closed) first. The
        # registration dies with the exchange.
        self.queue.close()

    def wipe(self) -> None:
        """Remove this rank's local checkpoint directory (tests / teardown)."""
        shutil.rmtree(self._dir, ignore_errors=True)
        os.makedirs(self._dir, exist_ok=True)
