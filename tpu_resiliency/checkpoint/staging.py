"""Host staging buffer pool for pipelined checkpoint snapshots.

Every async save needs a full-model-size set of host arrays to land the D2H
copies in. Allocating them fresh per save (what ``jax.device_get`` does) costs
an allocator round trip plus first-touch page faults over the whole payload on
EVERY checkpoint interval — the reference amortizes this with pinned-memory
tensors it reuses across saves (``checkpointing/utils.py:85``). This pool is
the TPU-host analogue: buffers are keyed by the tree's **leaf signature**
(shape/dtype per leaf, in pop order) and recycled across saves, so the
steady-state save performs no large host allocations at all.

Double buffering is the default (``depth=2``): save N+1 can acquire a second
buffer set while save N's background half is still writing/replicating out of
the first, so the train loop never waits on the previous save's IO to reclaim
staging memory. A third concurrent save of the same signature blocks in
``acquire`` until a lease frees — bounding staging memory at
``depth × tree_bytes`` instead of growing with queue depth.

Leaf views are aligned, typed numpy windows over one contiguous backing
``bytearray`` per lease, ready to feed the zero-copy
``format.serialize_parts`` / ``PeerExchange.send_parts`` path without any
fresh per-leaf arrays. Pool traffic is narrated to the event stream
(``staging_pool`` records → ``tpu_ckpt_staging_pool_bytes`` gauge and
``tpu_ckpt_staging_requests_total{outcome}``).
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

import numpy as np

from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: Leaf offsets within a lease's backing buffer are rounded up to this, so every
#: staged view is cacheline/SIMD aligned regardless of its neighbors' sizes.
_ALIGN = 64


def leaf_signature(specs: Sequence[dict]) -> tuple:
    """Hashable pool key for a leaf-spec list (shape + dtype per leaf, in order)."""
    return tuple((tuple(s["shape"]), str(s["dtype"]), int(s["nbytes"])) for s in specs)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class StagingLease:
    """One leased buffer set: typed views + raw uint8 windows over one backing
    bytearray. Release returns it to the pool (idempotent); the views must not
    be used after release — the next save of the same signature will overwrite
    them."""

    def __init__(self, pool: "HostStagingPool", key: tuple, backing: np.ndarray):
        from tpu_resiliency.checkpoint.format import resolve_dtype

        self._pool = pool
        self.key = key
        self._backing = backing
        self.views: list[np.ndarray] = []
        self.raw_views: list[memoryview] = []
        # The backing allocation's payload is not 64-aligned; skew the first
        # offset so every leaf view lands on an aligned ADDRESS (the buffer is
        # overallocated by one alignment quantum for exactly this).
        base_addr = backing.__array_interface__["data"][0]
        mv = memoryview(backing)
        off = (-base_addr) % _ALIGN
        for shape, dtype, nbytes in key:
            window = mv[off : off + nbytes]
            self.raw_views.append(window)
            self.views.append(
                np.frombuffer(window, dtype=resolve_dtype(dtype)).reshape(shape)
            )
            off += _aligned(nbytes)
        self.nbytes = sum(n for _, _, n in key)
        self._released = False

    def fill(self, index: int, arr: Any) -> np.ndarray:
        """Copy one host leaf into its staged window; returns the staged typed
        view. Same-dtype copies go through ``np.copyto`` — numpy's raw array
        assignment drops the GIL for the memcpy, so background staging never
        stalls the train-loop thread — with a raw uint8 fallback for any
        dtype/layout combination numpy refuses."""
        src = np.asarray(arr)
        dst = self.views[index]
        if src.nbytes != dst.nbytes:
            raise CheckpointError(
                f"staging lease leaf {index}: got {src.nbytes} B, "
                f"signature says {dst.nbytes} B"
            )
        try:
            np.copyto(dst, src, casting="no")
        except (TypeError, ValueError):
            from tpu_resiliency.checkpoint.format import _raw_view

            self.raw_views[index][:] = _raw_view(src)
        return dst

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._pool._release(self.key, self._backing)


class HostStagingPool:
    """Signature-keyed pool of reusable host snapshot buffers.

    ``acquire(specs)`` returns a :class:`StagingLease` — a pooled buffer on a
    hit, a freshly allocated one while fewer than ``depth`` leases of that
    signature exist, and otherwise blocks until a lease releases (``timeout``
    seconds, then :class:`CheckpointError`). Thread-safe; leases release from
    background writer threads.
    """

    def __init__(self, depth: int = 2, timeout: float = 600.0):
        if depth < 1:
            raise ValueError("staging pool depth must be >= 1")
        self.depth = depth
        self.timeout = timeout
        self._cond = threading.Condition()
        self._free: dict[tuple, list[np.ndarray]] = {}
        self._count: dict[tuple, int] = {}
        #: cumulative stats — the pool-hit acceptance check reads these
        self.hits = 0
        self.misses = 0
        self.total_bytes = 0
        self.in_use_bytes = 0

    def _lease_bytes(self, key: tuple) -> int:
        # One extra alignment quantum: the lease skews its first offset so leaf
        # views sit on 64-aligned addresses regardless of the bytearray's base.
        return sum(_aligned(n) for _, _, n in key) + _ALIGN

    def acquire(
        self, specs: Sequence[dict], timeout: Optional[float] = None
    ) -> StagingLease:
        key = leaf_signature(specs)
        need = self._lease_bytes(key)
        deadline = None
        outcome = "hit"
        with self._cond:
            while True:
                free = self._free.get(key)
                if free:
                    backing = free.pop()
                    break
                if self._count.get(key, 0) < self.depth:
                    # np.empty, not bytearray: no O(bytes) zeroing on the miss
                    # path (pages fault in lazily as fill() first touches
                    # them). Misses run once per signature per depth slot —
                    # never steady state.
                    backing = np.empty(need, dtype=np.uint8)
                    self._count[key] = self._count.get(key, 0) + 1
                    self.total_bytes += need
                    outcome = "miss"
                    break
                if deadline is None:
                    import time as _time

                    deadline = _time.monotonic() + (
                        self.timeout if timeout is None else timeout
                    )
                    outcome = "wait"
                import time as _time

                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise CheckpointError(
                        f"staging pool: all {self.depth} buffer(s) for this tree "
                        f"signature still leased after {self.timeout if timeout is None else timeout}s "
                        f"(previous saves' background halves have not released)"
                    )
                self._cond.wait(timeout=min(remaining, 1.0))
            if outcome == "miss":
                self.misses += 1
            else:
                self.hits += 1
            self.in_use_bytes += need
            pool_bytes, in_use = self.total_bytes, self.in_use_bytes
        record_event(
            "checkpoint", "staging_pool",
            outcome=outcome, nbytes=need, pool_bytes=pool_bytes,
            in_use_bytes=in_use,
        )
        return StagingLease(self, key, backing)

    def _release(self, key: tuple, backing: np.ndarray) -> None:
        with self._cond:
            self._free.setdefault(key, []).append(backing)
            self.in_use_bytes -= self._lease_bytes(key)
            pool_bytes, in_use = self.total_bytes, self.in_use_bytes
            self._cond.notify_all()
        record_event(
            "checkpoint", "staging_pool",
            outcome="release", nbytes=self._lease_bytes(key),
            pool_bytes=pool_bytes, in_use_bytes=in_use,
        )

    def trim(self) -> int:
        """Drop every idle buffer (e.g. after the tree signature changed for
        good — a resharding restart). Returns bytes freed; leased buffers are
        untouched and rejoin the pool on release."""
        with self._cond:
            freed = 0
            for key, bufs in self._free.items():
                freed += self._lease_bytes(key) * len(bufs)
                self._count[key] = self._count.get(key, 0) - len(bufs)
            self._free.clear()
            self.total_bytes -= freed
        if freed:
            log.info(f"staging pool trimmed {freed} idle bytes")
        return freed

    def stats(self) -> dict:
        with self._cond:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "total_bytes": self.total_bytes,
                "in_use_bytes": self.in_use_bytes,
                "signatures": len(self._count),
            }
