"""Asynchronous checkpoint execution: requests, callers, and the finalization queue.

Re-design of the reference's async core (``checkpointing/async_ckpt/core.py``):
``AsyncRequest`` (``core.py:37``), ``TemporalAsyncCaller`` fork-per-save
(``core.py:176-276``), ``PersistentAsyncCaller`` spawn-once worker (``core.py:279-473``),
and ``AsyncCallsQueue`` with its distributed is-done agreement (``core.py:152-164``) and
finalize-on-all-ranks step (``core.py:541-570``).

TPU-first changes:

- **Default caller is a thread, not a fork.** Forking a process that holds a live TPU
  runtime client is unsafe (the child inherits device handles it must never touch). By
  the time a request is scheduled the payload is already host numpy (see
  ``PyTreeStateDict.copy_tensors_to_host``), and file writes release the GIL, so a
  daemon thread gets fork-level overlap without the hazard.
- **Process caller uses spawn, started eagerly.** The spawn-once persistent worker
  (started before any request, so it inherits nothing) matches the reference's
  ``PersistentAsyncCaller``; payloads cross via the queue, which is why the thread
  caller is the default — use the process caller when GIL contention in the trainer
  matters more than the one extra copy.
- **Distributed agreement is pluggable.** The reference all-reduces ``is_alive`` over
  NCCL/Gloo; here any callable ``(bool) -> bool`` works — the store-backed group comm
  (``checkpoint/comm.py``) provides one; single-process callers pass nothing.
"""

from __future__ import annotations

import dataclasses
import multiprocessing
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Optional, Sequence

from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class AsyncRequest:
    """A checkpoint save split into an async part and on-all-ranks finalization.

    Mirrors reference ``core.py:37-123``: ``async_fn(*async_fn_args)`` runs in the
    background caller; ``finalize_fns`` run synchronously on every rank once all ranks'
    async parts are done; ``preload_fn`` (if any) runs synchronously *before* the async
    part is scheduled (D2H staging).

    ``cleanup_fns`` run in the SAME context as the async part, immediately after
    it, on success AND on failure — resource reclamation that must not depend on
    finalization happening (a staging-lease release must fire even when the save
    failed or the queue was ``abandon``\\ ed, or the pool leaks a full-tree
    buffer per incident). Process/fork callers require them picklable, like
    ``async_fn`` itself.
    """

    async_fn: Optional[Callable]
    async_fn_args: tuple = ()
    async_fn_kwargs: dict = dataclasses.field(default_factory=dict)
    finalize_fns: tuple = ()
    preload_fn: Optional[Callable] = None
    cleanup_fns: tuple = ()

    def add_finalize_fn(self, fn: Callable) -> "AsyncRequest":
        return dataclasses.replace(self, finalize_fns=tuple(self.finalize_fns) + (fn,))

    def run_async_part(self) -> None:
        """``async_fn`` then ``cleanup_fns`` (unconditionally) — the one body
        every caller executes in its background context."""
        try:
            if self.async_fn is not None:
                self.async_fn(*self.async_fn_args, **self.async_fn_kwargs)
        finally:
            for fn in self.cleanup_fns:
                try:
                    fn()
                except Exception:
                    log.warning("async-save cleanup_fn failed", exc_info=True)

    def execute_sync(self) -> None:
        """Debug/fallback path: run everything inline."""
        if self.preload_fn is not None:
            self.preload_fn()
        self.run_async_part()
        for fn in self.finalize_fns:
            fn()


class AsyncCaller:
    """Interface: run an async_fn in the background, poll or await completion."""

    def schedule(self, req: AsyncRequest) -> None:
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def wait(self, timeout: Optional[float] = None) -> bool:
        raise NotImplementedError

    def raise_if_failed(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ThreadAsyncCaller(AsyncCaller):
    """One daemon thread per scheduled save (the TPU-safe default)."""

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def schedule(self, req: AsyncRequest) -> None:
        if self._thread is not None and self._thread.is_alive():
            raise CheckpointError("previous async save still running")
        self._error = None

        def run() -> None:
            try:
                req.run_async_part()
            except BaseException as e:  # propagated from raise_if_failed
                self._error = e

        self._thread = threading.Thread(target=run, name="ckpt-async-save", daemon=True)
        self._thread.start()

    def is_done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def raise_if_failed(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError(f"async checkpoint save failed: {err!r}") from err


def _worker_loop(req_q, done_q) -> None:
    """Persistent spawn-worker body (module-level for picklability)."""
    while True:
        item = req_q.get()
        if item is None:
            return
        idx, fn, args, kwargs, cleanups = item
        try:
            try:
                fn(*args, **kwargs)
            finally:
                for c in cleanups:
                    try:
                        c()
                    except Exception:
                        pass
            done_q.put((idx, None))
        except BaseException as e:
            done_q.put((idx, repr(e)))


class ProcessAsyncCaller(AsyncCaller):
    """Spawn-once persistent worker process (reference ``PersistentAsyncCaller``).

    Started eagerly at construction — before the parent accumulates TPU state worth
    worrying about — and fed via a queue. ``async_fn`` and its args must be picklable.
    """

    def __init__(self) -> None:
        ctx = multiprocessing.get_context("spawn")
        self._req_q = ctx.Queue()
        self._done_q = ctx.Queue()
        self._proc = ctx.Process(
            target=_worker_loop, args=(self._req_q, self._done_q), daemon=True
        )
        self._proc.start()
        self._next_idx = 0
        self._pending: Optional[int] = None
        self._error: Optional[str] = None

    def schedule(self, req: AsyncRequest) -> None:
        if self._pending is not None:
            raise CheckpointError("previous async save still running")
        if not self._proc.is_alive():
            raise CheckpointError("checkpoint worker process died")
        idx = self._next_idx
        self._next_idx += 1
        self._req_q.put(
            (idx, req.async_fn, req.async_fn_args, req.async_fn_kwargs,
             tuple(req.cleanup_fns))
        )
        self._pending = idx

    def _drain(self, timeout: Optional[float]) -> None:
        if self._pending is None:
            return
        try:
            idx, err = self._done_q.get(timeout=timeout)
        except queue_mod.Empty:
            return
        if idx == self._pending:
            self._pending = None
            self._error = err

    def is_done(self) -> bool:
        self._drain(timeout=0.0 if self._pending is not None else None)
        return self._pending is None

    def wait(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._pending is not None:
            if not self._proc.is_alive():
                self._pending = None
                self._error = "checkpoint worker process died"
                break
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            self._drain(timeout=min(0.5, remaining) if remaining is not None else 0.5)
            if remaining is not None and remaining <= 0:
                break
        return self._pending is None

    def raise_if_failed(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError(f"async checkpoint save failed in worker: {err}")

    def close(self) -> None:
        try:
            self._req_q.put(None)
            self._proc.join(timeout=5.0)
            if self._proc.is_alive():
                self._proc.terminate()
        except (ValueError, OSError):
            pass


def _jax_backend_alive() -> bool:
    """True when this process holds an initialized JAX backend client (without
    triggering initialization by asking)."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        # Fail CLOSED: jax is imported but the (private) probe broke — assume a
        # backend may be live rather than silently disabling the guard.
        log.warning(
            "could not probe JAX backend state; treating it as initialized",
            exc_info=True,
        )
        return True


class ForkAsyncCaller(AsyncCaller):
    """Fork-per-save (reference ``TemporalAsyncCaller``). Zero-copy via COW.

    Only safe when the parent holds **no live TPU runtime** (e.g. a CPU-host data
    orchestrator) — forking a process with an initialized accelerator client is
    undefined behavior (runtime threads and device handles are duplicated into a
    child that never reaps them). ``schedule`` therefore REFUSES to fork once a
    JAX backend is initialized in this process, unless constructed with
    ``unsafe_allow_fork_with_backend=True`` (you own the consequences; CPU-only
    backends mostly tolerate it). Provided for parity; the thread caller is the
    default.
    """

    def __init__(self, unsafe_allow_fork_with_backend: bool = False) -> None:
        self._proc: Optional[multiprocessing.Process] = None
        self._failed = False
        self._allow_backend = unsafe_allow_fork_with_backend

    def schedule(self, req: AsyncRequest) -> None:
        if self._proc is not None and self._proc.is_alive():
            raise CheckpointError("previous async save still running")
        if not self._allow_backend and _jax_backend_alive():
            raise CheckpointError(
                "refusing to fork a checkpoint writer: this process holds an "
                "initialized JAX backend (forking duplicates runtime threads and "
                "device handles — undefined behavior). Use caller='thread' or "
                "'process' (spawn), or opt in with caller='fork_unsafe' / "
                "ForkAsyncCaller(unsafe_allow_fork_with_backend=True)."
            )
        ctx = multiprocessing.get_context("fork")
        self._proc = ctx.Process(
            target=req.run_async_part,
            daemon=True,
            name="ckpt-fork-save",
        )
        self._failed = False
        self._proc.start()

    def is_done(self) -> bool:
        return self._proc is None or not self._proc.is_alive()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._proc is None:
            return True
        self._proc.join(timeout)
        done = not self._proc.is_alive()
        if done and self._proc.exitcode not in (0, None):
            self._failed = True
        return done

    def raise_if_failed(self) -> None:
        if self._proc is not None and not self._proc.is_alive():
            if self._proc.exitcode not in (0, None) or self._failed:
                code = self._proc.exitcode
                self._failed = False
                raise CheckpointError(f"forked checkpoint save exited with code {code}")


_CALLERS = {
    "thread": ThreadAsyncCaller,
    "process": ProcessAsyncCaller,
    "fork": ForkAsyncCaller,
    # The escape hatch, reachable through the string-registry surface too:
    # AsyncCallsQueue(caller="fork_unsafe") forks even over a live JAX backend.
    "fork_unsafe": lambda: ForkAsyncCaller(unsafe_allow_fork_with_backend=True),
}


@dataclasses.dataclass
class _ActiveCall:
    idx: int
    request: AsyncRequest
    caller: AsyncCaller
    start_time: float


class AsyncCallsQueue:
    """FIFO of in-flight async saves with distributed finalization.

    Mirrors reference ``AsyncCallsQueue`` (``core.py:491-580``): saves finalize in
    schedule order; a save finalizes only when **all ranks** report it done (so no rank
    observes a checkpoint as complete while a peer is still writing), after which its
    ``finalize_fns`` run on every rank.

    ``sync_fn(local_done: bool) -> bool`` implements the cross-rank agreement (the
    reference's 1-int all-reduce of ``is_alive``, ``core.py:152-164``); ``None`` means
    single-rank operation.
    """

    def __init__(
        self,
        caller: str = "thread",
        sync_fn: Optional[Callable[[bool], bool]] = None,
        persistent: bool = False,
    ):
        if caller not in _CALLERS:
            raise ValueError(f"unknown caller {caller!r}; one of {sorted(_CALLERS)}")
        self._caller_kind = caller
        self._persistent_caller: Optional[AsyncCaller] = (
            _CALLERS[caller]() if persistent or caller == "process" else None
        )
        self._sync_fn = sync_fn
        self._active: list[_ActiveCall] = []
        self._next_idx = 0

    @property
    def num_unfinalized_calls(self) -> int:
        return len(self._active)

    @property
    def unfinalized_indices(self) -> list[int]:
        """Schedule indices still in flight (FIFO order) — lets callers track
        per-request bookkeeping across finalize/failure paths without guessing
        which indices the last finalize consumed."""
        return [c.idx for c in self._active]

    def schedule_async_request(self, req: AsyncRequest) -> int:
        """Run preload synchronously, then hand the async part to a caller."""
        if req.preload_fn is not None:
            req.preload_fn()
        caller = self._persistent_caller or _CALLERS[self._caller_kind]()
        if self._persistent_caller is not None and self._active:
            # A persistent caller runs one save at a time; wait out the previous one.
            self.maybe_finalize_async_calls(blocking=True)
        caller.schedule(req)
        idx = self._next_idx
        self._next_idx += 1
        self._active.append(_ActiveCall(idx, req, caller, time.monotonic()))
        return idx

    def _call_done(self, call: _ActiveCall, blocking: bool) -> bool:
        local_done = call.caller.wait(None) if blocking else call.caller.is_done()
        if self._sync_fn is not None:
            # All ranks must agree; a blocking caller that is locally done may still
            # need to wait for peers, which the sync_fn's own loop handles.
            return bool(self._sync_fn(local_done))
        return local_done

    def maybe_finalize_async_calls(self, blocking: bool = False) -> list[int]:
        """Finalize completed saves in FIFO order; returns finalized indices."""
        finalized: list[int] = []
        while self._active:
            call = self._active[0]
            if not self._call_done(call, blocking):
                break
            try:
                call.caller.raise_if_failed()
            except Exception:
                # A failed save must not stay queued: the next poll would see it done
                # with its error already consumed and finalize it as a success.
                self._active.pop(0)
                if call.caller is not self._persistent_caller:
                    call.caller.close()
                raise
            for fn in call.request.finalize_fns:
                fn()
            if call.caller is not self._persistent_caller:
                call.caller.close()
            self._active.pop(0)
            finalized.append(call.idx)
        return finalized

    def finalize_all(self) -> list[int]:
        return self.maybe_finalize_async_calls(blocking=True)

    def set_sync_fn(self, sync_fn: Optional[Callable[[bool], bool]]) -> None:
        """Swap the cross-rank agreement function (after the rank group changed).

        Only legal with no in-flight saves: a pending save's agreement was
        entered against the OLD group and must not finalize against the new one
        — :meth:`abandon` first.
        """
        if self._active:
            raise CheckpointError(
                f"{len(self._active)} in-flight saves were scheduled against the "
                "previous rank group; abandon() or finalize them before swapping "
                "sync_fn"
            )
        self._sync_fn = sync_fn

    def abandon(self) -> list[int]:
        """Drop queued saves WITHOUT the collective finalization — for restart
        paths where the group the saves were scheduled against no longer exists
        (dead peers would hang the agreement; a new-world agreement would judge
        the old iteration uncovered). Local async work (file writes) is waited
        out so shards land on disk; coverage verification and pruning are
        skipped — the next successful save re-establishes both. Returns the
        abandoned indices."""
        abandoned: list[int] = []
        while self._active:
            call = self._active.pop(0)
            try:
                call.caller.wait(None)
                call.caller.raise_if_failed()
            except Exception as e:
                log.warning(f"abandoned save {call.idx} had failed locally: {e!r}")
            finally:
                if call.caller is not self._persistent_caller:
                    call.caller.close()
            abandoned.append(call.idx)
        if abandoned:
            log.info(f"abandoned {len(abandoned)} in-flight saves (group change)")
        return abandoned

    def close(self) -> None:
        self.finalize_all()
        if self._persistent_caller is not None:
            self._persistent_caller.close()
