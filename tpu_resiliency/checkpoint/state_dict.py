"""Tensor-aware state dicts: split a pytree into payload arrays and a hollow skeleton.

TPU-native re-design of the reference's ``TensorAwareStateDict`` contract
(``checkpointing/local/base_state_dict.py:29-115``) and its ``BasicTensorAwareStateDict``
implementation (``checkpointing/local/basic_state_dict.py:57-188``). The reference walks
nested torch dicts; here the natural unit is a **JAX pytree**: any nested structure of
params / optimizer state / step counters. ``pop_tensors`` swaps every array leaf for a
:class:`TensorPlaceholder`, leaving a picklable "hollow" skeleton that can ride the
control plane (replication metadata, IPC) while the payload arrays move through the fast
path (device→host DMA, raw file IO, peer sockets).

Device round-trip: ``copy_tensors_to_host`` performs one batched ``jax.device_get`` (a
single D2H DMA per leaf, queued together — the analogue of the reference's pinned-memory
``non_blocking=True`` D2H copies, ``checkpointing/utils.py:85``); shardings are recorded
so ``restore_tensor_device`` can ``jax.device_put`` each leaf back onto the same mesh
layout after a restart.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import numpy as np

from tpu_resiliency.exceptions import CheckpointError


@dataclasses.dataclass
class TensorPlaceholder:
    """Stands in for an array leaf inside a hollow pytree.

    Analogue of the reference's ``TensorPlaceholder``
    (``checkpointing/local/basic_state_dict.py:30-54``), extended with the leaf's
    sharding so the array can be restored to its mesh layout.
    """

    shape: tuple
    dtype: str
    index: int
    sharding: Any = None  # jax.sharding.Sharding | None; not pickled across hosts

    def __getstate__(self):
        # Shardings reference device objects that do not pickle across processes;
        # the restore side supplies shardings from its own mesh instead.
        return {
            "shape": self.shape,
            "dtype": self.dtype,
            "index": self.index,
            "sharding": None,
        }

    def __setstate__(self, state):
        for k, v in state.items():
            setattr(self, k, v)


def _is_array(leaf: Any) -> bool:
    import jax

    return isinstance(leaf, (jax.Array, np.ndarray)) and not np.isscalar(leaf)


def leaf_specs(tensors: Sequence[Any]) -> list[dict]:
    """Container-format leaf specs (shape/dtype/nbytes) straight from device
    arrays — no host copy, no blocking: the pipelined save pickles the header
    and sizes the staging lease before any D2H byte has landed."""
    specs = []
    for t in tensors:
        dt = np.dtype(t.dtype)
        nbytes = int(np.prod(t.shape, dtype=np.int64)) * dt.itemsize
        specs.append({"shape": tuple(t.shape), "dtype": dt.name, "nbytes": nbytes})
    return specs


class HostSnapshot:
    """Leaf-by-leaf D2H resolver: the handle the pipelined save's background
    half consumes.

    Created by :meth:`PyTreeStateDict.copy_tensors_to_host_async`, which has
    already enqueued every leaf's ``copy_to_host_async()`` — all DMAs are in
    flight before this object reaches the background thread. ``resolve(i)``
    blocks only until leaf ``i``'s transfer lands (the analogue of the
    reference's per-tensor pinned-memory D2H events), stages it into the
    pooled lease when one is attached, and drops the device reference so
    device memory frees as the pipeline advances. Single-consumer: the
    background writer resolves leaves in order; no internal locking.
    """

    def __init__(self, tensors: Sequence[Any], pool: Any = None):
        self._tensors: list = list(tensors)
        self.specs = leaf_specs(self._tensors)
        self.nbytes = sum(s["nbytes"] for s in self.specs)
        #: Lease acquisition is LAZY (first resolve, i.e. on the background
        #: thread): the foreground enqueue path never pays the miss-path
        #: allocation, nor blocks when both double-buffer slots are still
        #: leased to earlier saves' background halves.
        self._pool = pool
        self._lease = None
        self._released = False
        self._resolved: list[Optional[np.ndarray]] = [None] * len(self._tensors)

    def __len__(self) -> int:
        return len(self._resolved)

    def _ensure_lease(self):
        if self._lease is None and self._pool is not None and not self._released:
            self._lease = self._pool.acquire(self.specs)
        return self._lease

    def resolve(self, i: int) -> np.ndarray:
        """Materialize leaf ``i`` on host (blocking only on ITS transfer)."""
        out = self._resolved[i]
        if out is None:
            t = self._tensors[i]
            lease = self._ensure_lease()
            if lease is not None:
                out = lease.fill(i, t)
            else:
                out = np.asarray(t)
            self._resolved[i] = out
            self._tensors[i] = None
        return out

    def resolve_view(self, i: int) -> memoryview:
        """Leaf ``i`` as the flat uint8 window writers and senders consume."""
        self.resolve(i)
        if self._lease is not None:
            return self._lease.raw_views[i]
        from tpu_resiliency.checkpoint.format import _raw_view

        return _raw_view(self._resolved[i])

    def resolve_all(self) -> list[np.ndarray]:
        return [self.resolve(i) for i in range(len(self))]

    def __iter__(self):
        for i in range(len(self)):
            yield self.resolve(i)

    def release(self) -> None:
        """Return the staging lease to its pool (idempotent). Call only after
        every consumer (file writer, peer sends) is done with the views."""
        self._released = True
        if self._lease is not None:
            self._lease.release()
            self._lease = None


class PyTreeStateDict:
    """A pytree with pop/insert tensor semantics for local checkpointing.

    Contract (mirrors reference ``base_state_dict.py:29-115``):

    - ``pop_tensors()`` → list of array leaves; ``self`` becomes hollow (picklable).
    - ``insert_tensors(tensors)`` → re-inflates the hollow skeleton.
    - ``copy_tensors_to_host()`` → payload becomes numpy (one batched D2H).
    - ``restore_tensor_device(shardings=...)`` → payload becomes device arrays again.
    - ``tree`` → the underlying pytree (hollow or full).
    """

    def __init__(self, tree: Any):
        self._tree = tree
        self._hollow = False
        self._tensors: Optional[list] = None
        self._shardings: Optional[list] = None

    @classmethod
    def from_hollow(
        cls,
        hollow_tree: Any,
        tensors: Sequence[Any],
        shardings: Optional[Sequence[Any]] = None,
        device: Any = None,
    ) -> "PyTreeStateDict":
        """Rebuild a full state dict from a loaded (hollow skeleton, payload) pair,
        placing tensors back on device — the standard restore path after
        ``LocalCheckpointManager.load`` / ``ckpt_format.read_payload``."""
        sd = cls.__new__(cls)
        sd._tree = hollow_tree
        sd._hollow = True
        sd._tensors = list(tensors)
        sd._shardings = None
        sd.restore_tensor_device(shardings=shardings, device=device)
        sd.insert_tensors(sd._tensors)
        return sd

    # -- introspection -----------------------------------------------------

    @property
    def is_hollow(self) -> bool:
        return self._hollow

    @property
    def tree(self) -> Any:
        if self._hollow:
            raise CheckpointError("state dict is hollow; insert_tensors() first")
        return self._tree

    @property
    def hollow_tree(self) -> Any:
        if not self._hollow:
            raise CheckpointError("state dict is not hollow; pop_tensors() first")
        return self._tree

    def tensors(self) -> list:
        if self._tensors is None:
            raise CheckpointError("tensors were not popped")
        return self._tensors

    # -- pop / insert ------------------------------------------------------

    def pop_tensors(self) -> list:
        """Replace every array leaf with a placeholder; return the arrays in order."""
        import jax

        if self._hollow:
            raise CheckpointError("pop_tensors() on an already-hollow state dict")
        leaves, treedef = jax.tree_util.tree_flatten(self._tree)
        tensors: list = []
        hollow_leaves: list = []
        for leaf in leaves:
            if _is_array(leaf):
                sharding = getattr(leaf, "sharding", None)
                hollow_leaves.append(
                    TensorPlaceholder(
                        shape=tuple(leaf.shape),
                        dtype=str(leaf.dtype),
                        index=len(tensors),
                        sharding=sharding,
                    )
                )
                tensors.append(leaf)
            else:
                hollow_leaves.append(leaf)
        self._tree = jax.tree_util.tree_unflatten(treedef, hollow_leaves)
        self._tensors = tensors
        self._hollow = True
        return tensors

    def insert_tensors(self, tensors: Sequence[Any]) -> None:
        """Inverse of :meth:`pop_tensors`."""
        import jax

        if not self._hollow:
            raise CheckpointError("insert_tensors() on a non-hollow state dict")
        leaves, treedef = jax.tree_util.tree_flatten(
            self._tree, is_leaf=lambda x: isinstance(x, TensorPlaceholder)
        )
        n_ph = sum(isinstance(leaf, TensorPlaceholder) for leaf in leaves)
        if n_ph != len(tensors):
            raise CheckpointError(f"expected {n_ph} tensors, got {len(tensors)}")
        # A hollow skeleton that deserialized but carries out-of-range indices
        # (a corrupt-but-unpicklable-looking v1 container, a hand-built tree)
        # must fail as a classified checkpoint error, not an IndexError.
        bad = [
            leaf.index
            for leaf in leaves
            if isinstance(leaf, TensorPlaceholder)
            and not 0 <= leaf.index < len(tensors)
        ]
        if bad:
            raise CheckpointError(
                f"hollow skeleton placeholder index(es) {sorted(bad)} out of "
                f"range for {len(tensors)} tensors (corrupt skeleton?)"
            )
        full = [
            tensors[leaf.index] if isinstance(leaf, TensorPlaceholder) else leaf
            for leaf in leaves
        ]
        self._tree = jax.tree_util.tree_unflatten(treedef, full)
        self._tensors = list(tensors)
        self._hollow = False

    # -- device movement ---------------------------------------------------

    def copy_tensors_to_host(self) -> None:
        """One batched D2H transfer; payload becomes numpy, shardings recorded."""
        import jax

        if self._tensors is None:
            raise CheckpointError("pop_tensors() before copy_tensors_to_host()")
        self._shardings = [getattr(t, "sharding", None) for t in self._tensors]
        # device_get on the whole list queues all transfers before blocking on any.
        self._tensors = [np.asarray(x) for x in jax.device_get(self._tensors)]

    def copy_tensors_to_host_async(self, pool: Any = None) -> HostSnapshot:
        """Non-blocking counterpart of :meth:`copy_tensors_to_host`: enqueue
        every leaf's D2H DMA and return a :class:`HostSnapshot` that resolves
        leaves as their transfers complete.

        The caller-visible cost is "enqueue": one ``copy_to_host_async()`` call
        per leaf (microseconds) instead of one barrier over the whole payload.
        ``pool`` (a :class:`~tpu_resiliency.checkpoint.staging.HostStagingPool`)
        stages resolved leaves into recycled buffers so steady-state saves
        allocate nothing large; the lease is acquired lazily at first resolve
        (on the background thread) and the snapshot owns it — ``release()``
        when the background half is done. ``self`` keeps its device tensors
        untouched (shardings are recorded for a later restore)."""
        if self._tensors is None:
            raise CheckpointError("pop_tensors() before copy_tensors_to_host_async()")
        self._shardings = [getattr(t, "sharding", None) for t in self._tensors]
        for t in self._tensors:
            start = getattr(t, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:
                    # Enqueue is an optimization; resolve() still blocks
                    # correctly on backends without the async entry point.
                    pass
        return HostSnapshot(self._tensors, pool=pool)

    def _align_shardings_pytree(self, shardings) -> list:
        """Flatten a shardings pytree that mirrors the saved tree's structure into a
        flat list aligned with the popped tensor order. Non-array leaves in the saved
        tree (e.g. a step counter) are allowed: their corresponding shardings-pytree
        entries are ignored."""
        import jax

        # None must count as a leaf on BOTH sides (it is jax's empty node by
        # default): in the saved tree it may be an optional field, in the
        # shardings pytree it means "default placement".
        is_ph = lambda x: isinstance(x, TensorPlaceholder) or x is None  # noqa: E731
        tree_leaves, tree_def = jax.tree_util.tree_flatten(self._tree, is_leaf=is_ph)
        sh_leaves, sh_def = jax.tree_util.tree_flatten(
            shardings, is_leaf=lambda x: x is None
        )
        if len(sh_leaves) != len(tree_leaves) or sh_def != tree_def:
            raise CheckpointError(
                f"shardings pytree does not mirror the saved tree — pass a pytree "
                f"with a Sharding/None at each saved-tree leaf, or a flat "
                f"per-tensor sequence.\n  shardings: {len(sh_leaves)} leaves, "
                f"{sh_def}\n  saved tree: {len(tree_leaves)} leaves, {tree_def}"
            )
        out: list = [None] * len(self._tensors)
        cursor = 0  # full-tree case: arrays appear in tree order == pop order
        for leaf, s in zip(tree_leaves, sh_leaves):
            if isinstance(leaf, TensorPlaceholder):
                out[leaf.index] = s
            elif _is_array(leaf):
                out[cursor] = s
                cursor += 1
        return out

    def restore_tensor_device(
        self,
        shardings: Optional[Sequence[Any]] = None,
        device: Any = None,
    ) -> None:
        """``jax.device_put`` the payload back (mesh shardings > explicit device > default).

        ``shardings`` may be a flat sequence of shardings (aligned with the popped
        tensor list) OR a pytree mirroring the saved tree's structure, with a
        ``Sharding`` or ``None`` (default placement) at each leaf."""
        import jax

        if self._tensors is None:
            raise CheckpointError("no tensors to restore")
        target = shardings if shardings is not None else self._shardings
        # Interpretation order for a list/tuple of placement-like entries
        # (Sharding, Device, None):
        #   1. length == popped-tensor count → the flat per-tensor form (exact);
        #   2. otherwise, a pytree mirroring a list-rooted saved tree → aligned
        #      structurally (handles non-array leaves interleaved with tensors);
        #   3. otherwise, the legacy flat form with prefix semantics (shorter
        #      lists pad the tail with default placement — the long-standing
        #      behavior of the `i < len(target)` guard below).
        # Any container with non-placement entries is always a mirrored pytree.
        if target is not None and not isinstance(target, (list, tuple)):
            target = self._align_shardings_pytree(target)
        elif target is not None:
            all_placement = all(
                s is None or isinstance(s, (jax.sharding.Sharding, jax.Device))
                for s in target
            )
            if not (all_placement and len(target) == len(self._tensors)):
                try:
                    target = self._align_shardings_pytree(target)
                except CheckpointError:
                    if not all_placement:
                        raise
                    # legacy flat prefix form; the guard below pads the tail
        out = []
        for i, t in enumerate(self._tensors):
            s = target[i] if target is not None and i < len(target) else None
            if s is not None:
                out.append(jax.device_put(t, s))
            elif device is not None:
                out.append(jax.device_put(t, device))
            else:
                out.append(jax.device_put(t))
        self._tensors = out
        if self._hollow:
            return
        # Payload already re-inserted: rebuild the tree with the new device arrays.
        self.insert_if_full()

    def insert_if_full(self) -> None:
        if not self._hollow and self._tensors is not None:
            # Re-thread device arrays through the tree by temporarily hollowing.
            tensors = self._tensors
            self.pop_tensors()
            self.insert_tensors(tensors)


def split_tree(tree: Any) -> tuple[PyTreeStateDict, list]:
    """Convenience: wrap + pop in one call. Returns (hollow wrapper, tensors)."""
    sd = PyTreeStateDict(tree)
    tensors = sd.pop_tensors()
    return sd, tensors


def tree_size_bytes(tensors: Sequence[Any]) -> int:
    total = 0
    for t in tensors:
        total += int(np.prod(t.shape)) * np.dtype(
            t.dtype if not hasattr(t.dtype, "name") else t.dtype.name
        ).itemsize
    return total


def sync_placeholder_shapes(hollow_tree: Any, tensors: Sequence[Any]) -> Any:
    """Update a hollow skeleton's placeholders to the ACTUAL payload geometry.

    After an elastic reshard (``local_manager.load_resharded``) the loaded
    skeleton's placeholders still describe the SAVING world's local blocks;
    the reassembled tensors are the TARGET world's. Shape-driven consumers —
    ``make_restore_shardings`` spec functions, shape assertions in user
    restore code — must see the target truth, so the reshard load path runs
    this before handing the skeleton out. In-place on the placeholders;
    returns ``hollow_tree`` for chaining."""
    import jax

    leaves = jax.tree_util.tree_flatten(
        hollow_tree, is_leaf=lambda x: isinstance(x, TensorPlaceholder)
    )[0]
    for leaf in leaves:
        if isinstance(leaf, TensorPlaceholder) and 0 <= leaf.index < len(tensors):
            t = tensors[leaf.index]
            leaf.shape = tuple(t.shape)
            leaf.dtype = np.dtype(getattr(t.dtype, "name", t.dtype)).name
    return hollow_tree


def make_restore_shardings(
    hollow: Any, spec_fn: Callable[[TensorPlaceholder], Any]
) -> list:
    """Build a sharding list for ``restore_tensor_device`` from a hollow skeleton."""
    import jax

    leaves = jax.tree_util.tree_flatten(
        hollow, is_leaf=lambda x: isinstance(x, TensorPlaceholder)
    )[0]
    placeholders = [leaf for leaf in leaves if isinstance(leaf, TensorPlaceholder)]
    placeholders.sort(key=lambda p: p.index)
    return [spec_fn(p) for p in placeholders]
