"""Single-file checkpoint container: pickled hollow skeleton + raw array payload.

The write path the reference implements with per-bucket writer processes over torch-DCP
files (``checkpointing/async_ckpt/filesystem_async.py:102-334``) collapses on TPU hosts
to: hollow metadata (small pickle) followed by each leaf's raw bytes, streamed
sequentially — large contiguous writes are how you saturate local NVMe, and the hollow /
payload split means the metadata can be read without touching the payload.

**Measured justification for single-stream (the reference fans out per-bucket
writers, ``filesystem_async.py:232-334,558``):** on this class of host storage,
writing a 1 GiB tree (fsync'd, warm, alternating runs —
``scripts/bench_ckpt_io.py``) measured single-stream at 0.30 GB/s median vs 0.16
GB/s for a 4-way thread fan-out: concurrent streams halve throughput by
interleaving what would be contiguous writes. Writes here are also already
asynchronous to the train loop (``async_core``), so writer parallelism buys no
step-time; it would only shorten the background window.

The capability exists anyway, behind the ``$TPU_RESILIENCY_CKPT_STRIPES``
storage-class knob (``stripes=`` on :func:`write_payload`/:func:`write_blob`):
N threads pwrite byte-balanced contiguous leaf groups at their final offsets in
the SAME container, so the striped file is byte-identical to the sequential one
and the read path never changes. Measured on this host (0.5 GiB, 64 leaves,
``scripts/bench_ckpt_io.py``): single-stream 0.59 GB/s vs 4-way striped 0.61
GB/s — a wash here, hence default 1; on striped NVMe arrays or parallel
filesystems re-run the script and set the env for the measured winner.

Atomicity follows the reference's ``.dirty``-then-rename protocol
(``checkpointing/local/ckpt_managers/local_manager.py:110-131``): write to
``<path>.dirty``, fsync, ``os.replace``. A crash leaves only ``.dirty`` files, which
cleanup removes; a visible file is always complete.

Layout::

    MAGIC(8) | header_len(8 LE) | header pickle | leaf 0 bytes | leaf 1 bytes | ...

Header: ``{"hollow": bytes, "leaves": [{"shape", "dtype", "nbytes"}, ...], "meta": {}}``.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, Optional, Sequence

import numpy as np

from tpu_resiliency.exceptions import CheckpointError

MAGIC = b"TPURES01"
_LEN = struct.Struct("<Q")
DIRTY_SUFFIX = ".dirty"

#: Storage-class knob for writer parallelism (reference analogue: per-bucket
#: writer fan-out, ``filesystem_async.py:232-334``). Default 1: on this class of
#: host storage one stream saturates the device and a fan-out HALVES throughput
#: (measured, see module docstring). Set >1 only after ``scripts/bench_ckpt_io.py``
#: shows a win on the target storage (striped NVMe arrays, parallel filesystems).
STRIPES_ENV = "TPU_RESILIENCY_CKPT_STRIPES"


def _effective_stripes(stripes: Optional[int]) -> int:
    if stripes is None:
        try:
            stripes = int(os.environ.get(STRIPES_ENV, "1"))
        except ValueError:
            stripes = 1
    return max(1, int(stripes))


def _commit_atomic(tmp: str, path: str, fsync: bool) -> None:
    """The ``.dirty``-then-rename commit tail shared by every writer: make the
    file visible only complete, and persist the rename itself."""
    os.replace(tmp, path)
    if fsync:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


def _pwrite_full(fd: int, view: memoryview, offset: int) -> None:
    while view.nbytes:
        n = os.pwrite(fd, view, offset)
        view = view[n:]
        offset += n


def _partition_by_bytes(arrays, stripes: int):
    """Equal BYTE ranges of the concatenated payload: ``[(offset, view), ...]``
    per stripe. Ranges ignore leaf boundaries (pwrite only sees bytes), so the
    knob works even when one huge fused-parameter leaf dominates the payload —
    whole-leaf grouping would leave every other writer idle."""
    total = sum(a.nbytes for a in arrays)
    bounds = [total * k // stripes for k in range(stripes + 1)]
    groups: list[list[tuple[int, memoryview]]] = [[] for _ in range(stripes)]
    off = 0
    k = 0
    for a in arrays:
        view = _raw_view(a)
        start, end = off, off + a.nbytes
        while start < end:
            while bounds[k + 1] <= start:
                k += 1
            take = min(end, bounds[k + 1]) - start
            groups[k].append((start, view[start - off : start - off + take]))
            start += take
        off = end
    return [g for g in groups if g]


def _leaf_to_numpy(leaf: Any) -> np.ndarray:
    arr = np.asarray(leaf)
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr


def _dtype_name(dtype: np.dtype) -> str:
    # `.str` is lossy for extension dtypes (bfloat16 → "<V2"); the name round-trips.
    return dtype.name


def resolve_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including ml_dtypes extension types (bfloat16, fp8...)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _raw_view(a: np.ndarray) -> memoryview:
    # Extension dtypes (bfloat16) don't support the buffer protocol; uint8 view does.
    # Flatten first: a 0-d array can't change dtype via view.
    return memoryview(np.ascontiguousarray(a).reshape(-1).view(np.uint8)).cast("B")


def write_payload(
    path: str,
    hollow_bytes: bytes,
    tensors: Sequence[Any],
    meta: Optional[dict] = None,
    fsync: bool = True,
    stripes: Optional[int] = None,
) -> int:
    """Atomically write a checkpoint file; returns bytes written.

    ``stripes`` > 1 fans the payload out over N writer threads pwrite-ing
    byte-balanced contiguous leaf groups at their final offsets in the SAME
    container — the file an N-way write produces is byte-identical to the
    sequential one, so the read path never changes. ``None`` reads the
    ``$TPU_RESILIENCY_CKPT_STRIPES`` storage-class default (1).
    """
    stripes = _effective_stripes(stripes)
    arrays = [_leaf_to_numpy(t) for t in tensors]
    header = {
        "hollow": hollow_bytes,
        "leaves": [
            {"shape": a.shape, "dtype": _dtype_name(a.dtype), "nbytes": a.nbytes} for a in arrays
        ],
        "meta": meta or {},
    }
    header_bytes = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path + DIRTY_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    base = len(MAGIC) + _LEN.size + len(header_bytes)
    written = base + sum(a.nbytes for a in arrays)
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(_LEN.pack(len(header_bytes)))
        f.write(header_bytes)
        # Byte-range striping splits within leaves, so even a single fused-
        # parameter leaf stripes; an all-empty payload yields no groups.
        groups = _partition_by_bytes(arrays, stripes) if stripes > 1 else []
        if not groups:
            for a in arrays:
                f.write(_raw_view(a))
        else:
            # Header leaves the buffered stream before any pwrite lands beyond it.
            f.flush()
            import concurrent.futures as cf

            fd = f.fileno()

            def run(group):
                for off, view in group:
                    _pwrite_full(fd, view, base + off)

            with cf.ThreadPoolExecutor(len(groups)) as pool:
                list(pool.map(run, groups))
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    _commit_atomic(tmp, path, fsync)
    return written


def write_blob(path: str, blob: bytes, fsync: bool = True, stripes: Optional[int] = None) -> None:
    """Atomically write an already-serialized container blob, optionally striped
    (N threads pwrite-ing byte ranges — same knob and rationale as
    :func:`write_payload`)."""
    stripes = _effective_stripes(stripes)
    tmp = path + DIRTY_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if stripes == 1 or len(blob) < (1 << 20):
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
    else:
        import concurrent.futures as cf

        view = memoryview(blob)
        chunk = (len(blob) + stripes - 1) // stripes
        with open(tmp, "wb") as f:
            fd = f.fileno()

            def run(i: int) -> None:
                _pwrite_full(fd, view[i * chunk : (i + 1) * chunk], i * chunk)

            with cf.ThreadPoolExecutor(stripes) as pool:
                list(pool.map(run, range(stripes)))
            if fsync:
                os.fsync(fd)
    _commit_atomic(tmp, path, fsync)


def read_header(path: str) -> dict:
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise CheckpointError(f"{path}: bad magic (not a tpu_resiliency checkpoint)")
        (hlen,) = _LEN.unpack(f.read(_LEN.size))
        return pickle.loads(f.read(hlen))


def read_payload(path: str) -> tuple[bytes, list[np.ndarray], dict]:
    """Read (hollow_bytes, tensors, meta). Tensors come back as numpy arrays."""
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise CheckpointError(f"{path}: bad magic (not a tpu_resiliency checkpoint)")
        (hlen,) = _LEN.unpack(f.read(_LEN.size))
        header = pickle.loads(f.read(hlen))
        tensors = []
        for spec in header["leaves"]:
            buf = f.read(spec["nbytes"])
            if len(buf) != spec["nbytes"]:
                raise CheckpointError(f"{path}: truncated payload")
            tensors.append(
                np.frombuffer(buf, dtype=resolve_dtype(spec["dtype"])).reshape(spec["shape"])
            )
    return header["hollow"], tensors, header.get("meta", {})


def header_prefix(
    hollow_bytes: bytes, specs: Sequence[dict], meta: dict | None = None
) -> bytes:
    """The ``MAGIC | header_len | header`` container head built from leaf SPECS
    alone (``{"shape", "dtype", "nbytes"}`` per leaf) — no host arrays needed.

    This is what lets the pipelined save commit to the container layout while
    every leaf's D2H transfer is still in flight: specs come straight off the
    device arrays' metadata, the prefix goes out to files and peer streams
    first, and the payload bytes follow as they resolve."""
    header = {
        "hollow": hollow_bytes,
        "leaves": [
            {
                "shape": tuple(s["shape"]),
                "dtype": str(s["dtype"]),
                "nbytes": int(s["nbytes"]),
            }
            for s in specs
        ],
        "meta": meta or {},
    }
    header_bytes = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    return MAGIC + _LEN.pack(len(header_bytes)) + header_bytes


def serialize_parts(
    hollow_bytes: bytes, tensors: Sequence[Any], meta: dict | None = None
) -> tuple[bytes, list[memoryview]]:
    """Container as ``(prefix_bytes, [leaf byte views])`` — the zero-copy form.

    The prefix is the small ``MAGIC | header_len | header`` head; the views are
    raw uint8 windows over each leaf's host buffer. Concatenating
    ``prefix + views`` yields exactly :func:`serialize_to_bytes`'s blob, but no
    joined copy ever exists: senders scatter-gather the parts straight onto a
    socket (``framing.send_bulk``) and writers stream them to a file
    (:func:`write_parts`). The views alias the input tensors — keep those alive
    (and unmutated) until the parts are consumed.
    """
    arrays = [_leaf_to_numpy(t) for t in tensors]
    prefix = header_prefix(
        hollow_bytes,
        [
            {"shape": a.shape, "dtype": _dtype_name(a.dtype), "nbytes": a.nbytes}
            for a in arrays
        ],
        meta,
    )
    return prefix, [_raw_view(a) for a in arrays]


def parts_nbytes(prefix: bytes, views: Sequence[Any]) -> int:
    """Total container size of a :func:`serialize_parts` result."""
    return len(prefix) + sum(memoryview(v).cast("B").nbytes for v in views)


def serialize_to_bytes(hollow_bytes: bytes, tensors: Sequence[Any], meta: dict | None = None) -> bytes:
    """In-memory form of the container (compat path for whole-blob consumers;
    the replication hot path uses :func:`serialize_parts` and never joins)."""
    prefix, views = serialize_parts(hollow_bytes, tensors, meta)
    return b"".join([prefix, *views])


def _chunk_view(chunk: Any) -> memoryview:
    """Flat uint8 view of any stream chunk — bytes-likes directly, numpy arrays
    through the extension-dtype-safe reinterpret (bfloat16 has no buffer
    protocol)."""
    if isinstance(chunk, np.ndarray):
        return _raw_view(chunk)
    return memoryview(chunk).cast("B")


def write_stream(path: str, chunks, fsync: bool = True) -> int:
    """Atomically stream container chunks to ``path`` as they become available.

    ``chunks`` is any iterable of bytes-likes or numpy arrays — typically a
    header prefix followed by leaves resolving off the D2H queue, which is how
    the pipelined save overlaps disk IO with the device transfers: each leaf
    hits the file the moment its DMA lands, not after a full-tree barrier.
    Same ``.dirty``-then-rename commit as every other writer: a producer
    raising mid-stream leaves only the ``.dirty`` temp file (the crash contract
    startup cleanup already handles), never a torn visible container. Returns
    bytes written."""
    tmp = path + DIRTY_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    written = 0
    with open(tmp, "wb") as f:
        for chunk in chunks:
            v = _chunk_view(chunk)
            f.write(v)
            written += v.nbytes
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    _commit_atomic(tmp, path, fsync)
    return written


def write_parts(path: str, parts: Sequence[Any], fsync: bool = True) -> int:
    """Atomically stream already-serialized container parts to ``path`` — the
    ``.dirty``-then-rename protocol of :func:`write_blob` without requiring a
    joined blob (a receive buffer, a :func:`serialize_parts` result, or any mix
    of bytes-likes). Returns bytes written."""
    return write_stream(path, parts, fsync=fsync)


def deserialize_from_buffer(buf) -> tuple[bytes, list[np.ndarray], dict]:
    """Zero-copy deserialization: tensors come back as views over ``buf``.

    ``buf`` is any bytes-like (typically the single receive buffer a bulk frame
    landed in); each leaf is ``np.frombuffer`` over a ``memoryview`` slice, so
    no per-leaf copies are made. The arrays alias ``buf`` — they are read-only
    when ``buf`` is, and mutating ``buf`` mutates them. Callers that outlive the
    buffer (or need writable tensors from an immutable source) copy explicitly.
    """
    mv = memoryview(buf).cast("B")
    if bytes(mv[: len(MAGIC)]) != MAGIC:
        raise CheckpointError("bad magic in serialized checkpoint blob")
    off = len(MAGIC)
    (hlen,) = _LEN.unpack(mv[off : off + _LEN.size])
    off += _LEN.size
    header = pickle.loads(mv[off : off + hlen])
    off += hlen
    tensors = []
    for spec in header["leaves"]:
        n = spec["nbytes"]
        if off + n > mv.nbytes:
            raise CheckpointError("truncated serialized checkpoint blob")
        tensors.append(
            np.frombuffer(mv[off : off + n], dtype=resolve_dtype(spec["dtype"])).reshape(
                spec["shape"]
            )
        )
        off += n
    return header["hollow"], tensors, header.get("meta", {})


def deserialize_from_bytes(blob) -> tuple[bytes, list[np.ndarray], dict]:
    """Alias of :func:`deserialize_from_buffer` (kept for callers written against
    the pre-streaming API; both are zero-copy over the input buffer now)."""
    return deserialize_from_buffer(blob)
