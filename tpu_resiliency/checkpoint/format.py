"""Single-file checkpoint container: pickled hollow skeleton + raw array payload.

The write path the reference implements with per-bucket writer processes over torch-DCP
files (``checkpointing/async_ckpt/filesystem_async.py:102-334``) collapses on TPU hosts
to: hollow metadata (small pickle) followed by each leaf's raw bytes, streamed
sequentially — large contiguous writes are how you saturate local NVMe, and the hollow /
payload split means the metadata can be read without touching the payload.

**Measured justification for single-stream (the reference fans out per-bucket
writers, ``filesystem_async.py:232-334,558``):** on this class of host storage,
writing a 1 GiB tree (fsync'd, warm, alternating runs —
``scripts/bench_ckpt_io.py``) measured single-stream at 0.30 GB/s median vs 0.16
GB/s for a 4-way thread fan-out: concurrent streams halve throughput by
interleaving what would be contiguous writes. Writes here are also already
asynchronous to the train loop (``async_core``), so writer parallelism buys no
step-time; it would only shorten the background window. Revisit only for storage
where one stream cannot saturate the device (e.g. striped NVMe arrays or object
stores) — measure with the same script first, then split at the leaf level
(each leaf's offset is in the header, so a reader-compatible multi-writer needs
only pwrite-at-offset into the same container).

Atomicity follows the reference's ``.dirty``-then-rename protocol
(``checkpointing/local/ckpt_managers/local_manager.py:110-131``): write to
``<path>.dirty``, fsync, ``os.replace``. A crash leaves only ``.dirty`` files, which
cleanup removes; a visible file is always complete.

Layout::

    MAGIC(8) | header_len(8 LE) | header pickle | leaf 0 bytes | leaf 1 bytes | ...

Header: ``{"hollow": bytes, "leaves": [{"shape", "dtype", "nbytes"}, ...], "meta": {}}``.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, Optional, Sequence

import numpy as np

from tpu_resiliency.exceptions import CheckpointError

MAGIC = b"TPURES01"
_LEN = struct.Struct("<Q")
DIRTY_SUFFIX = ".dirty"


def _leaf_to_numpy(leaf: Any) -> np.ndarray:
    arr = np.asarray(leaf)
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr


def _dtype_name(dtype: np.dtype) -> str:
    # `.str` is lossy for extension dtypes (bfloat16 → "<V2"); the name round-trips.
    return dtype.name


def resolve_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including ml_dtypes extension types (bfloat16, fp8...)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _raw_view(a: np.ndarray) -> memoryview:
    # Extension dtypes (bfloat16) don't support the buffer protocol; uint8 view does.
    # Flatten first: a 0-d array can't change dtype via view.
    return memoryview(np.ascontiguousarray(a).reshape(-1).view(np.uint8)).cast("B")


def write_payload(
    path: str,
    hollow_bytes: bytes,
    tensors: Sequence[Any],
    meta: Optional[dict] = None,
    fsync: bool = True,
) -> int:
    """Atomically write a checkpoint file; returns bytes written."""
    arrays = [_leaf_to_numpy(t) for t in tensors]
    header = {
        "hollow": hollow_bytes,
        "leaves": [
            {"shape": a.shape, "dtype": _dtype_name(a.dtype), "nbytes": a.nbytes} for a in arrays
        ],
        "meta": meta or {},
    }
    header_bytes = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path + DIRTY_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    written = 0
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(_LEN.pack(len(header_bytes)))
        f.write(header_bytes)
        written += len(MAGIC) + _LEN.size + len(header_bytes)
        for a in arrays:
            f.write(_raw_view(a))
            written += a.nbytes
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        # Persist the rename itself.
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    return written


def read_header(path: str) -> dict:
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise CheckpointError(f"{path}: bad magic (not a tpu_resiliency checkpoint)")
        (hlen,) = _LEN.unpack(f.read(_LEN.size))
        return pickle.loads(f.read(hlen))


def read_payload(path: str) -> tuple[bytes, list[np.ndarray], dict]:
    """Read (hollow_bytes, tensors, meta). Tensors come back as numpy arrays."""
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise CheckpointError(f"{path}: bad magic (not a tpu_resiliency checkpoint)")
        (hlen,) = _LEN.unpack(f.read(_LEN.size))
        header = pickle.loads(f.read(hlen))
        tensors = []
        for spec in header["leaves"]:
            buf = f.read(spec["nbytes"])
            if len(buf) != spec["nbytes"]:
                raise CheckpointError(f"{path}: truncated payload")
            tensors.append(
                np.frombuffer(buf, dtype=resolve_dtype(spec["dtype"])).reshape(spec["shape"])
            )
    return header["hollow"], tensors, header.get("meta", {})


def serialize_to_bytes(hollow_bytes: bytes, tensors: Sequence[Any], meta: dict | None = None) -> bytes:
    """In-memory form of the container (used for peer-to-peer replication frames)."""
    arrays = [_leaf_to_numpy(t) for t in tensors]
    header = {
        "hollow": hollow_bytes,
        "leaves": [
            {"shape": a.shape, "dtype": _dtype_name(a.dtype), "nbytes": a.nbytes} for a in arrays
        ],
        "meta": meta or {},
    }
    header_bytes = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    parts = [MAGIC, _LEN.pack(len(header_bytes)), header_bytes]
    parts.extend(_raw_view(a) for a in arrays)
    return b"".join(parts)


def deserialize_from_bytes(blob: bytes) -> tuple[bytes, list[np.ndarray], dict]:
    if blob[: len(MAGIC)] != MAGIC:
        raise CheckpointError("bad magic in serialized checkpoint blob")
    off = len(MAGIC)
    (hlen,) = _LEN.unpack(blob[off : off + _LEN.size])
    off += _LEN.size
    header = pickle.loads(blob[off : off + hlen])
    off += hlen
    tensors = []
    for spec in header["leaves"]:
        n = spec["nbytes"]
        tensors.append(
            np.frombuffer(blob[off : off + n], dtype=resolve_dtype(spec["dtype"])).reshape(
                spec["shape"]
            )
        )
        off += n
    return header["hollow"], tensors, header.get("meta", {})
