"""Single-file checkpoint container: pickled hollow skeleton + raw array payload.

The write path the reference implements with per-bucket writer processes over torch-DCP
files (``checkpointing/async_ckpt/filesystem_async.py:102-334``) collapses on TPU hosts
to: hollow metadata (small pickle) followed by each leaf's raw bytes, streamed
sequentially — large contiguous writes are how you saturate local NVMe, and the hollow /
payload split means the metadata can be read without touching the payload.

**Measured justification for single-stream (the reference fans out per-bucket
writers, ``filesystem_async.py:232-334,558``):** on this class of host storage,
writing a 1 GiB tree (fsync'd, warm, alternating runs —
``scripts/bench_ckpt_io.py``) measured single-stream at 0.30 GB/s median vs 0.16
GB/s for a 4-way thread fan-out: concurrent streams halve throughput by
interleaving what would be contiguous writes. Writes here are also already
asynchronous to the train loop (``async_core``), so writer parallelism buys no
step-time; it would only shorten the background window.

The capability exists anyway, behind the ``$TPU_RESILIENCY_CKPT_STRIPES``
storage-class knob (``stripes=`` on :func:`write_payload`/:func:`write_blob`):
N threads pwrite byte-balanced contiguous leaf groups at their final offsets in
the SAME container, so the striped file is byte-identical to the sequential one
and the read path never changes. Measured on this host (0.5 GiB, 64 leaves,
``scripts/bench_ckpt_io.py``): single-stream 0.59 GB/s vs 4-way striped 0.61
GB/s — a wash here, hence default 1; on striped NVMe arrays or parallel
filesystems re-run the script and set the env for the measured winner.

Atomicity follows the reference's ``.dirty``-then-rename protocol
(``checkpointing/local/ckpt_managers/local_manager.py:110-131``): write to
``<path>.dirty``, fsync, ``os.replace``. A crash leaves only ``.dirty`` files, which
cleanup removes; a visible file is always complete.

**Integrity (format v2, ``TPURES02``).** Atomic renames protect against torn
*writes*, not against what storage does to committed bytes: a flipped bit on
worn NVMe, a post-crash tail loss, a torn rename all yield a structurally
plausible container that deserializes into silently wrong weights. v2
containers therefore carry end-to-end checksums, computed streaming in every
write path and verified streaming on every read path:

- **per-leaf CRC32C** — recorded in the header leaf specs when the writer has
  the payload in hand (:func:`write_payload`, :func:`serialize_parts`), and
  ALWAYS in the trailer (the pipelined save only learns a leaf's CRC as its
  D2H copy resolves, after the header is long gone down the wire);
- **a whole-file trailer digest** — CRC over the container head extended with
  each leaf's packed CRC (a digest-of-digests: every byte of the file is
  covered in ONE streaming pass over the payload, no second read).

``TPURES01`` containers still load — verification is skipped and a
``ckpt_unverified`` event is recorded, so a fleet can tell "old format" from
"verified" in its metrics. The CRC implementation is ``google_crc32c`` when
the host has it, gated down to stdlib ``zlib.crc32`` otherwise; the trailer
records which algorithm signed the file, and a reader lacking that algorithm
degrades to unverified-with-event rather than failing the load.

This module is also the **disk-fault injection boundary**: every container
write and every ``.dirty``→visible commit funnels through a patchable IO shim
(:func:`_disk_write`, :func:`_commit_atomic`) that consults the chaos plan's
``disk`` channel (``platform/chaos.py``: seeded bit flips, post-commit
truncation, torn renames, ENOSPC, slow IO), so corruption scenarios reproduce
from a seed exactly like network fault plans.

**Chunk manifest (format v3, ``TPURES03``).** v2's unit of verification is the
*leaf* — fine for whole-container reads, hostile to ranged ones: serving a
4 KB reshard range out of a 256 MB leaf forced a CRC pass over the entire
container (BENCH_reshard.json's 0.42 speedup was exactly that stall). v3
additionally records a **per-chunk CRC manifest** in the trailer: every leaf's
payload is cut into fixed-size, leaf-aligned chunks (``chunk_size`` rides in
the trailer; chunks never span leaves, the last chunk of a leaf is short) and
each chunk is individually signed. Any byte range now verifies in O(range):
read the covering chunks, check their CRCs, done — :func:`chunk_spans` names
the covering chunks, the local manager's ranged-read server and the reshard
load path verify exactly those. The chunk manifest is also what the
byte-economy planes are built on: delta checkpoints diff per-chunk CRCs to
ship only changed chunks (``checkpoint/coding/delta.py``), and erasure blocks
verify without whole-container scans (``checkpoint/coding/strategy.py``).

``TPURES02`` containers still load fully verified (whole-leaf CRCs + digest);
they simply cannot serve chunk-granular verification, so ranged readers fall
back to the one-time whole-file pass. ``TPURES01`` loads unverified with a
``ckpt_unverified`` event, as before.

Layout (v3)::

    MAGIC(8) | header_len(8 LE) | header pickle | leaf 0 bytes | ... |
    TRAILER_MAGIC_V3(8) | algo(4) | chunk_size(4 LE) | nleaves(4 LE) |
    nchunks(4 LE) | leaf_crc32c(4 LE)*nleaves | chunk_crc32c(4 LE)*nchunks |
    container_crc(4 LE)

(v2 trailer, still read: ``TPURES02`` head + ``TRAILER_MAGIC(8) | algo(4) |
nleaves(4 LE) | leaf_crc32c(4 LE)*n | container_crc(4 LE)``.)

Header: ``{"hollow": bytes, "leaves": [{"shape", "dtype", "nbytes"[, "crc32c"]},
...], "meta": {}}``.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import struct
from typing import Any, Optional, Sequence

import numpy as np

from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform import chaos
from tpu_resiliency.utils.events import record as record_event

#: Current container version: v3 adds the per-chunk CRC manifest (O(range)
#: verification for ranged reads, the chunk-diff substrate for delta saves).
MAGIC = b"TPURES03"
#: v2 containers (leaf CRCs + trailer digest, no chunk manifest) still load
#: fully verified — ranged readers fall back to whole-file verification.
MAGIC_V2 = b"TPURES02"
#: v1 containers (pre-integrity) still load, unverified (``ckpt_unverified``).
MAGIC_V1 = b"TPURES01"
_MAGICS = (MAGIC, MAGIC_V2, MAGIC_V1)
TRAILER_MAGIC = b"TPURESCK"
TRAILER_MAGIC_V3 = b"TPURESC3"
_LEN = struct.Struct("<Q")
_U32 = struct.Struct("<I")
DIRTY_SUFFIX = ".dirty"
#: Quarantine suffix the recovery ladder renames corrupt containers to.
CORRUPT_SUFFIX = ".corrupt"

# -- checksum implementation --------------------------------------------------
#
# CRC32C (Castagnoli) via google_crc32c when the image ships it; stdlib
# zlib.crc32 (IEEE) otherwise — no new dependencies either way. The trailer
# records the signing algorithm, so readers on a host with the OTHER
# implementation degrade to unverified-with-event instead of false alarms.
try:
    import google_crc32c as _crc_impl

    CRC_ALGO = "crc32c"
    _ALGO_TAG = b"c32c"
    #: google_crc32c's C binding only accepts ``bytes``; chunk the copy so the
    #: transient allocation stays bounded at any payload size. 256 KiB keeps
    #: the steady-state pipelined save's peak transient under the <1 MB
    #: alloc gate even though the v3 manifest CRCs one whole chunk at a time.
    _CRC_CHUNK = 1 << 18

    def crc32c(data, crc: int = 0) -> int:
        """Streaming checksum update over any bytes-like (CRC32C here; the
        gated zlib fallback keeps the same signature and the trailer's algo
        tag tells readers which one signed the file)."""
        if isinstance(data, bytes):
            return _crc_impl.extend(crc, data)
        view = memoryview(data)
        if view.ndim != 1 or view.itemsize != 1:
            view = view.cast("B")
        for i in range(0, view.nbytes, _CRC_CHUNK):
            crc = _crc_impl.extend(crc, bytes(view[i : i + _CRC_CHUNK]))
        return crc

except ImportError:  # pragma: no cover - exercised only on hosts without it
    import zlib as _crc_impl

    CRC_ALGO = "crc32"
    _ALGO_TAG = b"zl32"

    def crc32c(data, crc: int = 0) -> int:
        """Streaming checksum update (stdlib CRC32 fallback — see module doc)."""
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = memoryview(data)
        if isinstance(data, memoryview) and (data.ndim != 1 or data.itemsize != 1):
            data = data.cast("B")
        return _crc_impl.crc32(data, crc) & 0xFFFFFFFF


#: algo tag → can THIS host verify it (only its own tag; the two algorithms
#: are different polynomials, not interchangeable).
_VERIFIABLE_TAGS = (_ALGO_TAG,)

#: Storage-class knob for writer parallelism (reference analogue: per-bucket
#: writer fan-out, ``filesystem_async.py:232-334``). Default 1: on this class of
#: host storage one stream saturates the device and a fan-out HALVES throughput
#: (measured, see module docstring). Set >1 only after ``scripts/bench_ckpt_io.py``
#: shows a win on the target storage (striped NVMe arrays, parallel filesystems).
STRIPES_ENV = "TPU_RESILIENCY_CKPT_STRIPES"


def _effective_stripes(stripes: Optional[int]) -> int:
    if stripes is None:
        try:
            stripes = int(os.environ.get(STRIPES_ENV, "1"))
        except ValueError:
            stripes = 1
    return max(1, int(stripes))


# -- chunk geometry -----------------------------------------------------------
#
# Chunks are LEAF-ALIGNED: each leaf's payload is independently cut into
# ``chunk_size`` pieces (the last one short), so a chunk never spans two
# leaves and leaf-relative range math never crosses a leaf boundary. The
# manifest orders chunks leaf-major (leaf 0's chunks, then leaf 1's, ...).

#: Default chunk size (1 MiB): a 1 GB container carries a 4 KB manifest, and
#: a 4 KB ranged read verifies at most two 1 MiB chunks instead of the file.
DEFAULT_CHUNK = 1 << 20
#: Storage-class override (bytes); floor 4 KiB so manifests stay bounded.
CHUNK_ENV = "TPU_RESILIENCY_CKPT_CHUNK"


def _effective_chunk(chunk_size: Optional[int]) -> int:
    if chunk_size is None:
        try:
            chunk_size = int(os.environ.get(CHUNK_ENV, str(DEFAULT_CHUNK)))
        except ValueError:
            chunk_size = DEFAULT_CHUNK
    return max(1 << 12, int(chunk_size))


def leaf_chunk_count(nbytes: int, chunk_size: int) -> int:
    """Chunks in one leaf's payload (0 for an empty leaf)."""
    return (int(nbytes) + chunk_size - 1) // chunk_size


def total_chunks(leaf_sizes: Sequence[int], chunk_size: int) -> int:
    return sum(leaf_chunk_count(n, chunk_size) for n in leaf_sizes)


def chunk_spans(
    nbytes: int, chunk_size: int, off: int, length: int
) -> tuple[int, int]:
    """Covering chunk index range ``[first, last)`` of a leaf-relative byte
    range ``[off, off+length)`` inside a leaf of ``nbytes`` bytes."""
    if length <= 0:
        return 0, 0
    first = off // chunk_size
    last = min((off + length - 1) // chunk_size + 1,
               leaf_chunk_count(nbytes, chunk_size))
    return first, last


# -- integrity trailer --------------------------------------------------------


def trailer_size(nleaves: int) -> int:
    """On-disk size of a v2 integrity trailer for ``nleaves`` leaves (kept for
    reading ``TPURES02`` containers; v3 writers use :func:`trailer_size_v3`)."""
    return len(TRAILER_MAGIC) + 4 + _U32.size * (nleaves + 2)


#: v3 trailer fixed head: magic | algo | chunk_size | nleaves | nchunks.
_V3_FIXED = len(TRAILER_MAGIC_V3) + 4 + 3 * _U32.size


def trailer_size_v3(nleaves: int, nchunks: int) -> int:
    """On-disk size of a v3 trailer — fixed given leaf count + chunk count,
    which the leaf specs and chunk size determine, so the pipelined save can
    still declare its total container size before any payload byte exists."""
    return _V3_FIXED + _U32.size * (nleaves + nchunks + 1)


def trailer_size_for(
    leaf_sizes: Sequence[int], chunk_size: Optional[int] = None
) -> int:
    """v3 trailer size straight from leaf byte sizes (spec-only, no payload)."""
    cs = _effective_chunk(chunk_size)
    return trailer_size_v3(len(leaf_sizes), total_chunks(leaf_sizes, cs))


def build_trailer(leaf_crcs: Sequence[int], container_crc: int) -> bytes:
    """Serialize the trailer: magic, algo tag, leaf count, per-leaf CRCs, and
    the whole-container digest."""
    return b"".join(
        [
            TRAILER_MAGIC,
            _ALGO_TAG,
            _U32.pack(len(leaf_crcs)),
            *(_U32.pack(c) for c in leaf_crcs),
            _U32.pack(container_crc),
        ]
    )


def parse_trailer(buf, source: str = "container") -> tuple[bytes, list[int], int]:
    """Parse a v2 trailer blob → ``(algo_tag, leaf_crcs, container_crc)``;
    raises :class:`CheckpointError` naming ``source`` when the trailer is
    missing or structurally damaged (the usual signature of tail truncation)."""
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    fixed = len(TRAILER_MAGIC) + 4 + _U32.size
    if mv.nbytes < fixed or bytes(mv[: len(TRAILER_MAGIC)]) != TRAILER_MAGIC:
        raise CheckpointError(
            f"{source}: integrity trailer missing or corrupt (truncated file?)"
        )
    algo = bytes(mv[len(TRAILER_MAGIC) : len(TRAILER_MAGIC) + 4])
    (n,) = _U32.unpack(mv[len(TRAILER_MAGIC) + 4 : fixed])
    if mv.nbytes != trailer_size(n):
        raise CheckpointError(
            f"{source}: integrity trailer truncated "
            f"({mv.nbytes} bytes for {n} leaves, want {trailer_size(n)})"
        )
    crcs = (
        list(struct.unpack(f"<{n}I", mv[fixed : fixed + 4 * n])) if n else []
    )
    (container_crc,) = _U32.unpack(mv[fixed + 4 * n :])
    return algo, crcs, container_crc


def build_trailer_v3(
    leaf_crcs: Sequence[int],
    chunk_crcs: Sequence[int],
    chunk_size: int,
    container_crc: int,
) -> bytes:
    """Serialize a v3 trailer: the v2 record plus the chunk manifest
    (chunk size + leaf-major per-chunk CRCs)."""
    return b"".join(
        [
            TRAILER_MAGIC_V3,
            _ALGO_TAG,
            _U32.pack(chunk_size),
            _U32.pack(len(leaf_crcs)),
            _U32.pack(len(chunk_crcs)),
            *(_U32.pack(c) for c in leaf_crcs),
            *(_U32.pack(c) for c in chunk_crcs),
            _U32.pack(container_crc),
        ]
    )


@dataclasses.dataclass
class TrailerInfo:
    """Version-neutral view of a container's integrity record. ``chunk_size``
    / ``chunk_crcs`` are ``None`` for v2 containers (no manifest — whole-leaf
    verification only)."""

    algo: bytes
    leaf_crcs: list[int]
    container_crc: int
    chunk_size: Optional[int] = None
    chunk_crcs: Optional[list[int]] = None

    @property
    def verifiable(self) -> bool:
        return self.algo in _VERIFIABLE_TAGS

    def leaf_chunk_crcs(self, leaf_sizes: Sequence[int]) -> list[list[int]]:
        """The manifest re-grouped per leaf (leaf-major flat order → lists)."""
        if self.chunk_crcs is None or self.chunk_size is None:
            raise CheckpointError("container carries no chunk manifest (v2)")
        out, pos = [], 0
        for n in leaf_sizes:
            cnt = leaf_chunk_count(int(n), self.chunk_size)
            out.append(self.chunk_crcs[pos : pos + cnt])
            pos += cnt
        return out


def parse_trailer_v3(buf, source: str = "container") -> TrailerInfo:
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    if mv.nbytes < _V3_FIXED or bytes(
        mv[: len(TRAILER_MAGIC_V3)]
    ) != TRAILER_MAGIC_V3:
        raise CheckpointError(
            f"{source}: v3 integrity trailer missing or corrupt "
            f"(truncated file?)"
        )
    off = len(TRAILER_MAGIC_V3)
    algo = bytes(mv[off : off + 4])
    off += 4
    chunk_size, nleaves, nchunks = struct.unpack(
        "<3I", mv[off : off + 3 * _U32.size]
    )
    if chunk_size < 1 or mv.nbytes != trailer_size_v3(nleaves, nchunks):
        raise CheckpointError(
            f"{source}: trailer size mismatch ({mv.nbytes} bytes for "
            f"{nleaves} leaves / {nchunks} chunks) — truncated or torn file"
        )
    off = _V3_FIXED
    leaf_crcs = list(
        struct.unpack(f"<{nleaves}I", mv[off : off + 4 * nleaves])
    ) if nleaves else []
    off += 4 * nleaves
    chunk_crcs = list(
        struct.unpack(f"<{nchunks}I", mv[off : off + 4 * nchunks])
    ) if nchunks else []
    off += 4 * nchunks
    (container_crc,) = _U32.unpack(mv[off:])
    return TrailerInfo(
        algo=algo, leaf_crcs=leaf_crcs, container_crc=container_crc,
        chunk_size=chunk_size, chunk_crcs=chunk_crcs,
    )


def parse_trailer_any(
    buf, magic: bytes, leaf_sizes: Sequence[int], source: str = "container"
) -> TrailerInfo:
    """Parse whichever trailer ``magic``'s container version carries, with
    structural cross-checks against the header's leaf sizes."""
    if magic == MAGIC_V2:
        algo, leaf_crcs, container_crc = parse_trailer(buf, source)
        if len(leaf_crcs) != len(leaf_sizes):
            raise CheckpointError(
                f"{source}: trailer records {len(leaf_crcs)} leaves, header "
                f"declares {len(leaf_sizes)}"
            )
        return TrailerInfo(algo=algo, leaf_crcs=leaf_crcs,
                           container_crc=container_crc)
    info = parse_trailer_v3(buf, source)
    if len(info.leaf_crcs) != len(leaf_sizes) or len(
        info.chunk_crcs
    ) != total_chunks(leaf_sizes, info.chunk_size):
        raise CheckpointError(
            f"{source}: trailer manifest disagrees with header leaf sizes "
            f"({len(info.leaf_crcs)} leaves / {len(info.chunk_crcs)} chunks "
            f"@ {info.chunk_size} B chunk)"
        )
    return info


def _container_crc(prefix, leaf_crcs: Sequence[int]) -> int:
    """The v2 whole-file digest: CRC over the container head (magic + header
    len + header pickle) extended with each leaf's packed CRC — a digest of
    digests, so the entire file is covered by ONE streaming pass over the
    payload (the leaf CRCs double as the file digest's input)."""
    crc = crc32c(prefix)
    for c in leaf_crcs:
        crc = crc32c(_U32.pack(c), crc)
    return crc


def _container_crc_v3(
    prefix, leaf_crcs: Sequence[int], chunk_crcs: Sequence[int]
) -> int:
    """v3 digest: the v2 digest-of-digests extended with the packed chunk
    manifest, so a flipped bit in ANY trailer entry (leaf or chunk CRC) is
    caught by the digest check."""
    crc = _container_crc(prefix, leaf_crcs)
    for c in chunk_crcs:
        crc = crc32c(_U32.pack(c), crc)
    return crc


def _expected_digest(info: TrailerInfo, prefix) -> int:
    if info.chunk_crcs is None:
        return _container_crc(prefix, info.leaf_crcs)
    return _container_crc_v3(prefix, info.leaf_crcs, info.chunk_crcs)


class Checksummer:
    """Streaming v3 integrity state for writers that see the container as
    prefix-then-leaves (the pipelined save, the durable stream writer): feed
    the header prefix at construction and each leaf view exactly once as it
    resolves, then emit the trailer chunk. One IO pass, no buffering — each
    leaf's bytes are CRC'd per chunk (manifest) and across the leaf (leaf
    record) as they stream through."""

    def __init__(self, prefix: bytes, chunk_size: Optional[int] = None):
        self.chunk_size = _effective_chunk(chunk_size)
        self.leaf_crcs: list[int] = []
        #: leaf-major flat manifest (the trailer's chunk section)
        self.chunk_crcs: list[int] = []
        #: per-leaf manifest slices — the delta tracker's diff input
        self.leaf_chunks: list[list[int]] = []
        self._prefix_crc = crc32c(prefix)

    def add_leaf(self, view) -> int:
        mv = memoryview(view)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        leaf_crc = 0
        chunks: list[int] = []
        for off in range(0, mv.nbytes, self.chunk_size):
            window = mv[off : off + self.chunk_size]
            chunks.append(crc32c(window))
            leaf_crc = crc32c(window, leaf_crc)
        self.leaf_crcs.append(leaf_crc)
        self.chunk_crcs.extend(chunks)
        self.leaf_chunks.append(chunks)
        return leaf_crc

    def trailer(self) -> bytes:
        crc = self._prefix_crc
        for c in self.leaf_crcs:
            crc = crc32c(_U32.pack(c), crc)
        for c in self.chunk_crcs:
            crc = crc32c(_U32.pack(c), crc)
        return build_trailer_v3(
            self.leaf_crcs, self.chunk_crcs, self.chunk_size, crc
        )


def _record_unverified(source: str, reason: str) -> None:
    """One ``ckpt_unverified`` event per skipped verification (v1 container or
    foreign checksum algorithm) → ``tpu_ckpt_unverified_total``."""
    record_event(
        "checkpoint", "ckpt_unverified", container=str(source), reason=reason
    )


# -- chaos-injectable IO shim -------------------------------------------------


def _disk_write(f, data, path: str) -> int:
    """Every buffered container write funnels here: the chaos ``disk`` channel
    may corrupt the buffer (bitflip), stall, or raise ENOSPC. ``path`` is the
    FINAL path (not the ``.dirty`` temp) so rules target the file a reader
    would see. Returns bytes written."""
    data = chaos.on_disk_write(path, data)
    f.write(data)
    return memoryview(data).nbytes


def _commit_atomic(tmp: str, path: str, fsync: bool) -> None:
    """The ``.dirty``-then-rename commit tail shared by every writer: make the
    file visible only complete, and persist the rename itself. The chaos
    ``disk.commit`` hook injects torn renames (temp truncated before the
    rename) and post-commit tail truncation here."""
    post_fault = chaos.on_disk_commit(tmp, path)
    os.replace(tmp, path)
    if post_fault is not None:
        post_fault()
    if fsync:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


def _pwrite_full(fd: int, view: memoryview, offset: int, path: Optional[str] = None) -> None:
    if path is not None:
        out = chaos.on_disk_write(path, view)
        view = memoryview(out) if not isinstance(out, memoryview) else out
        if view.ndim != 1 or view.itemsize != 1:
            view = view.cast("B")
    while view.nbytes:
        n = os.pwrite(fd, view, offset)
        view = view[n:]
        offset += n


def _partition_by_bytes(arrays, stripes: int):
    """Equal BYTE ranges of the concatenated payload: ``[(offset, view), ...]``
    per stripe. Ranges ignore leaf boundaries (pwrite only sees bytes), so the
    knob works even when one huge fused-parameter leaf dominates the payload —
    whole-leaf grouping would leave every other writer idle."""
    total = sum(a.nbytes for a in arrays)
    bounds = [total * k // stripes for k in range(stripes + 1)]
    groups: list[list[tuple[int, memoryview]]] = [[] for _ in range(stripes)]
    off = 0
    k = 0
    for a in arrays:
        view = _raw_view(a)
        start, end = off, off + a.nbytes
        while start < end:
            while bounds[k + 1] <= start:
                k += 1
            take = min(end, bounds[k + 1]) - start
            groups[k].append((start, view[start - off : start - off + take]))
            start += take
        off = end
    return [g for g in groups if g]


def _leaf_to_numpy(leaf: Any) -> np.ndarray:
    arr = np.asarray(leaf)
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr


def _dtype_name(dtype: np.dtype) -> str:
    # `.str` is lossy for extension dtypes (bfloat16 → "<V2"); the name round-trips.
    return dtype.name


def resolve_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including ml_dtypes extension types (bfloat16, fp8...)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _raw_view(a: np.ndarray) -> memoryview:
    # Extension dtypes (bfloat16) don't support the buffer protocol; uint8 view does.
    # Flatten first: a 0-d array can't change dtype via view.
    return memoryview(np.ascontiguousarray(a).reshape(-1).view(np.uint8)).cast("B")


def write_payload(
    path: str,
    hollow_bytes: bytes,
    tensors: Sequence[Any],
    meta: Optional[dict] = None,
    fsync: bool = True,
    stripes: Optional[int] = None,
) -> int:
    """Atomically write a checkpoint file; returns bytes written.

    ``stripes`` > 1 fans the payload out over N writer threads pwrite-ing
    byte-balanced contiguous leaf groups at their final offsets in the SAME
    container — the file an N-way write produces is byte-identical to the
    sequential one, so the read path never changes. ``None`` reads the
    ``$TPU_RESILIENCY_CKPT_STRIPES`` storage-class default (1).
    """
    stripes = _effective_stripes(stripes)
    arrays = [_leaf_to_numpy(t) for t in tensors]
    # Per-leaf + per-chunk CRCs computed from the source buffers BEFORE
    # anything touches disk: the checksums sign what the caller handed us, so
    # corruption anywhere downstream (the write path itself included) is
    # detectable.
    ck = Checksummer(b"")
    for a in arrays:
        ck.add_leaf(_raw_view(a))
    leaf_crcs = ck.leaf_crcs
    header = {
        "hollow": hollow_bytes,
        "leaves": [
            {
                "shape": a.shape,
                "dtype": _dtype_name(a.dtype),
                "nbytes": a.nbytes,
                "crc32c": c,
            }
            for a, c in zip(arrays, leaf_crcs)
        ],
        "meta": meta or {},
    }
    header_bytes = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    prefix = MAGIC + _LEN.pack(len(header_bytes)) + header_bytes
    trailer = build_trailer_v3(
        leaf_crcs, ck.chunk_crcs, ck.chunk_size,
        _container_crc_v3(prefix, leaf_crcs, ck.chunk_crcs),
    )
    tmp = path + DIRTY_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    base = len(prefix)
    payload = sum(a.nbytes for a in arrays)
    written = base + payload + len(trailer)
    with open(tmp, "wb") as f:
        _disk_write(f, prefix, path)
        # Byte-range striping splits within leaves, so even a single fused-
        # parameter leaf stripes; an all-empty payload yields no groups.
        groups = _partition_by_bytes(arrays, stripes) if stripes > 1 else []
        if not groups:
            for a in arrays:
                _disk_write(f, _raw_view(a), path)
        else:
            # Header leaves the buffered stream before any pwrite lands beyond it.
            f.flush()
            import concurrent.futures as cf

            fd = f.fileno()

            def run(group):
                for off, view in group:
                    _pwrite_full(fd, view, base + off, path)

            with cf.ThreadPoolExecutor(len(groups)) as pool:
                list(pool.map(run, groups))
            # The buffered stream's position is still at the header; land the
            # trailer after the pwrite-extended payload.
            f.seek(base + payload)
        _disk_write(f, trailer, path)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    _commit_atomic(tmp, path, fsync)
    return written


def write_blob(path: str, blob: bytes, fsync: bool = True, stripes: Optional[int] = None) -> None:
    """Atomically write an already-serialized container blob (its integrity
    trailer, when it is a v2 container, rides inside the blob verbatim),
    optionally striped (N threads pwrite-ing byte ranges — same knob and
    rationale as :func:`write_payload`)."""
    stripes = _effective_stripes(stripes)
    tmp = path + DIRTY_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if stripes == 1 or len(blob) < (1 << 20):
        with open(tmp, "wb") as f:
            _disk_write(f, blob, path)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
    else:
        import concurrent.futures as cf

        view = memoryview(blob)
        chunk = (len(blob) + stripes - 1) // stripes
        with open(tmp, "wb") as f:
            fd = f.fileno()

            def run(i: int) -> None:
                _pwrite_full(fd, view[i * chunk : (i + 1) * chunk], i * chunk, path)

            with cf.ThreadPoolExecutor(stripes) as pool:
                list(pool.map(run, range(stripes)))
            if fsync:
                os.fsync(fd)
    _commit_atomic(tmp, path, fsync)


def _read_prefix(f, source: str) -> tuple[bytes, dict, bytes]:
    """Read and parse the container head; returns ``(magic, header,
    raw_prefix_bytes)``. Every structural failure — wrong magic, truncated
    length field, undecodable header pickle — surfaces as
    :class:`CheckpointError` naming ``source``, so callers classify disk
    damage uniformly instead of leaking ``struct``/``pickle`` internals."""
    magic = f.read(len(MAGIC))
    if magic not in _MAGICS:
        raise CheckpointError(
            f"{source}: bad magic {magic[:8]!r} (not a tpu_resiliency checkpoint)"
        )
    raw_len = f.read(_LEN.size)
    if len(raw_len) != _LEN.size:
        raise CheckpointError(f"{source}: truncated container (no header length)")
    (hlen,) = _LEN.unpack(raw_len)
    header_bytes = f.read(hlen)
    if len(header_bytes) != hlen:
        raise CheckpointError(f"{source}: truncated container header")
    try:
        header = pickle.loads(header_bytes)
        for s in header["leaves"]:  # structural sanity before any payload read
            int(s["nbytes"])
    except Exception as e:
        raise CheckpointError(f"{source}: corrupt container header ({e!r})") from e
    return magic, header, magic + raw_len + header_bytes


def read_header(path: str) -> dict:
    with open(path, "rb") as f:
        return _read_prefix(f, path)[1]


def read_payload(path: str, verify: bool = True) -> tuple[bytes, list[np.ndarray], dict]:
    """Read (hollow_bytes, tensors, meta). Tensors come back as numpy arrays.

    v2 containers are verified streaming as they are read: each leaf's CRC is
    checked the moment its bytes leave the file, then the whole-file trailer
    digest; any mismatch raises :class:`CheckpointError` naming the path and
    the failing leaf. v1 containers (and v2 files signed by a checksum
    algorithm this host lacks) load with verification skipped and a
    ``ckpt_unverified`` event. ``verify=False`` skips checksum comparison
    (callers that already verified the same bytes, e.g. after a
    verify-on-receive retrieve)."""
    with open(path, "rb") as f:
        magic, header, prefix = _read_prefix(f, path)
        specs = header["leaves"]
        payload = sum(int(s["nbytes"]) for s in specs)
        info = None
        if magic != MAGIC_V1:
            info = _read_file_trailer(f, magic, specs, len(prefix), path)
            f.seek(len(prefix))
            if verify and not info.verifiable:
                _record_unverified(path, reason=f"algo:{info.algo!r}")
                info = None
            elif not verify:
                info = None
        elif verify:
            _record_unverified(path, reason="format-v1")
        leaf_crcs = info.leaf_crcs if info is not None else None
        tensors = []
        for i, spec in enumerate(specs):
            buf = f.read(spec["nbytes"])
            if len(buf) != spec["nbytes"]:
                raise CheckpointError(f"{path}: truncated payload")
            if leaf_crcs is not None and crc32c(buf) != leaf_crcs[i]:
                raise CheckpointError(
                    f"{path}: leaf {i} checksum mismatch (payload corrupted)"
                )
            tensors.append(
                np.frombuffer(buf, dtype=resolve_dtype(spec["dtype"])).reshape(spec["shape"])
            )
        if info is not None and _expected_digest(info, prefix) != info.container_crc:
            raise CheckpointError(
                f"{path}: container digest mismatch (header or trailer corrupted)"
            )
    return header["hollow"], tensors, header.get("meta", {})


def _read_file_trailer(
    f, magic: bytes, specs: Sequence[dict], prefix_len: int, source: str
) -> TrailerInfo:
    """Seek-and-parse a v2/v3 file trailer with the size cross-check (the
    truncation/torn-file detector); leaves the file position at the trailer."""
    leaf_sizes = [int(s["nbytes"]) for s in specs]
    payload = sum(leaf_sizes)
    size = os.fstat(f.fileno()).st_size
    tsize = size - prefix_len - payload
    want = (
        trailer_size(len(specs)) if magic == MAGIC_V2
        else None  # v3 trailer size depends on the recorded chunk size
    )
    if tsize <= 0 or (want is not None and tsize != want):
        raise CheckpointError(
            f"{source}: container size mismatch ({size} bytes for "
            f"{prefix_len + payload} of head+payload) — truncated or torn file"
        )
    f.seek(prefix_len + payload)
    info = parse_trailer_any(f.read(tsize), magic, leaf_sizes, source)
    if magic == MAGIC and tsize != trailer_size_v3(
        len(leaf_sizes), len(info.chunk_crcs)
    ):
        raise CheckpointError(
            f"{source}: container size mismatch (trailer region {tsize} B "
            f"disagrees with manifest) — truncated or torn file"
        )
    return info


def header_prefix(
    hollow_bytes: bytes, specs: Sequence[dict], meta: dict | None = None
) -> bytes:
    """The ``MAGIC | header_len | header`` container head built from leaf SPECS
    alone (``{"shape", "dtype", "nbytes"}`` per leaf) — no host arrays needed.

    This is what lets the pipelined save commit to the container layout while
    every leaf's D2H transfer is still in flight: specs come straight off the
    device arrays' metadata, the prefix goes out to files and peer streams
    first, and the payload bytes follow as they resolve. Writers building a
    prefix this way learn leaf CRCs only as leaves resolve, so their specs
    carry no ``crc32c`` keys — the trailer (fed by a :class:`Checksummer`
    over the same pass) is the authoritative checksum record; specs FROM
    materialized writers pass their known CRCs through."""
    header = {
        "hollow": hollow_bytes,
        "leaves": [
            {
                "shape": tuple(s["shape"]),
                "dtype": str(s["dtype"]),
                "nbytes": int(s["nbytes"]),
                **({"crc32c": int(s["crc32c"])} if "crc32c" in s else {}),
            }
            for s in specs
        ],
        "meta": meta or {},
    }
    header_bytes = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    return MAGIC + _LEN.pack(len(header_bytes)) + header_bytes


def serialize_parts(
    hollow_bytes: bytes, tensors: Sequence[Any], meta: dict | None = None
) -> tuple[bytes, list[memoryview]]:
    """Container as ``(prefix_bytes, [leaf byte views])`` — the zero-copy form.

    The prefix is the small ``MAGIC | header_len | header`` head; the views are
    raw uint8 windows over each leaf's host buffer, followed by one small
    ``bytes`` part: the v2 integrity trailer (per-leaf CRCs + whole-file
    digest, computed here from the source buffers). Concatenating
    ``prefix + views`` yields exactly :func:`serialize_to_bytes`'s blob, but no
    joined copy ever exists: senders scatter-gather the parts straight onto a
    socket (``framing.send_bulk``) and writers stream them to a file
    (:func:`write_parts`). The views alias the input tensors — keep those alive
    (and unmutated) until the parts are consumed: the recorded CRCs sign the
    bytes as they are NOW.
    """
    arrays = [_leaf_to_numpy(t) for t in tensors]
    views = [_raw_view(a) for a in arrays]
    ck = Checksummer(b"")
    for v in views:
        ck.add_leaf(v)
    leaf_crcs = ck.leaf_crcs
    prefix = header_prefix(
        hollow_bytes,
        [
            {
                "shape": a.shape,
                "dtype": _dtype_name(a.dtype),
                "nbytes": a.nbytes,
                "crc32c": c,
            }
            for a, c in zip(arrays, leaf_crcs)
        ],
        meta,
    )
    trailer = build_trailer_v3(
        leaf_crcs, ck.chunk_crcs, ck.chunk_size,
        _container_crc_v3(prefix, leaf_crcs, ck.chunk_crcs),
    )
    return prefix, [*views, trailer]


def parts_nbytes(prefix: bytes, views: Sequence[Any]) -> int:
    """Total container size of a :func:`serialize_parts` result."""
    return len(prefix) + sum(memoryview(v).cast("B").nbytes for v in views)


def serialize_to_bytes(hollow_bytes: bytes, tensors: Sequence[Any], meta: dict | None = None) -> bytes:
    """In-memory form of the container (compat path for whole-blob consumers;
    the replication hot path uses :func:`serialize_parts` and never joins)."""
    prefix, views = serialize_parts(hollow_bytes, tensors, meta)
    return b"".join([prefix, *views])


def _chunk_view(chunk: Any) -> memoryview:
    """Flat uint8 view of any stream chunk — bytes-likes directly, numpy arrays
    through the extension-dtype-safe reinterpret (bfloat16 has no buffer
    protocol)."""
    if isinstance(chunk, np.ndarray):
        return _raw_view(chunk)
    return memoryview(chunk).cast("B")


def write_stream(path: str, chunks, fsync: bool = True) -> int:
    """Atomically stream container chunks to ``path`` as they become available.

    ``chunks`` is any iterable of bytes-likes or numpy arrays — typically a
    header prefix followed by leaves resolving off the D2H queue, which is how
    the pipelined save overlaps disk IO with the device transfers: each leaf
    hits the file the moment its DMA lands, not after a full-tree barrier.
    Same ``.dirty``-then-rename commit as every other writer: a producer
    raising mid-stream leaves only the ``.dirty`` temp file (the crash contract
    startup cleanup already handles), never a torn visible container. Chunks
    are written verbatim — a v2 producer appends its own trailer chunk (drive
    a :class:`Checksummer` over the prefix and leaves, then yield
    ``ck.trailer()`` last). Returns bytes written."""
    tmp = path + DIRTY_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    written = 0
    with open(tmp, "wb") as f:
        for chunk in chunks:
            written += _disk_write(f, _chunk_view(chunk), path)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    _commit_atomic(tmp, path, fsync)
    return written


def write_parts(path: str, parts: Sequence[Any], fsync: bool = True) -> int:
    """Atomically stream already-serialized container parts to ``path`` — the
    ``.dirty``-then-rename protocol of :func:`write_blob` without requiring a
    joined blob (a receive buffer, a :func:`serialize_parts` result, or any mix
    of bytes-likes). Returns bytes written."""
    return write_stream(path, parts, fsync=fsync)


def _parse_buffer_prefix(mv: memoryview, source: str) -> tuple[bytes, dict, int]:
    """Buffer counterpart of :func:`_read_prefix`; returns ``(magic, header,
    payload_offset)`` with the same uniform :class:`CheckpointError`
    classification."""
    if mv.nbytes < len(MAGIC) + _LEN.size:
        raise CheckpointError(f"{source}: truncated serialized checkpoint blob")
    magic = bytes(mv[: len(MAGIC)])
    if magic not in _MAGICS:
        raise CheckpointError(f"{source}: bad magic in serialized checkpoint blob")
    off = len(MAGIC)
    (hlen,) = _LEN.unpack(mv[off : off + _LEN.size])
    off += _LEN.size
    if off + hlen > mv.nbytes:
        raise CheckpointError(f"{source}: truncated serialized checkpoint blob")
    try:
        header = pickle.loads(mv[off : off + hlen])
        for s in header["leaves"]:
            int(s["nbytes"])
    except Exception as e:
        raise CheckpointError(f"{source}: corrupt container header ({e!r})") from e
    return magic, header, off + hlen


def deserialize_from_buffer(
    buf, verify: bool = True, source: str = "buffer"
) -> tuple[bytes, list[np.ndarray], dict]:
    """Zero-copy deserialization: tensors come back as views over ``buf``.

    ``buf`` is any bytes-like (typically the single receive buffer a bulk frame
    landed in); each leaf is ``np.frombuffer`` over a ``memoryview`` slice, so
    no per-leaf copies are made. The arrays alias ``buf`` — they are read-only
    when ``buf`` is, and mutating ``buf`` mutates them. Callers that outlive the
    buffer (or need writable tensors from an immutable source) copy explicitly.

    v2 blobs are checksum-verified against their trailer (one streaming pass;
    mismatch raises :class:`CheckpointError`); pass ``verify=False`` when the
    same bytes were already verified (e.g. by a verify-on-receive retrieve).
    v1 blobs load unverified with a ``ckpt_unverified`` event.
    """
    mv = memoryview(buf).cast("B")
    magic, header, off = _parse_buffer_prefix(mv, source)
    prefix = mv[:off]
    info = None
    if magic != MAGIC_V1:
        payload = sum(int(s["nbytes"]) for s in header["leaves"])
        info = _buffer_trailer(mv, magic, header["leaves"], off, payload, source)
        if verify and not info.verifiable:
            _record_unverified(source, reason=f"algo:{info.algo!r}")
            info = None
        elif not verify:
            info = None
    elif verify:
        _record_unverified(source, reason="format-v1")
    leaf_crcs = info.leaf_crcs if info is not None else None
    tensors = []
    for i, spec in enumerate(header["leaves"]):
        n = spec["nbytes"]
        if off + n > mv.nbytes:
            raise CheckpointError(f"{source}: truncated serialized checkpoint blob")
        window = mv[off : off + n]
        if leaf_crcs is not None and crc32c(window) != leaf_crcs[i]:
            raise CheckpointError(
                f"{source}: leaf {i} checksum mismatch (payload corrupted)"
            )
        tensors.append(
            np.frombuffer(window, dtype=resolve_dtype(spec["dtype"])).reshape(
                spec["shape"]
            )
        )
        off += n
    if info is not None and _expected_digest(info, prefix) != info.container_crc:
        raise CheckpointError(
            f"{source}: container digest mismatch (header or trailer corrupted)"
        )
    return header["hollow"], tensors, header.get("meta", {})


def _buffer_trailer(
    mv: memoryview, magic: bytes, specs: Sequence[dict], off: int,
    payload: int, source: str,
) -> TrailerInfo:
    """Locate and parse the trailer inside a serialized blob (the blob may
    carry a surplus tail — an oversized registered receive buffer)."""
    leaf_sizes = [int(s["nbytes"]) for s in specs]
    start = off + payload
    if magic == MAGIC_V2:
        tsize = trailer_size(len(specs))
    else:
        if start + _V3_FIXED > mv.nbytes:
            raise CheckpointError(
                f"{source}: truncated serialized checkpoint blob"
            )
        head = mv[start : start + _V3_FIXED]
        if bytes(head[: len(TRAILER_MAGIC_V3)]) != TRAILER_MAGIC_V3:
            raise CheckpointError(
                f"{source}: v3 integrity trailer missing or corrupt"
            )
        _, nleaves, nchunks = struct.unpack(
            "<3I", head[len(TRAILER_MAGIC_V3) + 4 :]
        )
        tsize = trailer_size_v3(nleaves, nchunks)
    if start + tsize > mv.nbytes:
        raise CheckpointError(f"{source}: truncated serialized checkpoint blob")
    return parse_trailer_any(mv[start : start + tsize], magic, leaf_sizes, source)


def deserialize_from_bytes(blob) -> tuple[bytes, list[np.ndarray], dict]:
    """Alias of :func:`deserialize_from_buffer` (kept for callers written against
    the pre-streaming API; both are zero-copy over the input buffer now)."""
    return deserialize_from_buffer(blob)


# -- standalone verification --------------------------------------------------


def verify_container(buf, source: str = "frame") -> bool:
    """Integrity-check a serialized container without materializing tensors —
    the verify-on-receive primitive replication receivers run on every frame.

    Returns ``True`` when every leaf CRC and the container digest verified;
    ``False`` when the payload is unverifiable — a v1 container (one
    ``ckpt_unverified`` event), a v2 file signed by a checksum algorithm this
    host lacks, or not a container at all (replication also moves raw blobs
    in tests/tools). Raises :class:`CheckpointError` on checksum mismatch or
    structural corruption of a v2 container."""
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    if mv.nbytes < len(MAGIC) or bytes(mv[: len(MAGIC)]) not in _MAGICS:
        return False
    magic, header, off = _parse_buffer_prefix(mv, source)
    if magic == MAGIC_V1:
        _record_unverified(source, reason="format-v1")
        return False
    specs = header["leaves"]
    payload = sum(int(s["nbytes"]) for s in specs)
    info = _buffer_trailer(mv, magic, specs, off, payload, source)
    if not info.verifiable:
        _record_unverified(source, reason=f"algo:{info.algo!r}")
        return False
    pos = off
    for i, spec in enumerate(specs):
        n = int(spec["nbytes"])
        if crc32c(mv[pos : pos + n]) != info.leaf_crcs[i]:
            raise CheckpointError(
                f"{source}: leaf {i} checksum mismatch (payload corrupted)"
            )
        pos += n
    if _expected_digest(info, mv[:off]) != info.container_crc:
        raise CheckpointError(
            f"{source}: container digest mismatch (header or trailer corrupted)"
        )
    return True


def verify_file(path: str, chunk: int = 4 << 20) -> tuple[str, str]:
    """Stream-verify one container file with bounded memory (``chunk`` bytes
    at a time regardless of leaf sizes) — the ``ckpt_info --verify`` engine.

    Returns ``(status, detail)`` with status one of ``"ok"`` (every CRC
    verified), ``"unverified"`` (v1 container or foreign checksum algorithm —
    structurally intact but unsigned for this host), or ``"corrupt"``
    (checksum mismatch, truncation, or structural damage). Never raises for
    a damaged file — the verdict IS the result."""
    try:
        with open(path, "rb") as f:
            magic, header, prefix = _read_prefix(f, path)
            specs = header["leaves"]
            payload = sum(int(s["nbytes"]) for s in specs)
            size = os.fstat(f.fileno()).st_size
            if magic == MAGIC_V1:
                if size < len(prefix) + payload:
                    return "corrupt", (
                        f"truncated v1 payload ({size} bytes, want at least "
                        f"{len(prefix) + payload})"
                    )
                return "unverified", "format v1 (no checksums recorded)"
            info = _read_file_trailer(f, magic, specs, len(prefix), path)
            if not info.verifiable:
                return "unverified", (
                    f"signed with algorithm tag {info.algo!r}; this host "
                    f"verifies {_ALGO_TAG!r} ({CRC_ALGO})"
                )
            f.seek(len(prefix))
            if info.chunk_crcs is not None:
                # v3: one streaming pass checks the chunk manifest AND the
                # leaf records (a chunk-aligned read feeds both).
                flat = 0
                for i, spec in enumerate(specs):
                    remaining = int(spec["nbytes"])
                    crc = 0
                    while remaining:
                        buf = f.read(min(info.chunk_size, remaining))
                        if not buf:
                            return "corrupt", f"leaf {i}: short read"
                        if crc32c(buf) != info.chunk_crcs[flat]:
                            return "corrupt", (
                                f"leaf {i} chunk {flat} checksum mismatch"
                            )
                        flat += 1
                        crc = crc32c(buf, crc)
                        remaining -= len(buf)
                    if crc != info.leaf_crcs[i]:
                        return "corrupt", f"leaf {i} checksum mismatch"
            else:
                for i, spec in enumerate(specs):
                    remaining = int(spec["nbytes"])
                    crc = 0
                    while remaining:
                        buf = f.read(min(chunk, remaining))
                        if not buf:
                            return "corrupt", f"leaf {i}: short read"
                        crc = crc32c(buf, crc)
                        remaining -= len(buf)
                    if crc != info.leaf_crcs[i]:
                        return "corrupt", f"leaf {i} checksum mismatch"
            if _expected_digest(info, prefix) != info.container_crc:
                return "corrupt", "container digest mismatch (header/trailer)"
            detail = f"{len(specs)} leaves, {payload} payload bytes ({CRC_ALGO})"
            if info.chunk_crcs is not None:
                detail += (
                    f", {len(info.chunk_crcs)} chunks @ {info.chunk_size} B"
                )
            return "ok", detail
    except CheckpointError as e:
        return "corrupt", str(e)
    except OSError as e:
        return "corrupt", f"unreadable: {e}"


def read_trailer(path: str) -> tuple[dict, int, Optional[TrailerInfo]]:
    """Parse a container's header AND trailer without touching the payload:
    ``(header, prefix_len, TrailerInfo-or-None)`` — two small reads. This is
    the chunk-granular serve path's geometry source: a v3 container's chunk
    manifest loads in O(trailer) so ranged reads can verify O(range) instead
    of paying a whole-file pass. ``None`` trailer = a v1 container."""
    with open(path, "rb") as f:
        magic, header, prefix = _read_prefix(f, path)
        if magic == MAGIC_V1:
            return header, len(prefix), None
        info = _read_file_trailer(f, magic, header["leaves"], len(prefix), path)
        # The digest covers the trailer entries themselves: recompute it from
        # the parsed records so a bit-flipped manifest can't vouch for chunks.
        if info.verifiable and _expected_digest(info, prefix) != info.container_crc:
            raise CheckpointError(
                f"{path}: container digest mismatch (header or trailer corrupted)"
            )
        return header, len(prefix), info


def chunk_report(path: str) -> dict:
    """Per-chunk verification report (the ``ckpt_info --chunks`` engine):
    ``{"status", "chunk_size", "leaves": [{"nbytes", "chunks", "bad": [...]}]}``
    — v2/v1 containers report ``chunk_size: None`` (no manifest)."""
    status, detail = verify_file(path)
    out: dict = {"status": status, "detail": detail, "chunk_size": None,
                 "leaves": []}
    try:
        header, prefix_len, info = read_trailer(path)
    except (CheckpointError, OSError):
        return out
    if info is None or info.chunk_crcs is None or not info.verifiable:
        return out
    out["chunk_size"] = info.chunk_size
    with open(path, "rb") as f:
        f.seek(prefix_len)
        flat = 0
        for spec in header["leaves"]:
            remaining = int(spec["nbytes"])
            nchunks = leaf_chunk_count(remaining, info.chunk_size)
            bad: list[int] = []
            for c in range(nchunks):
                buf = f.read(min(info.chunk_size, remaining))
                if len(buf) != min(info.chunk_size, remaining) or crc32c(
                    buf
                ) != info.chunk_crcs[flat]:
                    bad.append(c)
                flat += 1
                remaining -= len(buf)
            out["leaves"].append(
                {"nbytes": int(spec["nbytes"]), "chunks": nchunks, "bad": bad}
            )
    return out
