"""Clique replication of local checkpoint shards across ranks.

Re-design of the reference's replication layer
(``checkpointing/local/replication/strategies.py:76-188`` and ``group_utils.py``): local
checkpoints live on node-local storage, so a lost node loses its shard — unless each
shard is mirrored within a small *clique* of ranks chosen to span failure domains.
``replication_jump`` spaces clique members apart (set it to ranks-per-host so mirrors
land on different hosts / ICI slices); ``replication_factor`` is the mirror count.

Data moves over :class:`~tpu_resiliency.checkpoint.comm.PeerExchange` TCP links (DCN,
not ICI — the training mesh never sees checkpoint traffic); membership math is pure
Python. Retrieval builds an :class:`ExchangePlan` — who sends which shard to whom —
from a store-gathered availability map, mirroring ``group_utils.py:57,466``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


def parse_group_sequence(
    replication_jump: int, replication_factor: int, world_size: int
) -> list[list[int]]:
    """Partition ``range(world_size)`` into cliques of ``replication_factor`` ranks
    spaced ``replication_jump`` apart (reference ``group_utils.py:124``).

    Example: jump=2, factor=2, world=8 → [[0,2],[1,3],[4,6],[5,7]].
    """
    if replication_factor < 1:
        raise ValueError("replication_factor must be >= 1")
    if replication_jump < 1:
        raise ValueError("replication_jump must be >= 1")
    block = replication_jump * replication_factor
    if world_size % block != 0:
        raise ValueError(
            f"world_size {world_size} not divisible by "
            f"replication_jump*replication_factor = {block}"
        )
    groups = []
    for base in range(0, world_size, block):
        for offset in range(replication_jump):
            groups.append(
                [base + offset + k * replication_jump for k in range(replication_factor)]
            )
    return groups


def group_of(rank: int, groups: Sequence[Sequence[int]]) -> list[int]:
    for g in groups:
        if rank in g:
            return list(g)
    raise ValueError(f"rank {rank} not in any replication group")


@dataclasses.dataclass
class ExchangePlan:
    """Shard routing for retrieval: per-rank send and receive lists.

    ``sends[r]`` = list of ``(dst_rank, shard_owner_rank)`` that rank ``r`` must send;
    ``recvs[r]`` = list of ``(src_rank, shard_owner_rank)`` that rank ``r`` will receive.
    """

    sends: dict[int, list[tuple[int, int]]]
    recvs: dict[int, list[tuple[int, int]]]

    @staticmethod
    def build(
        wanted: dict[int, int],
        holders: dict[int, set[int]],
        avoid: frozenset[int] | set[int] = frozenset(),
    ) -> "ExchangePlan":
        """``wanted[rank] = owner_rank_of_needed_shard`` (skip ranks that hold their own);
        ``holders[rank] = set of owner-ranks whose shards rank holds locally``.

        Holder choice is deterministic and load-balanced: among candidates, pick the one
        with the fewest sends assigned so far, ties broken by rank order (the reference
        picks a random live holder, ``strategies.py:142-188``; deterministic choice keeps
        every rank's independently-computed plan identical without a broadcast).

        ``avoid``: ranks the health-vector policy holds degraded — they are chosen as
        senders only when no healthy holder exists (recovery should never queue behind
        the slowest NIC in the clique; BASELINE target 5).
        """
        sends: dict[int, list[tuple[int, int]]] = {}
        recvs: dict[int, list[tuple[int, int]]] = {}
        load: dict[int, int] = {}
        for dst in sorted(wanted):
            owner = wanted[dst]
            candidates = sorted(r for r, held in holders.items() if owner in held and r != dst)
            if not candidates:
                raise CheckpointError(
                    f"no live holder for shard of rank {owner} needed by rank {dst}"
                )
            src = min(candidates, key=lambda r: (r in avoid, load.get(r, 0), r))
            load[src] = load.get(src, 0) + 1
            sends.setdefault(src, []).append((dst, owner))
            recvs.setdefault(dst, []).append((src, owner))
        return ExchangePlan(sends=sends, recvs=recvs)


class CliqueReplicationStrategy:
    """Mirror each rank's shard across its clique; route shards back after rank loss.

    ``replicate(blob)`` returns ``{owner_rank: blob}`` for every clique member — the
    caller persists all of them locally (reference ``strategies.py:87-140``'s hollow
    all-gather + batched tensor all-gather, collapsed into whole-shard exchange over
    host TCP links).

    ``retrieve(wanted, available, payload_fn)`` executes a global exchange plan so every
    rank ends up holding the shard it needs (reference ``strategies.py:142-188``).
    """

    def __init__(
        self,
        comm: StoreComm,
        exchange: PeerExchange,
        replication_jump: int = 1,
        replication_factor: int = 2,
    ):
        self.comm = comm
        self.exchange = exchange
        self.jump = replication_jump
        self.factor = replication_factor
        self.groups = parse_group_sequence(
            replication_jump, replication_factor, comm.world_size
        )
        self.my_group = group_of(comm.rank, self.groups)
        self._round = 0

    @property
    def enabled(self) -> bool:
        return self.factor > 1

    def replicate(self, blob: bytes) -> dict[int, bytes]:
        """Exchange shard blobs within the clique. Returns {owner_rank: blob}."""
        rank = self.comm.rank
        held = {rank: blob}
        if not self.enabled:
            return held
        tag = f"repl/{self._round}"
        self._round += 1
        for peer in self.my_group:
            if peer != rank:
                self.exchange.send(peer, tag, blob)
        for peer in self.my_group:
            if peer != rank:
                held[peer] = self.exchange.recv(peer, tag)
        return held

    def retrieve(
        self,
        my_needed_owner: Optional[int],
        my_held_owners: set[int],
        get_blob,
        avoid: frozenset[int] | set[int] = frozenset(),
    ) -> Optional[bytes]:
        """Global shard routing after rank loss / reassignment.

        ``my_needed_owner``: owner-rank of the shard this rank needs but does not hold
        (``None`` if satisfied locally). ``my_held_owners``: owner-ranks of shards held
        locally. ``get_blob(owner)`` loads a held shard's bytes for sending. All ranks
        must call this collectively with the same ``avoid`` set (degraded ranks are
        deprioritized as senders). Returns the received blob, or ``None``.
        """
        gathered = self.comm.all_gather(
            (self.comm.rank, my_needed_owner, sorted(my_held_owners)), tag="retrieve-meta"
        )
        wanted = {r: need for r, need, _ in gathered if need is not None}
        holders = {r: set(held) for r, _, held in gathered}
        if not wanted:
            return None
        plan = ExchangePlan.build(wanted, holders, avoid=avoid)
        tag = f"retr/{self._round}"
        self._round += 1
        for dst, owner in plan.sends.get(self.comm.rank, []):
            self.exchange.send(dst, f"{tag}/{owner}", get_blob(owner))
        blob = None
        for src, owner in plan.recvs.get(self.comm.rank, []):
            blob = self.exchange.recv(src, f"{tag}/{owner}")
        return blob
