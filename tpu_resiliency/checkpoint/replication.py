"""Clique replication of local checkpoint shards across ranks.

Re-design of the reference's replication layer
(``checkpointing/local/replication/strategies.py:76-188`` and ``group_utils.py``): local
checkpoints live on node-local storage, so a lost node loses its shard — unless each
shard is mirrored within a small *clique* of ranks chosen to span failure domains.
``replication_jump`` spaces clique members apart (set it to ranks-per-host so mirrors
land on different hosts / ICI slices); ``replication_factor`` is the mirror count.

Data moves over :class:`~tpu_resiliency.checkpoint.comm.PeerExchange` TCP links (DCN,
not ICI — the training mesh never sees checkpoint traffic); membership math is pure
Python. Retrieval builds an :class:`ExchangePlan` — who sends which shard to whom —
from a store-gathered availability map, mirroring ``group_utils.py:57,466``.
"""

from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import time
from typing import Any, Callable, Optional, Sequence

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.tracing import span

log = get_logger(__name__)


def _verify_received(payload, src: int, stage: str) -> bool:
    """Verify-on-receive: checksum a peer-delivered container against its v2
    trailer. Returns True to keep the payload; False (after one
    ``ckpt_integrity_failure`` event → ``tpu_ckpt_integrity_failures_total``)
    to treat the frame like a degraded peer's — dropped, never loaded.
    Payloads that aren't v2 containers (v1 format, raw blobs) pass through
    unverified; the format layer records those separately."""
    try:
        ckpt_format.verify_container(payload, source=f"{stage}<-rank{src}")
        return True
    except CheckpointError as e:
        log.warning(
            f"replication: dropping corrupt frame from rank {src} "
            f"({stage}): {e}"
        )
        record_event(
            "checkpoint", "ckpt_integrity_failure", stage=stage, src=src,
            error=repr(e),
        )
        return False


def _fan_out(sends: list[Callable[[], Any]]) -> None:
    """Run peer sends concurrently; first failure propagates.

    The serial peer loop paid full wire time per peer; concurrent sends overlap
    them so a round's send side costs ~one shard transfer regardless of clique
    size (the network analogue of the reference's per-bucket writer fan-out,
    ``filesystem_async.py:232-334``). Per-call executor: rounds are minutes
    apart and move GBs — thread spawn is noise, and there is no pool lifecycle
    to leak.
    """
    if not sends:
        return
    if len(sends) == 1:
        sends[0]()
        return
    with cf.ThreadPoolExecutor(max_workers=len(sends)) as pool:
        for f in [pool.submit(s) for s in sends]:
            f.result()


def parse_group_sequence(
    replication_jump: int, replication_factor: int, world_size: int
) -> list[list[int]]:
    """Partition ``range(world_size)`` into cliques of ``replication_factor`` ranks
    spaced ``replication_jump`` apart (reference ``group_utils.py:124``).

    Example: jump=2, factor=2, world=8 → [[0,2],[1,3],[4,6],[5,7]].
    """
    if replication_factor < 1:
        raise ValueError("replication_factor must be >= 1")
    if replication_jump < 1:
        raise ValueError("replication_jump must be >= 1")
    block = replication_jump * replication_factor
    if world_size % block != 0:
        raise ValueError(
            f"world_size {world_size} not divisible by "
            f"replication_jump*replication_factor = {block}"
        )
    return group_sequence_for(range(world_size), replication_jump, replication_factor)


def group_sequence_for(
    active_ranks: Sequence[int], replication_jump: int, replication_factor: int
) -> list[list[int]]:
    """Cliques over an ARBITRARY active rank set — the post-reassignment worlds
    this framework produces are rarely ``range(n)`` and rarely divisible.

    Full blocks follow :func:`parse_group_sequence`'s jump spacing over *positions*
    in the sorted active list (positions, not rank ids: after a shrink the
    survivors' ids have gaps, but failure domains follow physical placement order).
    Remainder ranks merge into the last full-spacing clique when one exists
    (slightly larger clique beats an unmirrored shard); with no full block they
    form consecutive cliques of up to ``replication_factor``.
    """
    if replication_factor < 1:
        raise ValueError("replication_factor must be >= 1")
    if replication_jump < 1:
        raise ValueError("replication_jump must be >= 1")
    ranks = sorted(active_ranks)
    n = len(ranks)
    block = replication_jump * replication_factor
    full_end = (n // block) * block
    groups: list[list[int]] = []
    for base in range(0, full_end, block):
        for offset in range(replication_jump):
            groups.append(
                [
                    ranks[base + offset + k * replication_jump]
                    for k in range(replication_factor)
                ]
            )
    rem = ranks[full_end:]
    if rem:
        if groups:
            groups[-1].extend(rem)
        else:
            for i in range(0, len(rem), replication_factor):
                groups.append(rem[i : i + replication_factor])
            # A singleton tail clique would hold ZERO mirrors — the data loss
            # replication exists to prevent. Fold it into its neighbor.
            if len(groups) >= 2 and len(groups[-1]) == 1:
                groups[-2].extend(groups.pop())
    return groups


def group_of(rank: int, groups: Sequence[Sequence[int]]) -> list[int]:
    for g in groups:
        if rank in g:
            return list(g)
    raise ValueError(f"rank {rank} not in any replication group")


@dataclasses.dataclass
class ExchangePlan:
    """Shard routing for retrieval: per-rank send and receive lists.

    ``sends[r]`` = list of ``(dst_rank, shard_owner_rank)`` that rank ``r`` must send;
    ``recvs[r]`` = list of ``(src_rank, shard_owner_rank)`` that rank ``r`` will receive.
    """

    sends: dict[int, list[tuple[int, int]]]
    recvs: dict[int, list[tuple[int, int]]]

    @staticmethod
    def build(
        wanted: dict[int, int],
        holders: dict[int, set[int]],
        avoid: frozenset[int] | set[int] = frozenset(),
    ) -> "ExchangePlan":
        """``wanted[rank] = owner_rank_of_needed_shard`` (skip ranks that hold their own);
        ``holders[rank] = set of owner-ranks whose shards rank holds locally``.

        Holder choice is deterministic and load-balanced: among candidates, pick the one
        with the fewest sends assigned so far, ties broken by rank order (the reference
        picks a random live holder, ``strategies.py:142-188``; deterministic choice keeps
        every rank's independently-computed plan identical without a broadcast).

        ``avoid``: ranks the health-vector policy holds degraded — they are chosen as
        senders only when no healthy holder exists (recovery should never queue behind
        the slowest NIC in the clique; BASELINE target 5).
        """
        sends: dict[int, list[tuple[int, int]]] = {}
        recvs: dict[int, list[tuple[int, int]]] = {}
        load: dict[int, int] = {}
        for dst in sorted(wanted):
            owner = wanted[dst]
            candidates = sorted(r for r, held in holders.items() if owner in held and r != dst)
            if not candidates:
                raise CheckpointError(
                    f"no live holder for shard of rank {owner} needed by rank {dst}"
                )
            src = min(candidates, key=lambda r: (r in avoid, load.get(r, 0), r))
            load[src] = load.get(src, 0) + 1
            sends.setdefault(src, []).append((dst, owner))
            recvs.setdefault(dst, []).append((src, owner))
        return ExchangePlan(sends=sends, recvs=recvs)


@dataclasses.dataclass
class PendingRound:
    """A minted-but-not-yet-run replication round (tag agreement done on the
    caller thread, transfer deferred — see ``start_round``). Inert when the
    strategy is disabled or the clique has no peers. ``iteration`` is stamped
    by the caller when the payload must self-describe (erasure block
    artifacts carry it); the mirror strategy ignores it."""

    tag: Optional[str]
    peers: list[int]
    round: int
    iteration: int = -1

    @property
    def active(self) -> bool:
        return self.tag is not None and bool(self.peers)


class CliqueReplicationStrategy:
    """Mirror each rank's shard across its clique; route shards back after rank loss.

    ``replicate(blob)`` returns ``{owner_rank: blob}`` for every clique member — the
    caller persists all of them locally (reference ``strategies.py:87-140``'s hollow
    all-gather + batched tensor all-gather, collapsed into whole-shard exchange over
    host TCP links).

    ``retrieve(wanted, available, payload_fn)`` executes a global exchange plan so every
    rank ends up holding the shard it needs (reference ``strategies.py:142-188``).
    """

    def __init__(
        self,
        comm: Optional[StoreComm],
        exchange: PeerExchange,
        replication_jump: int = 1,
        replication_factor: int = 2,
    ):
        self.comm = comm
        self.exchange = exchange
        self.jump = replication_jump
        self.factor = replication_factor
        #: Exchange tags embed this counter; every member of a group must agree
        #: on it (same number of replicate/retrieve/remirror calls), or peers
        #: wait on tags that are never sent. ``rebuild`` resets it so survivors
        #: and freshly constructed joiners re-align at 0.
        self._round = 0
        #: Peers that exhausted their transfer retries in the LAST replicate
        #: round — that round saved with reduced redundancy instead of failing.
        #: Callers feed this into :meth:`retrieve`'s ``avoid`` set (the
        #: ``ExchangePlan`` deprioritizes degraded senders) and should treat a
        #: persistently non-empty set as a health signal.
        self.last_degraded: set[int] = set()
        if comm is not None:
            self._set_groups(comm.ranks)
        else:
            self.groups = None
            self.my_group = None

    def _set_groups(self, active_ranks: Sequence[int]) -> None:
        self.groups = group_sequence_for(active_ranks, self.jump, self.factor)
        self.my_group = group_of(self.comm.rank, self.groups)

    def rebuild(self, comm: StoreComm) -> None:
        """Recompute cliques after rank reassignment.

        Call collectively from every surviving rank with the NEW group's comm
        (the old group includes dead ranks, whose barriers would hang). The
        reference sidesteps this by fixing groups for the job's lifetime
        (``strategies.py:76-140``); a framework whose health policy *changes* the
        active set owns the rebuild. Follow with :meth:`remirror` so shards whose
        old mirrors died are covered again before the next failure.
        """
        self.comm = comm
        self._set_groups(comm.ranks)
        # Survivors carry arbitrary _round values; joiners constructed fresh sit
        # at 0. Tags must agree across the new group, and rebuild is the one
        # moment every member is provably at the same point — re-align here.
        self._round = 0
        self.last_degraded = set()  # the old world's degradations are history
        # Tags restart at 0, so frames from abandoned pre-rebuild rounds (a peer
        # died mid-replicate; nobody will ever recv them) must not linger: they
        # pin multi-GB payloads in the exchange inbox forever AND would be
        # mis-delivered to the new world's round 0 under the reused tag.
        for prefix in ("repl/", "retr/", "remir/"):
            self.exchange.purge(prefix)
        log.info(
            f"replication cliques rebuilt over {comm.ranks}: my_group={self.my_group}"
        )

    def remirror(
        self,
        my_iteration: Optional[int],
        get_blob,
        held: frozenset[tuple[int, int]] | set[tuple[int, int]] = frozenset(),
        get_path=None,
    ) -> dict[int, tuple[int, bytes]]:
        """Re-mirror shards within the (rebuilt) cliques. Collective over the comm.

        ``my_iteration``: newest iteration of this rank's OWN shard on local disk
        (``None`` when it has none — a fresh joiner participates as receiver
        only). ``get_blob(owner, iteration)`` loads a locally-held shard's bytes;
        ``get_path(owner, iteration)`` (optional) names its on-disk file, letting
        sends splice file→socket via ``sendfile`` with zero userspace copies.
        ``held``: the ``(owner, iteration)`` pairs already on this rank's disk —
        a peer that already holds a mirror is skipped (after a shrink, surviving
        clique pairs keep their existing multi-GB mirrors; only shards that lost
        redundancy move). Two passes:

        1. every active rank's OWN shard is mirrored to clique peers lacking it;
        2. mirrors whose OWNER left the active set (the departed rank's state —
           the copy the ``load_shard`` reshard path consumes) are re-spread from
           a deterministic primary holder to its clique, so the next failure
           can't destroy the sole surviving copy.

        Returns ``{owner_rank: (iteration, blob)}`` of mirrors received — the
        caller persists them. Unlike :meth:`replicate`, participation is
        asymmetric by design: after an upscale some members have nothing to send.
        """
        self._ensure_groups()
        rank = self.comm.rank
        gathered = self.comm.all_gather(
            (rank, my_iteration, sorted(held)), tag="remirror-meta"
        )
        have = {r: it for r, it, _ in gathered if it is not None}
        peer_held = {r: {tuple(p) for p in h} for r, _, h in gathered}
        if not self.enabled:
            return {}
        tag = f"remir/{self._round}"
        self._round += 1
        received: dict[int, tuple[int, bytes]] = {}
        # Pass 1: own shards — sends fan out concurrently, file-spliced when the
        # caller names the on-disk path.
        if rank in have:
            targets = [
                peer
                for peer in self.my_group
                if peer != rank and (rank, have[rank]) not in peer_held[peer]
            ]
            if targets:
                _fan_out(self._shard_senders(
                    targets, f"{tag}/{rank}", rank, have[rank], get_blob, get_path
                ))
        for peer in self.my_group:
            if (
                peer != rank
                and peer in have
                and (peer, have[peer]) not in peer_held[rank]
            ):
                received[peer] = (have[peer], self.exchange.recv(peer, f"{tag}/{peer}"))
        # Pass 2: orphaned mirrors (owner no longer active). Every rank computes
        # the same plan from the gathered holdings; the lowest-ranked holder of
        # the newest copy re-spreads it within its own clique.
        active = set(self.comm.ranks)
        orphans: dict[int, int] = {}
        for _, _, h in gathered:
            for o, it in (tuple(p) for p in h):
                if o not in active:
                    orphans[o] = max(orphans.get(o, it), it)
        for owner in sorted(orphans):
            it = orphans[owner]
            holders = sorted(r for r in active if (owner, it) in peer_held[r])
            if not holders:
                continue
            primary = holders[0]
            grp = group_of(primary, self.groups)
            dsts = [d for d in grp if d != primary and (owner, it) not in peer_held[d]]
            if rank == primary:
                _fan_out(self._shard_senders(
                    dsts, f"{tag}/orph/{owner}", owner, it, get_blob, get_path
                ))
            elif rank in dsts:
                received[owner] = (
                    it,
                    self.exchange.recv(primary, f"{tag}/orph/{owner}"),
                )
        return received

    def _shard_senders(
        self, peers: Sequence[int], tag: str, owner: int, iteration: int,
        get_blob, get_path,
    ) -> list:
        """Per-peer send thunks for one locally-held shard: ``sendfile`` splices
        straight from disk when the caller names the path; otherwise the blob is
        loaded ONCE and shared across the fan-out."""
        if not peers:
            return []
        if get_path is not None:
            path = get_path(owner, iteration)
            return [
                (lambda p=peer: self.exchange.send_file(p, tag, path))
                for peer in peers
            ]
        blob = get_blob(owner, iteration)
        return [(lambda p=peer: self.exchange.send(p, tag, blob)) for peer in peers]

    @property
    def enabled(self) -> bool:
        return self.factor > 1

    #: Erasure subclass flips this: callers that must route block/section
    #: callbacks (the local manager's ladder) gate on it.
    coded = False

    def replicate(self, blob: bytes) -> dict[int, bytes]:
        """Exchange shard blobs within the clique. Returns {owner_rank: blob}."""
        self._ensure_groups()
        held = {self.comm.rank: blob}
        held.update(self.replicate_parts([blob]))
        return held

    def start_round(self) -> "PendingRound":
        """Mint a replication round WITHOUT moving bytes — the tag-agreement
        half of a round, split out so a background worker can run the
        transfer later while tags keep getting minted in save-call order on
        the caller thread (the same ordering contract as
        :meth:`start_stream`). Pair with :meth:`exchange_round`."""
        self._ensure_groups()
        if not self.enabled:
            return PendingRound(None, [], -1)
        tag = f"repl/{self._round}"
        rnd = self._round
        self._round += 1
        peers = [p for p in self.my_group if p != self.comm.rank]
        return PendingRound(tag, peers, rnd)

    def replicate_parts(self, parts: Sequence[Any]) -> dict[int, Any]:
        """Exchange this rank's shard (as its constituent buffers) within the
        clique; returns ``{peer_owner: received_payload}`` — this rank's own
        entry is NOT included (the caller already holds the parts).

        The streaming hot path: sends scatter-gather ``parts`` straight from the
        caller's buffers (no joined blob ever exists), fan out over a thread
        pool so a round costs ~one shard transfer regardless of clique size, and
        overlap with the receives draining concurrently on this thread. Received
        payloads are single receive buffers (`bytes`-like) ready for
        ``format.write_parts`` / ``deserialize_from_buffer``.

        **Degraded peers do not fail the save.** A peer whose send exhausted
        its retries, or whose mirror never arrived within the round deadline,
        is dropped from the returned map and recorded in :attr:`last_degraded`
        (one ``peer_degraded`` event each → ``tpu_replication_peer_degraded_total``):
        this round's shard simply has fewer mirrors — strictly better than
        aborting the checkpoint because one clique member's NIC blipped. All
        receive waits share ONE round deadline (``exchange.timeout``), so k
        degraded peers cost one timeout, not k.
        """
        return self.exchange_round(self.start_round(), parts)

    def exchange_round(
        self, pending: "PendingRound", parts: Sequence[Any]
    ) -> dict[int, Any]:
        """The transfer half of a replication round minted by
        :meth:`start_round` — same semantics as :meth:`replicate_parts`
        (symmetric clique exchange, degraded peers dropped not fatal), but
        runnable on a background thread after the foreground agreed the tag."""
        if not pending.active:
            return {}
        tag, rnd, peers = pending.tag, pending.round, pending.peers
        nbytes = sum(memoryview(p).cast("B").nbytes for p in parts)
        received: dict[int, Any] = {}
        degraded: set[int] = set()
        deadline = time.monotonic() + self.exchange.timeout
        with span(
            "checkpoint", "ckpt.replicate.fanout",
            round=rnd, peers=len(peers), bytes=nbytes,
        ):
            with cf.ThreadPoolExecutor(max_workers=len(peers)) as pool:
                futs = {
                    peer: pool.submit(self.exchange.send_parts, peer, tag, parts)
                    for peer in peers
                }
                for peer in peers:
                    try:
                        got = self.exchange.recv(
                            peer, tag,
                            timeout=max(0.05, deadline - time.monotonic()),
                        )
                        # Verify-on-receive: a checksum-failed mirror is a
                        # degraded peer, not a stored-then-trusted liability.
                        if _verify_received(got, peer, stage="replicate-recv"):
                            received[peer] = got
                        else:
                            degraded.add(peer)
                    except CheckpointError:
                        degraded.add(peer)
                for peer, f in futs.items():
                    try:
                        f.result()
                    except CheckpointError:
                        degraded.add(peer)
        self._mark_degraded(degraded, rnd)
        return received

    def _mark_degraded(self, degraded: set[int], rnd: int) -> None:
        self.last_degraded = set(degraded)
        for peer in sorted(degraded):
            log.warning(
                f"replication round {rnd}: peer {peer} degraded "
                f"(transfer retries exhausted); saving with reduced redundancy"
            )
            record_event(
                "checkpoint", "peer_degraded", peer=peer, round=rnd,
            )

    def start_stream(self, nbytes: int) -> "ReplicationStream":
        """Foreground half of a leaf-streaming replication round.

        Allocates the round tag (call ORDER is the cross-rank agreement — do
        this on the caller thread, in save order, before handing the stream to
        a background worker; concurrent background rounds then stay aligned
        across ranks because their tags were minted in matching order) and
        captures the clique fan-out. ``nbytes`` is the total container size,
        known from the leaf specs before any D2H byte lands. All transfer work
        happens on the returned :class:`ReplicationStream`; with replication
        disabled or no peers it is an inert no-op handle.
        """
        self._ensure_groups()
        rank = self.comm.rank
        if not self.enabled:
            return ReplicationStream(self, None, [], nbytes, -1)
        tag = f"repl/{self._round}"
        rnd = self._round
        self._round += 1
        peers = [p for p in self.my_group if p != rank]
        return ReplicationStream(self, tag, peers, nbytes, rnd)

    def _ensure_groups(self) -> None:
        """Hook for the lazy subclass; the eager strategy's groups always exist."""

    def retrieve(
        self,
        my_needed_owner: Optional[int],
        my_held_owners: set[int],
        get_blob,
        avoid: frozenset[int] | set[int] = frozenset(),
        get_path=None,
    ) -> Optional[bytes]:
        """Global shard routing after rank loss / reassignment.

        ``my_needed_owner``: owner-rank of the shard this rank needs but does not hold
        (``None`` if satisfied locally). ``my_held_owners``: owner-ranks of shards held
        locally. ``get_blob(owner)`` loads a held shard's bytes for sending;
        ``get_path(owner)`` (optional) names its on-disk file so sends splice
        file→socket via ``sendfile``. All ranks must call this collectively with
        the same ``avoid`` set (degraded ranks are deprioritized as senders).
        Returns the received blob, or ``None``.
        """
        self._ensure_groups()
        gathered = self.comm.all_gather(
            (self.comm.rank, my_needed_owner, sorted(my_held_owners)), tag="retrieve-meta"
        )
        wanted = {r: need for r, need, _ in gathered if need is not None}
        holders = {r: set(held) for r, _, held in gathered}
        if not wanted:
            return None
        plan = ExchangePlan.build(wanted, holders, avoid=avoid)
        tag = f"retr/{self._round}"
        self._round += 1
        sends = []
        for dst, owner in plan.sends.get(self.comm.rank, []):
            if get_path is not None:
                sends.append(
                    lambda d=dst, o=owner, p=get_path(owner): self.exchange.send_file(
                        d, f"{tag}/{o}", p
                    )
                )
            else:
                sends.append(
                    lambda d=dst, o=owner, b=get_blob(owner): self.exchange.send(
                        d, f"{tag}/{o}", b
                    )
                )
        _fan_out(sends)
        blob = None
        for src, owner in plan.recvs.get(self.comm.rank, []):
            got = self.exchange.recv(src, f"{tag}/{owner}")
            # Verify-on-receive (per-leaf CRCs + container digest): a bad
            # frame is treated like a degraded peer — the sender is
            # deprioritized for future exchange plans and the caller's
            # recovery ladder falls back instead of loading corruption.
            if _verify_received(got, src, stage="retrieve-recv"):
                blob = got
            else:
                self.last_degraded.add(src)
        return blob

    def fetch_ranges(
        self, holder: int, request: dict, timeout: Optional[float] = None
    ) -> tuple[dict, list]:
        """Ranged read against one peer's locally-held container — the elastic
        reshard fetch: move only the byte ranges this rank newly owns, not the
        whole mirror. Point-to-point (no collective participation; the holder
        serves off its accept thread), per-range checksum-verified by the
        exchange. A failed holder is marked degraded (deprioritized for
        future plans) before the error propagates — the caller retries
        against the next replica holder."""
        self._ensure_groups()
        with span(
            "checkpoint", "reshard.fetch",
            holder=holder, owner=request.get("owner"),
            ranges=len(request.get("ranges") or []),
        ):
            try:
                return self.exchange.fetch_ranges(holder, request, timeout=timeout)
            except CheckpointError:
                self.last_degraded.add(holder)
                raise


class ReplicationStream:
    """One in-flight leaf-streaming replication round (see
    :meth:`CliqueReplicationStrategy.start_stream`).

    ``open()`` dials every clique peer and sends the bulk preambles;
    ``send_chunk(view)`` fans one resolved leaf out to all peers concurrently
    (per-chunk thread fan-out keeps per-peer byte order while overlapping the
    wires); ``finish()`` closes the sends, drains the matching receives, and
    returns ``{peer_owner: payload}`` exactly like ``replicate_parts``. The
    whole object lives on the background save thread after ``start_stream``
    minted its tag on the caller thread.
    """

    def __init__(self, strategy, tag, peers: Sequence[int], nbytes: int, rnd: int):
        self._strategy = strategy
        self.tag = tag
        self.peers = list(peers)
        self.nbytes = nbytes
        self._round = rnd
        self._streams: list = []
        self._pool = None
        self._span = None

    @property
    def active(self) -> bool:
        return bool(self.peers) and self.tag is not None

    def open(self) -> "ReplicationStream":
        if not self.active:
            return self
        self._span = span(
            "checkpoint", "ckpt.replicate.fanout",
            round=self._round, peers=len(self.peers), bytes=self.nbytes,
            streaming=True,
        )
        self._span.__enter__()
        try:
            ex = self._strategy.exchange
            self._streams = [
                ex.open_send_stream(p, self.tag, self.nbytes) for p in self.peers
            ]
            if len(self._streams) > 1:
                self._pool = cf.ThreadPoolExecutor(max_workers=len(self._streams))
        except BaseException as e:
            self._teardown(e)
            raise
        return self

    def send_chunk(self, view) -> None:
        if not self._streams:
            return
        try:
            if self._pool is None:
                self._streams[0].send_chunk(view)
            else:
                # One leaf, all peers at once; waiting per chunk preserves each
                # peer's byte order while the wires overlap.
                for f in [
                    self._pool.submit(s.send_chunk, view) for s in self._streams
                ]:
                    f.result()
        except BaseException as e:
            self._teardown(e)
            raise

    def finish(self) -> dict[int, Any]:
        """Complete sends, collect every peer's mirror (verify-on-receive: a
        checksum-failed mirror is dropped and its peer degraded, exactly like
        ``replicate_parts``); returns {owner: payload}."""
        if not self.active:
            return {}
        received: dict[int, Any] = {}
        dropped: set[int] = set()
        try:
            for s in self._streams:
                s.close()
            for peer in self.peers:
                got = self._strategy.exchange.recv(peer, self.tag)
                if _verify_received(got, peer, stage="stream-recv"):
                    received[peer] = got
                else:
                    dropped.add(peer)
        except BaseException as e:
            self._teardown(e)
            raise
        if dropped:
            self._strategy._mark_degraded(dropped, self._round)
        self._teardown(None)
        return received

    def abort(self) -> None:
        self._teardown(RuntimeError("replication stream aborted"))

    def _teardown(self, exc) -> None:
        for s in self._streams:
            try:
                s.abort()
            except Exception:
                pass
        self._streams = []
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._span is not None:
            sp, self._span = self._span, None
            if exc is None:
                sp.__exit__(None, None, None)
            else:
                sp.__exit__(type(exc), exc, None)


class LazyCliqueReplicationStrategy(CliqueReplicationStrategy):
    """Clique construction deferred to first use (reference parity:
    ``checkpointing/local/replication/strategies.py:190-``).

    Matters when world membership is not final at strategy-construction time —
    spares still promoting, rank assignment still settling after a restart round.
    ``comm_fn()`` is invoked once, at the first ``replicate``/``retrieve``/
    ``remirror``, and must return the group comm for the world that exists THEN.
    ``rebuild`` still works afterwards, exactly as on the eager strategy.
    """

    def __init__(
        self,
        comm_fn,
        exchange: PeerExchange,
        replication_jump: int = 1,
        replication_factor: int = 2,
    ):
        super().__init__(None, exchange, replication_jump, replication_factor)
        self._comm_fn = comm_fn

    def _ensure_groups(self) -> None:
        if self.comm is None:
            self.comm = self._comm_fn()
            self._set_groups(self.comm.ranks)
            log.info(
                f"lazy replication bound to world {self.comm.ranks}: "
                f"my_group={self.my_group}"
            )
