"""Durable cold tier: checkpoints that outlive the job.

Everything PR 15/17 built — TPURES03 containers, erasure blocks, delta
chains — lives on clique peers' *local* disks, so a correlated failure (a
whole-slice preemption, the production norm on TPU pods) loses every copy at
once and a fresh job cannot bootstrap from a dead one's state. This module
adds the third durability tier below local copies and parity reconstruction:
an :class:`ObjectStore`-backed archive a FRESH launcher with an empty workdir
can restore from, on any world size.

Two halves share the store layout:

- :class:`ColdTier` **spill side** — an async background spiller hanging off
  :class:`~tpu_resiliency.checkpoint.local_manager.LocalCheckpointManager`'s
  save-finalize hook. Finalized keyframe containers are enqueued and shipped
  by a daemon thread, NEVER on the save critical path: uploads stream in
  fixed slices through the chaos ``cold`` channel, commit under tmp+rename
  semantics, and become *visible* only when the ``tpu-coldtier-1`` manifest
  doc lands beside the artifact — a torn upload leaves no manifest, so
  readers can never see it. Failures retry with bounded backoff; a
  persistently dead backend trips a per-store circuit breaker and the tier
  degrades to local-only with ``coldtier_degraded`` events — a dead object
  store never fails a save.
- **Restore side** — manifest-driven: :meth:`ColdTier.coverage` names which
  ``(iteration, owner)`` shards the cold tier holds (the third rung of
  ``find_latest``'s coverage ladder), :meth:`ColdTier.fetch` pulls a whole
  container (whole-file digest verified fail-closed before a byte becomes
  visible locally), and :meth:`ColdTier.fetch_ranges` pulls only the byte
  ranges a reshard plan names — the manifest's chunk CRCs make partial
  restore O(needed bytes), each covering chunk verified before its slice is
  handed back.

Store layout (keys under the backend root)::

    s<session>/iter_<iteration:07d>/owner_<owner>.ckpt   # the container bytes
    s<session>/iter_<iteration:07d>/owner_<owner>.json   # tpu-coldtier-1 manifest

Manifest schema (``tpu-coldtier-1``)::

    {"format": "tpu-coldtier-1", "session": S, "iteration": N, "owner": O,
     "key": "<artifact key>", "bytes": TOTAL, "file_crc32c": C,
     "prefix_len": P, "prefix_crc32c": C, "chunk_size": Z | null,
     "leaves": [{"nbytes": N, "crc32c": C, "chunks": [C, ...]} ...],
     "keyframe": true, "delta_base": M | null}

Every digest in the manifest is computed from the bytes the spiller streamed
(plus the container's own recomputed trailer record), so a reader verifies
fetched bytes against the manifest, then the container's own integrity
record — two independent fail-closed gates.
"""

from __future__ import annotations

import errno
import io
import json
import os
import queue
import re
import threading
import time
from typing import Iterable, Optional

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.platform import chaos
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: Launcher-exported envs the default wiring reads (``cold_from_env``).
COLD_DIR_ENV = "TPU_RESILIENCY_COLD_DIR"
COLD_KEEP_ENV = "TPU_RESILIENCY_COLD_KEEP"

MANIFEST_FORMAT = "tpu-coldtier-1"

_MANIFEST_RE = re.compile(r"^s(\d+)/iter_(\d{7})/owner_(\d+)\.json$")


def artifact_key(session: int, iteration: int, owner: int) -> str:
    return f"s{session}/iter_{iteration:07d}/owner_{owner}.ckpt"


def manifest_key(session: int, iteration: int, owner: int) -> str:
    return f"s{session}/iter_{iteration:07d}/owner_{owner}.json"


# -- object store abstraction -------------------------------------------------


class ObjectStore:
    """Minimal pluggable blob interface the cold tier is written against.

    ``put`` MUST be atomic-visible (tmp+rename-equivalent: a reader never
    observes a partially-written object under its final key) and route its
    bytes through the chaos ``cold`` channel so fault plans can corrupt,
    stall, and ENOSPC uploads deterministically per seed.
    """

    def put(self, key: str, slices: Iterable[bytes]) -> int:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def get_range(self, key: str, offset: int, nbytes: int) -> bytes:
        raise NotImplementedError

    def stat(self, key: str) -> int:
        """Object size in bytes; raises ``FileNotFoundError`` when absent."""
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class FilesystemStore(ObjectStore):
    """Filesystem backend: keys are relative paths under ``root`` (an NFS /
    FUSE-mounted bucket in production, a plain directory in tests). Writes
    land on a same-directory temp file, each slice passing through
    ``chaos.on_cold_write``, and commit via ``chaos.on_cold_commit`` +
    ``os.replace`` — the same patchable discipline as ``format._disk_write``,
    on the ``cold`` channel."""

    def __init__(self, root: str, fsync: bool = False):
        self.root = os.path.abspath(root)
        self.fsync = fsync
        os.makedirs(self.root, exist_ok=True)

    def describe(self) -> str:
        return f"fs:{self.root}"

    def _path(self, key: str) -> str:
        if key.startswith("/") or any(
            part in ("", ".", "..") for part in key.split("/")
        ):
            raise ValueError(f"cold tier: malformed object key {key!r}")
        return os.path.join(self.root, *key.split("/"))

    def put(self, key: str, slices: Iterable[bytes]) -> int:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".upload"
        written = 0
        try:
            with open(tmp, "wb") as f:
                for piece in slices:
                    out = chaos.on_cold_write(key, tmp, piece)
                    f.write(out)
                    written += memoryview(out).nbytes
                if self.fsync:
                    os.fsync(f.fileno())
                else:
                    # Page-cache hygiene: the spiller must not leave
                    # gigabytes of dirty pages for the kernel to write back
                    # while the training loop runs (writeback throttling
                    # stalls the FOREGROUND's writes) nor evict the job's
                    # warm working set. Pay the writeback debt here, in the
                    # demoted worker thread, then drop the cached pages.
                    try:
                        os.fdatasync(f.fileno())
                        os.posix_fadvise(
                            f.fileno(), 0, 0, os.POSIX_FADV_DONTNEED
                        )
                    except (AttributeError, OSError):
                        pass
            post_fault = chaos.on_cold_commit(tmp, key, path)
            os.replace(tmp, path)
            if post_fault is not None:
                post_fault()
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return written

    def get(self, key: str) -> bytes:
        with open(self._path(key), "rb") as f:
            return f.read()

    def get_range(self, key: str, offset: int, nbytes: int) -> bytes:
        with open(self._path(key), "rb") as f:
            return os.pread(f.fileno(), nbytes, offset)

    def stat(self, key: str) -> int:
        return os.path.getsize(self._path(key))

    def list(self, prefix: str = "") -> list[str]:
        out = []
        for dirpath, _, names in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root).replace(os.sep, "/")
            for name in names:
                key = name if rel == "." else f"{rel}/{name}"
                if key.startswith(prefix) and not key.endswith(".upload"):
                    out.append(key)
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass


# -- the tier -----------------------------------------------------------------


class _Breaker:
    """Per-backend circuit breaker: ``threshold`` consecutive upload failures
    open it for ``cooldown_s``; while open, spills drop immediately (degraded
    to local-only) instead of hammering a dead store. Half-opens after the
    cooldown — the next spill probes the backend."""

    def __init__(self, threshold: int, cooldown_s: float):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.failures = 0
        self.open_until = 0.0

    @property
    def is_open(self) -> bool:
        return time.monotonic() < self.open_until

    def success(self) -> None:
        self.failures = 0
        self.open_until = 0.0

    def failure(self) -> bool:
        """Record a failure; True when this one opened (or re-armed) the
        breaker."""
        self.failures += 1
        if self.failures >= self.threshold:
            self.open_until = time.monotonic() + self.cooldown_s
            return True
        return False


class ColdTier:
    """Async spiller + manifest-driven reader over one :class:`ObjectStore`.

    One instance per rank; restore-side methods (:meth:`coverage`,
    :meth:`fetch`, :meth:`fetch_ranges`) need no worker thread and are safe
    from any process that can reach the store — including ``tpu-ckpt-info
    --cold`` on a machine where the job never ran.
    """

    def __init__(
        self,
        store: ObjectStore,
        session: int = 0,
        rank: int = 0,
        keep: Optional[int] = None,
        slice_size: int = 1 << 20,
        retries: int = 3,
        backoff_s: float = 0.05,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 30.0,
    ):
        if keep is not None and keep < 1:
            raise ValueError(f"cold tier: keep must be >= 1, got {keep}")
        self.store = store
        self.session = session
        self.rank = rank
        self.keep = keep
        self.slice_size = max(1, int(slice_size))
        self.retries = max(1, int(retries))
        self.backoff_s = backoff_s
        self._breaker = _Breaker(breaker_threshold, breaker_cooldown_s)
        self._q: "queue.Queue" = queue.Queue()
        self._pending = 0
        self._cv = threading.Condition()
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- spill side ---------------------------------------------------------

    def spill(
        self,
        iteration: int,
        owner: int,
        path: str,
        keyframe: bool = True,
        delta_base: Optional[int] = None,
    ) -> bool:
        """Enqueue one finalized local container for upload; returns
        immediately (the worker thread does the IO). Delta frames are skipped
        — the cold tier archives self-contained keyframes only, so a restore
        never chases a chain whose base was pruned. Returns True when
        enqueued."""
        if not keyframe:
            return False
        with self._cv:
            if self._closed:
                return False
            self._pending += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._worker, daemon=True, name="coldtier-spill"
                )
                self._thread.start()
        self._q.put((iteration, owner, path, delta_base))
        return True

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every enqueued spill finished (uploaded, degraded, or
        dropped). True when drained within ``timeout``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._pending:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        if drain:
            self.flush(timeout)
        with self._cv:
            self._closed = True
            thread = self._thread
        if thread is not None:
            self._q.put(None)
            thread.join(timeout)

    def _worker(self) -> None:
        # The spiller must stay off the critical path in WALL CLOCK, not just
        # in call graph: on a small host the CRC + copy work of a 1 GB
        # artifact competes with the foreground save for cores (the CRC
        # backends release the GIL, so this is kernel scheduling, not lock
        # convoy). Demote this thread to the lowest priority so it only
        # consumes cycles the training loop isn't using.
        try:
            os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), 19)
        except (AttributeError, OSError):
            pass
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._spill_one(*item)
            except BaseException as e:  # absolute backstop: never kill saves
                log.error(f"cold tier: unexpected spill failure: {e!r}")
            finally:
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()

    def _spill_one(
        self, iteration: int, owner: int, path: str, delta_base: Optional[int]
    ) -> None:
        if self._breaker.is_open:
            record_event(
                "coldtier", "coldtier_degraded", rank=self.rank,
                iteration=iteration, owner=owner, reason="breaker-open",
                store=self.store.describe(),
            )
            return
        last_err: Optional[str] = None
        for attempt in range(self.retries):
            if attempt:
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            try:
                nbytes = self._upload(iteration, owner, path, delta_base)
            except FileNotFoundError:
                # Pruned locally between finalize and spill (tiny keep with a
                # slow store) — nothing to archive, not a backend failure.
                return
            except (OSError, CheckpointError, ValueError) as e:
                last_err = repr(e)
                continue
            self._breaker.success()
            record_event(
                "coldtier", "coldtier_spilled", rank=self.rank,
                iteration=iteration, owner=owner, bytes=nbytes,
                key=artifact_key(self.session, iteration, owner),
            )
            self._prune()
            return
        opened = self._breaker.failure()
        log.warning(
            f"cold tier: spill of iter {iteration} owner {owner} failed "
            f"after {self.retries} attempts ({last_err}); degrading to "
            f"local-only" + (" [breaker open]" if opened else "")
        )
        record_event(
            "coldtier", "coldtier_degraded", rank=self.rank,
            iteration=iteration, owner=owner, reason="upload-failed",
            error=last_err, breaker_open=opened, store=self.store.describe(),
        )

    def _upload(
        self, iteration: int, owner: int, path: str, delta_base: Optional[int]
    ) -> int:
        """Stream one local container to the store and commit its manifest.
        The manifest is written LAST — it is the visibility point, so any
        torn/failed artifact upload leaves nothing a reader would trust."""
        header, prefix_len, info = ckpt_format.read_trailer(path)
        if info is None or not info.verifiable:
            raise CheckpointError(
                f"{path}: container carries no verifiable integrity record "
                f"(v1 or foreign algorithm) — refusing unverifiable archive"
            )
        leaf_sizes = [int(s["nbytes"]) for s in header["leaves"]]
        akey = artifact_key(self.session, iteration, owner)

        crc_state = {"file": 0, "prefix": 0, "total": 0}

        def slices():
            with open(path, "rb") as f:
                while True:
                    piece = f.read(self.slice_size)
                    if not piece:
                        # Don't let streaming a multi-GB container evict the
                        # training loop's warm pages (re-reading it later
                        # costs one cold read; evicting the job's working
                        # set costs every step until it refills).
                        try:
                            os.posix_fadvise(
                                f.fileno(), 0, 0, os.POSIX_FADV_DONTNEED
                            )
                        except (AttributeError, OSError):
                            pass
                        return
                    off = crc_state["total"]
                    if off < prefix_len:
                        head = piece[: prefix_len - off]
                        crc_state["prefix"] = ckpt_format.crc32c(
                            head, crc_state["prefix"]
                        )
                    crc_state["file"] = ckpt_format.crc32c(
                        piece, crc_state["file"]
                    )
                    crc_state["total"] += len(piece)
                    yield piece

        self.store.put(akey, slices())
        # Containment gate: a torn commit (rename journaled, tail lost) shows
        # up as a size mismatch — fail the attempt before any manifest lands.
        landed = self.store.stat(akey)
        if landed != crc_state["total"]:
            try:  # never leave torn bytes at a key a retry would trust
                self.store.delete(akey)
            except OSError:
                pass
            raise CheckpointError(
                f"cold tier: {akey} landed torn ({landed} of "
                f"{crc_state['total']} bytes)"
            )
        chunk_lists = (
            info.leaf_chunk_crcs(leaf_sizes)
            if info.chunk_crcs is not None
            else [None] * len(leaf_sizes)
        )
        manifest = {
            "format": MANIFEST_FORMAT,
            "session": self.session,
            "iteration": iteration,
            "owner": owner,
            "key": akey,
            "bytes": crc_state["total"],
            "file_crc32c": crc_state["file"],
            "prefix_len": prefix_len,
            "prefix_crc32c": crc_state["prefix"],
            "chunk_size": info.chunk_size,
            "leaves": [
                {"nbytes": n, "crc32c": int(info.leaf_crcs[i]),
                 **({"chunks": [int(c) for c in chunk_lists[i]]}
                    if chunk_lists[i] is not None else {})}
                for i, n in enumerate(leaf_sizes)
            ],
            "keyframe": True,
            "delta_base": delta_base,
        }
        doc = json.dumps(manifest, sort_keys=True).encode()
        self.store.put(manifest_key(self.session, iteration, owner), [doc])
        return crc_state["total"]

    # -- retention ----------------------------------------------------------

    def _prune(self) -> None:
        """Keyframe-aware retention: keep the newest ``keep`` cold iterations
        (across ALL owners — retention is a per-tier property, not
        per-shard), never pruning an iteration some retained manifest names
        as its ``delta_base``. Manifests are deleted BEFORE artifacts so a
        concurrent reader can never trust a half-deleted iteration."""
        if self.keep is None:
            return
        try:
            manifests = self.manifests()
        except OSError as e:
            log.warning(f"cold tier: retention scan failed: {e!r}")
            return
        iterations = sorted(manifests, reverse=True)
        retained = set(iterations[: self.keep])
        for it in iterations[self.keep:]:
            bases = {
                m.get("delta_base")
                for kept in retained
                for m in manifests.get(kept, {}).values()
            }
            if it in bases:
                retained.add(it)  # a retained chain's base is never orphaned
                continue
            for owner in sorted(manifests[it]):
                try:
                    self.store.delete(manifest_key(self.session, it, owner))
                    self.store.delete(artifact_key(self.session, it, owner))
                except OSError as e:
                    log.warning(
                        f"cold tier: pruning iter {it} owner {owner} "
                        f"failed: {e!r}"
                    )
                    continue
                record_event(
                    "coldtier", "coldtier_pruned", rank=self.rank,
                    iteration=it, owner=owner,
                )

    # -- restore side -------------------------------------------------------

    def manifests(self) -> dict[int, dict[int, dict]]:
        """``{iteration: {owner: manifest}}`` for every VALID manifest in this
        session's cold prefix. Unparseable or wrong-format docs are skipped
        (fail-closed: a torn manifest upload makes its iteration invisible,
        never trusted)."""
        out: dict[int, dict[int, dict]] = {}
        for key in self.store.list(prefix=f"s{self.session}/iter_"):
            m = _MANIFEST_RE.match(key)
            if m is None or int(m.group(1)) != self.session:
                continue
            it, owner = int(m.group(2)), int(m.group(3))
            doc = self._read_manifest(key, it, owner)
            if doc is not None:
                out.setdefault(it, {})[owner] = doc
        return out

    def _read_manifest(self, key: str, it: int, owner: int) -> Optional[dict]:
        try:
            doc = json.loads(self.store.get(key))
        except (OSError, ValueError):
            return None
        if (
            not isinstance(doc, dict)
            or doc.get("format") != MANIFEST_FORMAT
            or int(doc.get("iteration", -1)) != it
            or int(doc.get("owner", -1)) != owner
            or not isinstance(doc.get("leaves"), list)
        ):
            return None
        return doc

    def coverage(self) -> dict[int, set[int]]:
        """``{iteration: {owners archived}}`` — the coverage ladder's third
        rung input."""
        return {it: set(per) for it, per in self.manifests().items()}

    def manifest(self, iteration: int, owner: int) -> Optional[dict]:
        return self._read_manifest(
            manifest_key(self.session, iteration, owner), iteration, owner
        )

    def fetch(self, iteration: int, owner: int, dest_path: str) -> dict:
        """Fetch one whole container to ``dest_path`` (atomic local commit
        through the ``disk`` chaos shim, like any other container write).
        The bytes are verified against the manifest's whole-file digest
        BEFORE anything becomes visible locally; a mismatch raises and emits
        ``coldtier_fetch`` outcome=corrupt. Returns the manifest."""
        doc = self.manifest(iteration, owner)
        if doc is None:
            raise CheckpointError(
                f"cold tier: no manifest for iter {iteration} owner {owner}"
            )
        key = str(doc["key"])
        try:
            blob = self.store.get(key)
        except OSError as e:
            raise CheckpointError(f"cold tier: fetch of {key} failed: {e}") from e
        if len(blob) != int(doc["bytes"]) or ckpt_format.crc32c(blob) != int(
            doc["file_crc32c"]
        ):
            record_event(
                "coldtier", "coldtier_fetch", rank=self.rank,
                iteration=iteration, owner=owner, mode="full",
                bytes=len(blob), outcome="corrupt",
            )
            raise CheckpointError(
                f"cold tier: {key} fails manifest digest "
                f"({len(blob)} bytes) — refusing corrupt restore"
            )
        tmp = dest_path + ckpt_format.DIRTY_SUFFIX
        os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            ckpt_format._disk_write(f, blob, dest_path)
        ckpt_format._commit_atomic(tmp, dest_path, fsync=True)
        record_event(
            "coldtier", "coldtier_fetch", rank=self.rank, iteration=iteration,
            owner=owner, mode="full", bytes=len(blob), outcome="ok",
        )
        return doc

    def fetch_header(self, iteration: int, owner: int) -> tuple[dict, dict]:
        """Ranged-fetch and parse a container's head only: ``(manifest,
        header)``. The prefix bytes are verified against the manifest's
        prefix digest fail-closed — a reshard bootstrap learns the saved
        layout in O(header), not O(container)."""
        doc = self.manifest(iteration, owner)
        if doc is None:
            raise CheckpointError(
                f"cold tier: no manifest for iter {iteration} owner {owner}"
            )
        plen = int(doc["prefix_len"])
        prefix = self.store.get_range(str(doc["key"]), 0, plen)
        if len(prefix) != plen or ckpt_format.crc32c(prefix) != int(
            doc["prefix_crc32c"]
        ):
            record_event(
                "coldtier", "coldtier_fetch", rank=self.rank,
                iteration=iteration, owner=owner, mode="header",
                bytes=len(prefix), outcome="corrupt",
            )
            raise CheckpointError(
                f"cold tier: {doc['key']} header fails manifest digest"
            )
        _, header, _ = ckpt_format._read_prefix(
            io.BytesIO(prefix), str(doc["key"])
        )
        return doc, header

    def fetch_ranges(
        self, iteration: int, owner: int, ranges: list[tuple[int, int, int]]
    ) -> list[bytes]:
        """Ranged payload fetch: ``ranges`` are leaf-relative ``(leaf, off,
        nbytes)`` like the peer serve path. Each request pulls only the
        covering chunk span and verifies every covering chunk against the
        manifest before slicing — O(needed bytes), fail-closed. Containers
        archived without a chunk manifest (v2-era) fall back to whole-leaf
        fetch+verify."""
        doc = self.manifest(iteration, owner)
        if doc is None:
            raise CheckpointError(
                f"cold tier: no manifest for iter {iteration} owner {owner}"
            )
        key = str(doc["key"])
        leaves = doc["leaves"]
        offsets = []
        pos = int(doc["prefix_len"])
        for spec in leaves:
            offsets.append(pos)
            pos += int(spec["nbytes"])
        cs = doc.get("chunk_size")
        out: list[bytes] = []
        total = 0
        for leaf, off, nbytes in ranges:
            leaf, off, nbytes = int(leaf), int(off), int(nbytes)
            if leaf < 0 or leaf >= len(leaves):
                raise CheckpointError(
                    f"cold tier: {key} has no leaf {leaf}"
                )
            leaf_nbytes = int(leaves[leaf]["nbytes"])
            if off < 0 or nbytes < 0 or off + nbytes > leaf_nbytes:
                raise CheckpointError(
                    f"cold tier: {key} range [{off}, {off + nbytes}) outside "
                    f"leaf {leaf} payload of {leaf_nbytes} bytes"
                )
            chunks = leaves[leaf].get("chunks")
            if cs and chunks is not None:
                if nbytes == 0:
                    out.append(b"")
                    continue
                first, last = ckpt_format.chunk_spans(leaf_nbytes, cs, off, nbytes)
                span_start = first * cs
                span_end = min(last * cs, leaf_nbytes)
                blob = self.store.get_range(
                    key, offsets[leaf] + span_start, span_end - span_start
                )
                if len(blob) != span_end - span_start:
                    raise CheckpointError(
                        f"cold tier: {key} short read in leaf {leaf}"
                    )
                mv = memoryview(blob)
                for c in range(first, last):
                    w = mv[c * cs - span_start:
                           min((c + 1) * cs, leaf_nbytes) - span_start]
                    if ckpt_format.crc32c(w) != int(chunks[c]):
                        record_event(
                            "coldtier", "coldtier_fetch", rank=self.rank,
                            iteration=iteration, owner=owner, mode="ranged",
                            bytes=len(blob), outcome="corrupt",
                        )
                        raise CheckpointError(
                            f"cold tier: {key} leaf {leaf} chunk {c} fails "
                            f"manifest digest — refusing corrupt restore"
                        )
                out.append(bytes(mv[off - span_start: off - span_start + nbytes]))
            else:
                blob = self.store.get_range(key, offsets[leaf], leaf_nbytes)
                if len(blob) != leaf_nbytes or ckpt_format.crc32c(blob) != int(
                    leaves[leaf]["crc32c"]
                ):
                    record_event(
                        "coldtier", "coldtier_fetch", rank=self.rank,
                        iteration=iteration, owner=owner, mode="ranged",
                        bytes=len(blob), outcome="corrupt",
                    )
                    raise CheckpointError(
                        f"cold tier: {key} leaf {leaf} fails manifest digest"
                    )
                out.append(blob[off: off + nbytes])
            total += nbytes
        record_event(
            "coldtier", "coldtier_fetch", rank=self.rank, iteration=iteration,
            owner=owner, mode="ranged", bytes=total, outcome="ok",
        )
        return out

    def verify(self, iteration: int, owner: int) -> tuple[str, str]:
        """Offline digest check of one archived artifact against its manifest
        (the ``tpu-ckpt-info --cold --verify`` engine): ``("ok"|"corrupt",
        detail)`` — like ``format.verify_file``, never raises."""
        try:
            doc = self.manifest(iteration, owner)
            if doc is None:
                return "corrupt", "manifest missing or unparseable"
            blob = self.store.get(str(doc["key"]))
        except OSError as e:
            return "corrupt", f"unreadable: {e}"
        if len(blob) != int(doc["bytes"]):
            return "corrupt", (
                f"size mismatch ({len(blob)} of {doc['bytes']} bytes)"
            )
        if ckpt_format.crc32c(blob) != int(doc["file_crc32c"]):
            return "corrupt", "whole-file digest mismatch"
        return "ok", f"{len(blob)} bytes, {len(doc['leaves'])} leaves"


def cold_from_env(
    session: int = 0, rank: int = 0, keep: Optional[int] = None, **kwargs
) -> Optional[ColdTier]:
    """The launcher wiring: a :class:`ColdTier` over a
    :class:`FilesystemStore` at ``$TPU_RESILIENCY_COLD_DIR``, retention from
    ``$TPU_RESILIENCY_COLD_KEEP``; None when the env is unset (cold tier
    off)."""
    root = os.environ.get(COLD_DIR_ENV)
    if not root:
        return None
    if keep is None:
        raw = os.environ.get(COLD_KEEP_ENV)
        keep = int(raw) if raw else None
    return ColdTier(
        FilesystemStore(root), session=session, rank=rank, keep=keep, **kwargs
    )
