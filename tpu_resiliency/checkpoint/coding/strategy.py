"""Erasure-coded clique replication: parity blocks instead of full mirrors.

``CliqueReplicationStrategy`` moves ``(n-1)×`` the payload per save (every
clique peer gets a whole mirror). This strategy moves ``~(1 + (m-1)/k)×``:
the shard is RS-coded into ``k`` data + ``m`` parity blocks
(``checkpoint/coding/rs.py``; ``k = clique_size - m``, default ``m=1`` so
``k = n-1``), each clique member is assigned the coded block matching its
position in the sorted clique, and the owner ships every member its one
``payload/k``-sized block — the owner's own assigned block is implicit in the
full container it keeps locally. Losing the owner leaves ``k+m-1 ≥ k``
surviving blocks, so the shard reconstructs **byte-identically** from any
``k`` of them; the reconstruct rung slots into the recovery ladder between
"local verify" and "peer retrieve" (a clique that also holds real mirrors —
mixed-version peers, previously recovered containers — still serves them in
the peer-retrieve rung, which is also the degrade path when a corrupt parity
block breaks reconstruction: the container-level verify after reassembly
makes a false-positive reconstruction structurally impossible).

Block artifacts persist on peer disks as self-describing containers
(``TPUECB01 | header_len | header pickle | block bytes``; the header carries
the code geometry, the block CRC, and the source container's digest so
mismatched generations can never be mixed into one reconstruction).

Surface parity: ``replicate`` / ``replicate_parts`` / ``exchange_round`` /
``remirror`` / ``retrieve`` / ``rebuild`` keep the
:class:`~tpu_resiliency.checkpoint.replication.CliqueReplicationStrategy`
contract — payloads returned to the caller are simply block artifacts
instead of mirrors, and the local manager routes them by magic.
"""

from __future__ import annotations

import pickle
import struct
import time
from typing import Any, Optional, Sequence

import numpy as np

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.checkpoint.coding import delta as ckpt_delta
from tpu_resiliency.checkpoint.coding import rs
from tpu_resiliency.checkpoint.replication import (
    CliqueReplicationStrategy,
    ExchangePlan,
    PendingRound,
    _fan_out,
    _verify_received,
    group_of,
)
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.tracing import span

log = get_logger(__name__)

ECB_MAGIC = b"TPUECB01"
ECB_SCHEMA = "tpu-ecblk-1"
_LEN = struct.Struct("<Q")


# -- block artifact codec ------------------------------------------------------


def build_block_parts(
    owner: int,
    iteration: int,
    k: int,
    m: int,
    index: int,
    block,
    orig_len: int,
    container_crc: int,
    payload_kind: str = "container",
    base_iteration: Optional[int] = None,
) -> list:
    """One block artifact as send-ready parts (header bytes + block views —
    no join; concatenated they ARE the on-disk artifact).

    ``block`` is one bytes-like (parity) or a sequence of views — a data
    block served as verbatim byte ranges of the streamed payload, so the
    systematic half of the code never pays a backing copy. ``payload_kind``
    records what the coded payload IS (``container`` or a ``delta`` frame,
    with ``base_iteration`` as the chain hint) so reconstruction runs the
    right verification; absent in pre-delta artifacts, which read as
    ``container``."""
    pieces = list(block) if isinstance(block, (list, tuple)) else [block]
    crc = 0
    block_len = 0
    for p in pieces:
        crc = ckpt_format.crc32c(p, crc)
        block_len += memoryview(p).nbytes
    header = {
        "schema": ECB_SCHEMA,
        "owner": int(owner),
        "iteration": int(iteration),
        "k": int(k),
        "m": int(m),
        "index": int(index),
        "block_len": int(block_len),
        "orig_len": int(orig_len),
        "algo": ckpt_format.CRC_ALGO,
        "crc": crc,
        "container_crc": int(container_crc),
        "payload": str(payload_kind),
    }
    if base_iteration is not None:
        header["base_iteration"] = int(base_iteration)
    hb = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    return [ECB_MAGIC + _LEN.pack(len(hb)) + hb, *pieces]


def is_block(buf) -> bool:
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    return mv.nbytes >= len(ECB_MAGIC) and bytes(mv[: len(ECB_MAGIC)]) == ECB_MAGIC


def parse_block(buf, source: str = "ecblk") -> tuple[dict, memoryview]:
    """``(header, block_view)`` with structural + CRC validation; raises
    :class:`CheckpointError` on any damage — a corrupt parity block must be
    REJECTED here, long before it could poison a reconstruction."""
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    head = len(ECB_MAGIC) + _LEN.size
    if mv.nbytes < head or bytes(mv[: len(ECB_MAGIC)]) != ECB_MAGIC:
        raise CheckpointError(f"{source}: not an erasure block artifact")
    (hlen,) = _LEN.unpack(mv[len(ECB_MAGIC) : head])
    if head + hlen > mv.nbytes:
        raise CheckpointError(f"{source}: truncated erasure block header")
    try:
        header = pickle.loads(mv[head : head + hlen])
        k, m, index = int(header["k"]), int(header["m"]), int(header["index"])
        block_len = int(header["block_len"])
    except Exception as e:
        raise CheckpointError(
            f"{source}: corrupt erasure block header ({e!r})"
        ) from e
    if header.get("schema") != ECB_SCHEMA or not 0 <= index < k + m:
        raise CheckpointError(f"{source}: malformed erasure block header")
    block = mv[head + hlen : head + hlen + block_len]
    if block.nbytes != block_len:
        raise CheckpointError(
            f"{source}: truncated erasure block ({block.nbytes} of "
            f"{block_len} bytes)"
        )
    if header.get("algo") == ckpt_format.CRC_ALGO and ckpt_format.crc32c(
        block
    ) != header.get("crc"):
        raise CheckpointError(
            f"{source}: erasure block checksum mismatch (index {index})"
        )
    return header, block


def block_identity(buf) -> tuple[int, int, int, int, int]:
    """``(iteration, owner, index, k, m)`` off an artifact's header — the
    local manager's filename router."""
    header, _ = parse_block(buf)
    return (
        header["iteration"], header["owner"], header["index"], header["k"],
        header["m"],
    )


def reconstruct_container(
    artifacts: Sequence[Any], source: str = "parity"
) -> bytes:
    """Reassemble a container from block artifacts (any ``k`` of one
    generation). Every artifact is CRC-validated, the geometry and the source
    container's digest must agree across artifacts, and the reassembled bytes
    are container-verified before they are returned — the three fences that
    make a false-positive reconstruction impossible."""
    parsed = []
    for a in artifacts:
        parsed.append(parse_block(a, source=source))
    if not parsed:
        raise CheckpointError(f"{source}: no erasure blocks to reconstruct from")
    ref = parsed[0][0]
    k, m = ref["k"], ref["m"]
    have: dict[int, np.ndarray] = {}
    for header, block in parsed:
        if (
            header["k"] != k
            or header["m"] != m
            or header["orig_len"] != ref["orig_len"]
            or header["container_crc"] != ref["container_crc"]
            or header["iteration"] != ref["iteration"]
            or header["owner"] != ref["owner"]
        ):
            raise CheckpointError(
                f"{source}: erasure blocks from mismatched generations "
                f"(owner {ref['owner']} iter {ref['iteration']})"
            )
        have[header["index"]] = np.frombuffer(block, dtype=np.uint8)
    data = rs.reconstruct(k, m, have, want=list(range(k)))
    blob = bytes(rs.join([data[i] for i in range(k)], ref["orig_len"]))
    if ref.get("payload", "container") == "delta" or ckpt_delta.is_delta(blob):
        # A delta frame has no container trailer: its generation identity is
        # a CRC over the whole frame, and verification here is structural
        # (parse) + that digest. The chained base validation — frame applies
        # only to the exact base container it names — happens at apply time
        # in the local manager; a missing/stale base degrades to the agreed
        # fallback ladder, never to a wrong container.
        if ckpt_format.crc32c(blob) != ref["container_crc"]:
            raise CheckpointError(
                f"{source}: reconstructed delta frame digest mismatch "
                f"(owner {ref['owner']} iter {ref['iteration']})"
            )
        try:
            ckpt_delta.parse_delta(blob, source=f"{source}-reconstruct")
        except CheckpointError as e:
            raise CheckpointError(
                f"{source}: reconstructed delta frame failed validation ({e})"
            ) from e
        return blob
    try:
        ok = ckpt_format.verify_container(
            blob, source=f"{source}(owner={ref['owner']})"
        )
    except CheckpointError as e:
        raise CheckpointError(
            f"{source}: reconstructed container failed verification ({e})"
        ) from e
    if not ok:
        # Unverifiable (v1 container / foreign algo): fall back on the digest
        # the artifacts recorded — the last 4 trailer bytes are the container
        # digest in every signed format version.
        if len(blob) < 4 or struct.unpack("<I", blob[-4:])[0] != ref[
            "container_crc"
        ]:
            raise CheckpointError(
                f"{source}: reconstructed container digest mismatch"
            )
    return blob


def _split_parts(parts: Sequence[Any], k: int) -> tuple[list[np.ndarray], int]:
    """rs.split over a multi-part payload: one padded backing fill, block
    views over it. Superseded on the hot path by :func:`encode_payload`
    (which never materializes the payload-sized backing copy); kept as the
    reference implementation the byte-identity tests compare against."""
    views = []
    total = 0
    for p in parts:
        mv = memoryview(p)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        views.append(mv)
        total += mv.nbytes
    block_len = max(1, (total + k - 1) // k)
    backing = np.zeros(block_len * k, dtype=np.uint8)
    pos = 0
    for mv in views:
        backing[pos : pos + mv.nbytes] = np.frombuffer(mv, dtype=np.uint8)
        pos += mv.nbytes
    return [backing[i * block_len : (i + 1) * block_len] for i in range(k)], total


def encode_payload(
    parts: Sequence[Any], k: int, m: int, encoder=None
) -> tuple[list, int, int, list[np.ndarray]]:
    """Streaming split+encode over a multi-part payload: ``(views, total,
    block_len, parity)``.

    Data block ``i`` is the verbatim byte range ``[i·block_len,
    (i+1)·block_len)`` of the concatenated views (tail zero-padded) —
    materialize it as views with :func:`data_block_views`; only the parity
    blocks are new allocations (``m·block_len``, not ``k+m``). When
    ``encoder`` is a pre-fed :class:`rs.StreamingEncoder` whose geometry and
    byte count match, its parity is reused — the pipelined save feeds it
    during the Checksummer pass, making the encode here free; any mismatch
    (group moved between mint and exchange) falls back to a fresh streaming
    pass."""
    views = []
    total = 0
    for p in parts:
        mv = memoryview(p)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        views.append(mv)
        total += mv.nbytes
    if (
        encoder is not None
        and encoder.total == total
        and encoder.k == k
        and encoder.m == m
    ):
        return views, total, encoder.block_len, encoder.parity_blocks()
    enc = rs.StreamingEncoder(total, k, m)
    for mv in views:
        enc.update(mv)
    return views, total, enc.block_len, enc.parity_blocks()


def data_block_views(
    views: Sequence[Any], total: int, block_len: int, index: int
) -> list:
    """Data block ``index`` as a list of views over the payload parts, plus
    a zeros tail on the final block — the <k-byte pad ``rs.split`` would
    have charged a payload-sized backing copy for."""
    start = index * block_len
    end = min(start + block_len, total)
    out = []
    pos = 0
    for mv in views:
        nxt = pos + mv.nbytes
        if nxt > start and pos < end:
            out.append(mv[max(start - pos, 0) : min(end - pos, mv.nbytes)])
        pos = nxt
    pad = block_len - max(0, end - start)
    if pad > 0:
        out.append(np.zeros(pad, dtype=np.uint8))
    return out


def coded_block(
    views: Sequence[Any],
    total: int,
    block_len: int,
    parity: Sequence[np.ndarray],
    k: int,
    index: int,
):
    """Coded block ``index``: a data-block view list below ``k``, a parity
    ndarray at/above — the shape :func:`build_block_parts` accepts either of."""
    if index < k:
        return data_block_views(views, total, block_len, index)
    return parity[index - k]


def _container_digest(parts: Sequence[Any]) -> int:
    """The container's trailer digest = the last 4 bytes of the serialized
    container (both trailer versions end with it) — the generation identity
    stamped into every block artifact."""
    tail = memoryview(parts[-1])
    if tail.ndim != 1 or tail.itemsize != 1:
        tail = tail.cast("B")
    if tail.nbytes < 4:
        raise CheckpointError("erasure: container trailer part too short")
    return struct.unpack("<I", tail[-4:])[0]


def _payload_meta(parts: Sequence[Any]) -> dict:
    """Digest + kind + chain hint for the payload a round is about to code:
    ``{digest, payload_kind[, base_iteration]}``. Containers keep the trailer
    digest identity; a delta frame (single-part, by construction of the save
    path) is identified by a CRC over the whole frame since it carries no
    trailer digest of its own."""
    if len(parts) == 1 and ckpt_delta.is_delta(parts[0]):
        header, _ = ckpt_delta.parse_delta(parts[0], source="parity-encode")
        crc = ckpt_format.crc32c(parts[0])
        return {
            "digest": crc,
            "payload_kind": "delta",
            "base_iteration": int(header["base_iteration"]),
        }
    return {"digest": _container_digest(parts), "payload_kind": "container"}


# -- the strategy --------------------------------------------------------------


class ErasureReplicationStrategy(CliqueReplicationStrategy):
    """k-of-n replication over the existing clique machinery.

    ``parity`` (default 1) is ``m``; ``k`` adapts per clique as
    ``len(clique) - m`` (a remainder-merged clique simply gets a wider
    stripe). ``replication_factor`` keeps its meaning — clique width — and
    must exceed ``parity`` so at least one data block exists. Tolerance:
    the owner plus ``m-1`` peers may be lost before the shard is
    unrecoverable from blocks alone (full mirrors held by mixed-version
    peers extend that, and the retrieve rung uses them automatically).
    """

    coded = True

    def __init__(
        self,
        comm,
        exchange,
        replication_jump: int = 1,
        replication_factor: int = 2,
        parity: int = 1,
    ):
        if parity < 1:
            raise CheckpointError("erasure: parity must be >= 1")
        if replication_factor <= parity:
            raise CheckpointError(
                f"erasure: replication_factor ({replication_factor}) must "
                f"exceed parity ({parity}) — at least one data block"
            )
        self.parity = int(parity)
        super().__init__(comm, exchange, replication_jump, replication_factor)

    # -- geometry ----------------------------------------------------------

    def _code_geometry(self, group: Sequence[int]) -> tuple[int, int]:
        n = len(group)
        m = min(self.parity, n - 1) if n > 1 else 0
        return max(1, n - m), m

    def _position(self, rank: int, group: Sequence[int]) -> int:
        return sorted(group).index(rank)

    # -- replicate ---------------------------------------------------------

    def start_encode(self, pending: PendingRound, total: int):
        """A :class:`rs.StreamingEncoder` sized for this round's payload, or
        ``None`` when the round is inert. The pipelined save feeds it chunk
        by chunk alongside the Checksummer so the parity pass of
        :meth:`exchange_round` is already done when the worker gets there."""
        if not pending.active:
            return None
        group = sorted([self.comm.rank, *pending.peers])
        k, m = self._code_geometry(group)
        return rs.StreamingEncoder(total, k, m)

    def exchange_round(
        self, pending: PendingRound, parts: Sequence[Any], encoder=None
    ) -> dict[int, Any]:
        """Erasure round: encode this rank's payload (container or delta
        frame) into coded blocks, ship each peer its positionally-assigned
        block, receive each peer's assigned block of THEIR payload. Returned
        payloads are block artifacts ``{owner: artifact}`` — the caller
        persists them like mirrors (the magic routes the filename).
        Degraded-peer semantics match the mirror strategy exactly.

        Data blocks go on the wire as views over ``parts`` (systematic code,
        no backing copy); ``encoder``, when pre-fed by the save pipeline,
        makes the parity pass free here."""
        if not pending.active:
            return {}
        rank = self.comm.rank
        group = sorted([rank, *pending.peers])
        k, m = self._code_geometry(group)
        with span(
            "checkpoint", "ckpt.parity.encode",
            round=pending.round, k=k, m=m,
        ):
            views, orig_len, block_len, parity = encode_payload(
                parts, k, m, encoder=encoder
            )
            meta = _payload_meta(parts)
            digest = meta.pop("digest")
        sent = 0
        received: dict[int, Any] = {}
        degraded: set[int] = set()
        deadline = time.monotonic() + self.exchange.timeout
        import concurrent.futures as cf

        with span(
            "checkpoint", "ckpt.replicate.fanout",
            round=pending.round, peers=len(pending.peers),
            bytes=len(pending.peers) * block_len, erasure=True,
        ):
            with cf.ThreadPoolExecutor(max_workers=len(pending.peers)) as pool:
                futs = {}
                for peer in pending.peers:
                    idx = self._position(peer, group)
                    art = build_block_parts(
                        rank, pending.iteration, k, m, idx,
                        coded_block(views, orig_len, block_len, parity, k, idx),
                        orig_len, digest, **meta,
                    )
                    sent += sum(memoryview(p).nbytes for p in art)
                    futs[peer] = pool.submit(
                        self.exchange.send_parts, peer, pending.tag, art
                    )
                for peer in pending.peers:
                    try:
                        got = self.exchange.recv(
                            peer, pending.tag,
                            timeout=max(0.05, deadline - time.monotonic()),
                        )
                        parse_block(got, source=f"replicate<-rank{peer}")
                        received[peer] = got
                    except CheckpointError as e:
                        log.warning(
                            f"erasure replicate round {pending.round}: "
                            f"dropping peer {peer} ({e})"
                        )
                        record_event(
                            "checkpoint", "ckpt_integrity_failure",
                            stage="parity-recv", src=peer, error=repr(e),
                        )
                        degraded.add(peer)
                for peer, f in futs.items():
                    try:
                        f.result()
                    except CheckpointError:
                        degraded.add(peer)
        self._mark_degraded(degraded, pending.round)
        record_event(
            "checkpoint", "ckpt_parity",
            k=k, m=m, round=pending.round, block_bytes=block_len,
            sent_bytes=sent, sent_blocks=len(pending.peers),
            received=len(received), payload_bytes=orig_len,
        )
        return received

    # -- retrieve (the ladder's reconstruct + peer-retrieve rungs) ---------

    def retrieve(
        self,
        my_needed_owner: Optional[int],
        my_held_owners: set[int],
        get_blob,
        avoid: frozenset[int] | set[int] = frozenset(),
        get_path=None,
        my_held_blocks: frozenset | set = frozenset(),
        get_block=None,
    ) -> Optional[bytes]:
        """Collective shard recovery, erasure-aware. Two agreed sub-phases:

        1. **reconstruct-from-parity**: ranks holding blocks of a needed
           owner's shard send them (k per needy rank, data blocks preferred,
           deterministic holder choice); the needy rank reconstructs and
           VERIFIES. 2. **peer retrieve**: a second agreement round gathers
           who is still unsatisfied (no blocks, or reconstruction failed —
           e.g. a corrupt parity block) and runs the classic whole-mirror
           exchange over ranks that hold real containers. Only if both rungs
           fail does the caller's ladder fall back an iteration.

        ``my_held_blocks``: this rank's ``(owner, index, k, m)`` artifact
        inventory for the iteration; ``get_block(owner, index)`` loads one
        artifact's bytes.
        """
        self._ensure_groups()
        rank = self.comm.rank
        gathered = self.comm.all_gather(
            (rank, my_needed_owner, sorted(my_held_owners),
             sorted(tuple(b) for b in my_held_blocks)),
            tag="retrieve-meta",
        )
        wanted = {r: need for r, need, _, _ in gathered if need is not None}
        holders = {r: set(held) for r, _, held, _ in gathered}
        #: owner -> index -> sorted holder ranks
        block_holders: dict[int, dict[int, list[int]]] = {}
        geometry: dict[int, tuple[int, int]] = {}
        for r, _, _, blks in gathered:
            for owner, index, bk, bm in (tuple(b) for b in blks):
                block_holders.setdefault(owner, {}).setdefault(index, []).append(r)
                geometry[owner] = (bk, bm)
        if not wanted:
            return None
        tag = f"retr/{self._round}"
        self._round += 1
        # Phase 1 plan: per needy rank, the k chosen (index, src) pairs —
        # identical on every rank (sorted inputs, deterministic choice).
        plan_sends: dict[int, list[tuple[int, int, int]]] = {}
        recon_for: dict[int, list[tuple[int, int]]] = {}
        load: dict[int, int] = {}
        for dst in sorted(wanted):
            owner = wanted[dst]
            idx_holders = block_holders.get(owner, {})
            if owner not in geometry:
                continue
            k, m = geometry[owner]
            usable = {
                i: sorted(h for h in hs if h != dst)
                for i, hs in idx_holders.items()
            }
            usable = {i: hs for i, hs in usable.items() if hs}
            mine = {i for i, hs in idx_holders.items() if dst in hs}
            needed_n = max(0, k - len(mine))
            candidates = [i for i in sorted(
                usable, key=lambda i: (i >= k, i)) if i not in mine]
            if len(mine) + len(candidates) < k:
                continue  # not reconstructible from blocks; phase 2 owns it
            picks: list[tuple[int, int]] = []
            for i in candidates[:needed_n]:
                src = min(
                    usable[i], key=lambda r: (r in avoid, load.get(r, 0), r)
                )
                load[src] = load.get(src, 0) + 1
                picks.append((i, src))
                plan_sends.setdefault(src, []).append((dst, owner, i))
            recon_for[dst] = picks
        sends = []
        for dst, owner, index in plan_sends.get(rank, []):
            sends.append(
                lambda d=dst, o=owner, i=index: self.exchange.send(
                    d, f"{tag}/b/{o}/{i}", get_block(o, i)
                )
            )
        _fan_out(sends)
        blob: Optional[bytes] = None
        if rank in recon_for and my_needed_owner is not None:
            owner = my_needed_owner
            arts = []
            for index, src in recon_for[rank]:
                arts.append(self.exchange.recv(src, f"{tag}/b/{owner}/{index}"))
            for owner_i, index, bk, bm in (
                tuple(b) for b in sorted(my_held_blocks)
            ):
                if owner_i == owner:
                    arts.append(get_block(owner, index))
            try:
                with span("checkpoint", "ckpt.parity.reconstruct", owner=owner):
                    blob = reconstruct_container(
                        arts, source=f"reconstruct(owner={owner})"
                    )
                record_event(
                    "checkpoint", "ckpt_parity_reconstruct",
                    owner=owner, outcome="ok", blocks=len(arts),
                    bytes=len(blob),
                )
            except CheckpointError as e:
                log.warning(
                    f"rank {rank}: parity reconstruction of owner {owner} "
                    f"failed ({e}); degrading to peer retrieve"
                )
                record_event(
                    "checkpoint", "ckpt_parity_reconstruct",
                    owner=owner, outcome="failed", blocks=len(arts),
                    error=repr(e),
                )
                blob = None
        # Phase 2: who is STILL unsatisfied (reconstruction failed or no
        # blocks)? Classic mirror exchange over real container holders.
        still_needed = my_needed_owner if blob is None else None
        gathered2 = self.comm.all_gather((rank, still_needed), tag="retrieve-resid")
        wanted2 = {r: need for r, need in gathered2 if need is not None}
        if wanted2:
            plan = ExchangePlan.build(wanted2, holders, avoid=avoid)
            sends = []
            for dst, owner in plan.sends.get(rank, []):
                if get_path is not None:
                    sends.append(
                        lambda d=dst, o=owner, p=get_path(owner):
                        self.exchange.send_file(d, f"{tag}/m/{o}", p)
                    )
                else:
                    sends.append(
                        lambda d=dst, o=owner, b=get_blob(owner):
                        self.exchange.send(d, f"{tag}/m/{o}", b)
                    )
            _fan_out(sends)
            for src, owner in plan.recvs.get(rank, []):
                got = self.exchange.recv(src, f"{tag}/m/{owner}")
                if _verify_received(got, src, stage="retrieve-recv"):
                    blob = got
                else:
                    self.last_degraded.add(src)
        return blob

    # -- remirror ----------------------------------------------------------

    def remirror(
        self,
        my_iteration: Optional[int],
        get_blob,
        held: frozenset | set = frozenset(),
        get_path=None,
        held_blocks: frozenset | set = frozenset(),
        get_block=None,
    ) -> dict[int, tuple[int, Any]]:
        """Re-establish block redundancy after a clique rebuild. Collective.

        Pass 1: every active rank re-encodes its own newest shard and ships
        clique peers the assigned blocks they lack. Pass 2: orphaned owners
        (departed ranks) — when a real container survives somewhere, its
        lowest-ranked holder re-encodes and spreads blocks within its own
        clique; when only blocks survive (≥ k of one generation), they are
        routed to the lowest-ranked active holder, which reconstructs and
        returns the container for persistence (its next remirror spreads
        blocks again). Returns ``{owner: (iteration, artifact-or-container)}``
        for the caller to persist."""
        self._ensure_groups()
        rank = self.comm.rank
        gathered = self.comm.all_gather(
            (rank, my_iteration, sorted(held),
             sorted(tuple(b) for b in held_blocks)),
            tag="remirror-meta",
        )
        have = {r: it for r, it, _, _ in gathered if it is not None}
        peer_held = {r: {tuple(p) for p in h} for r, _, h, _ in gathered}
        #: rank -> {(owner, iteration, index, k, m)}
        peer_blocks = {r: {tuple(b) for b in blks} for r, _, _, blks in gathered}
        if not self.enabled:
            return {}
        tag = f"remir/{self._round}"
        self._round += 1
        received: dict[int, tuple[int, Any]] = {}
        group = sorted(self.my_group)
        k, m = self._code_geometry(group)
        # Pass 1: own shards → assigned blocks to clique peers lacking them.
        if rank in have:
            it = have[rank]
            targets = [
                peer for peer in group
                if peer != rank and not any(
                    b[0] == rank and b[1] == it and b[2] == self._position(peer, group)
                    for b in peer_blocks.get(peer, ())
                )
            ]
            if targets:
                parts = [get_blob(rank, it)]
                views, orig_len, block_len, parity = encode_payload(parts, k, m)
                meta = _payload_meta(parts)
                digest = meta.pop("digest")
                _fan_out([
                    (lambda p=peer, i=self._position(peer, group):
                     self.exchange.send_parts(
                         p, f"{tag}/{rank}",
                         build_block_parts(
                             rank, it, k, m, i,
                             coded_block(views, orig_len, block_len, parity,
                                         k, i),
                             orig_len, digest, **meta)))
                    for peer in targets
                ])
        for peer in group:
            if peer == rank or peer not in have:
                continue
            it = have[peer]
            mine = self._position(rank, sorted(group))
            if any(
                b[0] == peer and b[1] == it and b[2] == mine
                for b in peer_blocks.get(rank, ())
            ):
                continue
            received[peer] = (it, self.exchange.recv(peer, f"{tag}/{peer}"))
        # Pass 2: orphaned owners.
        active = set(self.comm.ranks)
        orphans: dict[int, int] = {}
        for r, _, h, blks in gathered:
            for o, it in (tuple(p) for p in h):
                if o not in active:
                    orphans[o] = max(orphans.get(o, it), it)
            for o, it, _, _, _ in (tuple(b) for b in blks):
                if o not in active:
                    orphans[o] = max(orphans.get(o, it), it)
        for owner in sorted(orphans):
            it = orphans[owner]
            c_holders = sorted(
                r for r in active if (owner, it) in peer_held[r]
            )
            if c_holders:
                primary = c_holders[0]
                grp = sorted(group_of(primary, self.groups))
                gk, gm = self._code_geometry(grp)
                dsts = [
                    d for d in grp
                    if d != primary and not any(
                        b[0] == owner and b[1] == it
                        and b[2] == self._position(d, grp)
                        for b in peer_blocks.get(d, ())
                    )
                ]
                if rank == primary and dsts:
                    parts = [get_blob(owner, it)]
                    views, orig_len, block_len, parity = encode_payload(
                        parts, gk, gm
                    )
                    meta = _payload_meta(parts)
                    digest = meta.pop("digest")
                    _fan_out([
                        (lambda p=d, i=self._position(d, grp):
                         self.exchange.send_parts(
                             p, f"{tag}/orph/{owner}",
                             build_block_parts(
                                 owner, it, gk, gm, i,
                                 coded_block(views, orig_len, block_len,
                                             parity, gk, i),
                                 orig_len, digest, **meta)))
                        for d in dsts
                    ])
                elif rank in dsts:
                    received[owner] = (
                        it, self.exchange.recv(primary, f"{tag}/orph/{owner}")
                    )
                continue
            # Blocks only: route them to the elected reconstructor.
            idx_holders: dict[int, list[int]] = {}
            geo = None
            for r in sorted(active):
                for o, bit, index, bk, bm in (
                    tuple(b) for b in peer_blocks.get(r, ())
                ):
                    if o == owner and bit == it:
                        idx_holders.setdefault(index, []).append(r)
                        geo = (bk, bm)
            if geo is None:
                continue
            bk, bm = geo
            holders_any = sorted({r for hs in idx_holders.values() for r in hs})
            primary = holders_any[0]
            mine = {
                i for i, hs in idx_holders.items() if primary in hs
            }
            candidates = [
                i for i in sorted(idx_holders, key=lambda i: (i >= bk, i))
                if i not in mine
            ]
            picks = []
            for i in candidates[: max(0, bk - len(mine))]:
                src = min(h for h in idx_holders[i] if h != primary)
                picks.append((i, src))
            if len(mine) + len(picks) < bk:
                continue  # unrecoverable from blocks; nothing to do
            if rank == primary:
                arts = [get_block(owner, it, i) for i in sorted(mine)]
                for i, src in picks:
                    arts.append(
                        self.exchange.recv(src, f"{tag}/rb/{owner}/{i}")
                    )
                try:
                    blob = reconstruct_container(
                        arts, source=f"remirror(owner={owner})"
                    )
                    received[owner] = (it, blob)
                    record_event(
                        "checkpoint", "ckpt_parity_reconstruct",
                        owner=owner, outcome="ok", blocks=len(arts),
                        bytes=len(blob), stage="remirror",
                    )
                except CheckpointError as e:
                    record_event(
                        "checkpoint", "ckpt_parity_reconstruct",
                        owner=owner, outcome="failed", blocks=len(arts),
                        error=repr(e), stage="remirror",
                    )
            else:
                sends = []
                for i, src in picks:
                    if src == rank:
                        sends.append(
                            lambda o=owner, it2=it, i2=i: self.exchange.send(
                                primary, f"{tag}/rb/{o}/{i2}",
                                get_block(o, it2, i2),
                            )
                        )
                _fan_out(sends)
        return received
