"""Checkpoint byte-economy plane: erasure coding + delta checkpoints.

Two compounding attacks on replication bandwidth (ROADMAP item 3; the
reference — NVRx local checkpointing — only ever full-mirrors):

- :mod:`~tpu_resiliency.checkpoint.coding.rs` /
  :mod:`~tpu_resiliency.checkpoint.coding.strategy` — Reed-Solomon parity
  across the clique instead of full mirrors: each peer stores one coded
  block (``payload/k`` bytes) of every clique member's shard, so a save
  moves ~``(1 + (m-1)/k)×`` the payload instead of ``(n-1)×``, and a lost
  rank's shard reconstructs byte-identically from any ``k`` surviving
  blocks (the reconstruct rung slots into the recovery ladder between
  "local verify" and "peer retrieve").
- :mod:`~tpu_resiliency.checkpoint.coding.delta` — delta checkpoints: the
  ``TPURES03`` chunk manifest makes consecutive saves diffable per chunk,
  so steady-state replication ships only changed chunks between full
  keyframes (``delta_interval`` knob on the local manager).
"""

import os

from tpu_resiliency.checkpoint.coding.delta import (  # noqa: F401
    DeltaTracker,
    apply_delta,
    encode_delta,
    is_delta,
)
from tpu_resiliency.checkpoint.coding.strategy import (  # noqa: F401
    ErasureReplicationStrategy,
    block_identity,
    is_block,
)

#: ``mirror`` (default) | ``erasure`` | ``erasure:<parity>`` — the launcher's
#: ``--ckpt-coding`` flag exports it so worker scripts pick the strategy
#: without plumbing a new argument through every training loop.
CODING_ENV = "TPU_RESILIENCY_CKPT_CODING"


def replication_from_env(
    comm,
    exchange,
    replication_jump: int = 1,
    replication_factor: int = 2,
    coding: str | None = None,
):
    """Strategy factory honoring ``$TPU_RESILIENCY_CKPT_CODING`` (or an
    explicit ``coding`` spec): the one construction-site change that moves a
    job from full mirrors to k-of-n parity."""
    from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
    from tpu_resiliency.exceptions import CheckpointError

    spec = (coding if coding is not None else os.environ.get(CODING_ENV, "mirror"))
    spec = (spec or "mirror").strip().lower()
    if spec in ("", "mirror"):
        return CliqueReplicationStrategy(
            comm, exchange, replication_jump, replication_factor
        )
    if spec == "erasure" or spec.startswith("erasure:"):
        parity = 1
        if ":" in spec:
            try:
                parity = int(spec.split(":", 1)[1])
            except ValueError as e:
                raise CheckpointError(
                    f"bad {CODING_ENV} spec {spec!r} (want erasure[:parity])"
                ) from e
        return ErasureReplicationStrategy(
            comm, exchange, replication_jump, replication_factor, parity=parity
        )
    raise CheckpointError(
        f"unknown checkpoint coding {spec!r} (want mirror | erasure[:parity])"
    )


__all__ = [
    "CODING_ENV",
    "DeltaTracker",
    "apply_delta",
    "encode_delta",
    "is_delta",
    "ErasureReplicationStrategy",
    "block_identity",
    "is_block",
    "replication_from_env",
]
