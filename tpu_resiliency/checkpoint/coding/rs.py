"""Systematic Reed-Solomon erasure coding over GF(256), pure numpy.

The byte-economy plane needs exactly one algebraic property: split a shard
into ``k`` data blocks, derive ``m`` parity blocks, and reconstruct the
original from ANY ``k`` of the ``k+m`` coded blocks. A Cauchy-matrix code
gives that property by construction (every square submatrix of a Cauchy
matrix over a field is invertible — the classic result zfec/ISA-L "cauchy"
layouts lean on), and GF(256) keeps every symbol one byte, so encode/decode
are table-lookup + XOR passes that numpy vectorizes to memory speed.

No dependencies beyond numpy: log/antilog tables for the field (primitive
polynomial ``0x11D``), vectorized scalar×vector multiply via the tables,
and a scalar ``k×k`` Gaussian inversion (k is a clique size — single
digits — so the inversion is nanoseconds; the O(k·m) table passes over the
payload are the real cost, and they replace an O(n-1) full-mirror copy of
the same payload on the wire).

Block layout contract: blocks are equal length (``block_len = ceil(total/k)``,
the tail zero-padded); coded index ``i < k`` is data block ``i`` (systematic
— data blocks are verbatim byte ranges of the payload), index ``k+j`` is
parity block ``j``. :func:`split` and :func:`join` own the padding math.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from tpu_resiliency.exceptions import CheckpointError

_PRIM = 0x11D

# log/antilog tables; EXP doubled so EXP[LOG[a] + LOG[b]] never wraps.
_EXP = np.zeros(510, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _PRIM
_EXP[255:510] = _EXP[:255]


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[_LOG[a] + _LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(_EXP[255 - _LOG[a]])


def _mul_scalar_vec(a: int, v: np.ndarray) -> np.ndarray:
    """``a · v`` over GF(256), vectorized through the log tables."""
    if a == 0:
        return np.zeros_like(v)
    if a == 1:
        return v.copy()
    out = np.zeros_like(v)
    nz = v != 0
    out[nz] = _EXP[_LOG[a] + _LOG[v[nz]]]
    return out


def _addmul_scalar_vec(acc: np.ndarray, a: int, v: np.ndarray) -> None:
    """``acc ^= a · v`` in place (the encode/decode inner loop)."""
    if a == 0:
        return
    if a == 1:
        np.bitwise_xor(acc, v, out=acc)
        return
    nz = v != 0
    acc[nz] ^= _EXP[_LOG[a] + _LOG[v[nz]]].astype(np.uint8)


def parity_matrix(k: int, m: int) -> list[list[int]]:
    """The ``m×k`` parity coefficients.

    ``m == 1`` uses the all-ones row (RAID-5 XOR parity): ``[I; 1]`` has
    every ``k``-row subset invertible (drop one identity row and the ones
    row still spans the missing coordinate), and encode/decode collapse to
    memory-speed XOR passes — the common ``parity=1`` clique pays no GF
    multiply at all. ``m > 1`` uses Cauchy coefficients: row ``j``, column
    ``i`` is ``1/(x_j + y_i)`` with ``x = {0..m-1}``, ``y = {m..m+k-1}``
    (disjoint, so the denominator is never zero); every square submatrix of
    a Cauchy matrix is invertible, so any ``k`` coded blocks reconstruct."""
    if k < 1 or m < 0 or k + m > 256:
        raise CheckpointError(f"rs: unsupported code geometry k={k} m={m}")
    if m == 1:
        return [[1] * k]
    return [[gf_inv(j ^ (m + i)) for i in range(k)] for j in range(m)]


def encode(blocks: Sequence[np.ndarray], m: int) -> list[np.ndarray]:
    """``m`` parity blocks over ``k`` equal-length uint8 data blocks."""
    k = len(blocks)
    mat = parity_matrix(k, m)
    out = []
    for j in range(m):
        acc = np.zeros_like(blocks[0])
        for i, b in enumerate(blocks):
            _addmul_scalar_vec(acc, mat[j][i], b)
        out.append(acc)
    return out


def _invert(mat: list[list[int]]) -> list[list[int]]:
    """Gauss-Jordan inversion of a small GF(256) matrix."""
    k = len(mat)
    a = [row[:] + [1 if i == j else 0 for j in range(k)]
         for i, row in enumerate(mat)]
    for col in range(k):
        piv = next((r for r in range(col, k) if a[r][col]), None)
        if piv is None:
            raise CheckpointError("rs: singular decode matrix")
        a[col], a[piv] = a[piv], a[col]
        inv_p = gf_inv(a[col][col])
        a[col] = [gf_mul(x, inv_p) for x in a[col]]
        for r in range(k):
            if r != col and a[r][col]:
                f = a[r][col]
                a[r] = [x ^ gf_mul(f, y) for x, y in zip(a[r], a[col])]
    return [row[k:] for row in a]


def reconstruct(
    k: int,
    m: int,
    have: Dict[int, np.ndarray],
    want: Optional[Sequence[int]] = None,
) -> Dict[int, np.ndarray]:
    """Recover data blocks from any ``k`` coded blocks.

    ``have`` maps coded index (``0..k+m-1``) → uint8 block; ``want`` lists
    the data indices to recover (default: every missing one). Raises
    :class:`CheckpointError` when fewer than ``k`` blocks survive."""
    if want is None:
        want = [i for i in range(k) if i not in have]
    missing_data = [i for i in want if i not in have]
    if not missing_data:
        return {i: have[i] for i in want}
    if len(have) < k:
        raise CheckpointError(
            f"rs: cannot reconstruct — {len(have)} of {k} required blocks "
            f"survive (have {sorted(have)})"
        )
    # Prefer data blocks (identity rows make the inversion cheaper and the
    # choice deterministic); take the k lowest surviving indices after that.
    chosen = sorted(have, key=lambda i: (i >= k, i))[:k]
    pm = parity_matrix(k, m)
    rows = [
        ([1 if c == i else 0 for c in range(k)] if i < k else pm[i - k])
        for i in chosen
    ]
    inv = _invert(rows)
    out: Dict[int, np.ndarray] = {}
    for t in want:
        if t in have:
            out[t] = have[t]
            continue
        acc = np.zeros_like(have[chosen[0]])
        for r, idx in enumerate(chosen):
            _addmul_scalar_vec(acc, inv[t][r], have[idx])
        out[t] = acc
    return out


class StreamingEncoder:
    """Incremental systematic encode: feed the payload in order, read the
    parity blocks at the end.

    The classic path (:func:`split` + :func:`encode`) pays a payload-sized
    zero-filled backing copy before the first parity byte is computed, plus
    GF table passes whose temporaries are block-sized. This encoder removes
    both: the code is systematic, so data blocks are verbatim byte ranges of
    the payload (the caller can serve them as views — no backing copy), and
    parity accumulates window-by-window as the payload streams past, so the
    transient scratch is O(window), not O(payload). ``update`` is designed to
    ride the same per-leaf pass the save path's ``Checksummer`` already runs.

    Byte-equivalence with the classic path is exact: the tail zero-padding
    :func:`split` materializes is absorbing under GF multiply-accumulate
    (``coeff · 0 = 0``), so never feeding it changes nothing. ``m == 1``
    keeps the RAID-5 property: the all-ones parity row makes every window
    pass a pure in-place XOR with zero allocations.
    """

    def __init__(self, total: int, k: int, m: int, window: int = 1 << 20):
        if total < 0:
            raise CheckpointError(f"rs: negative payload size {total}")
        self.total = int(total)
        self.k = int(k)
        self.m = int(m)
        self.window = max(1, int(window))
        self.block_len = max(1, (self.total + self.k - 1) // self.k)
        self.mat = parity_matrix(self.k, self.m)
        self.parity = [
            np.zeros(self.block_len, dtype=np.uint8) for _ in range(self.m)
        ]
        self._pos = 0

    def update(self, view) -> None:
        """Accumulate one payload part (any bytes-like) into the parity."""
        mv = memoryview(view)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        if self._pos + mv.nbytes > self.total:
            raise CheckpointError(
                f"rs: streamed {self._pos + mv.nbytes} bytes past the "
                f"declared total of {self.total}"
            )
        off = 0
        while off < mv.nbytes:
            pos = self._pos + off
            blk = pos // self.block_len
            boff = pos % self.block_len
            n = min(self.window, mv.nbytes - off, self.block_len - boff)
            w = np.frombuffer(mv[off : off + n], dtype=np.uint8)
            for j in range(self.m):
                _addmul_scalar_vec(
                    self.parity[j][boff : boff + n], self.mat[j][blk], w
                )
            off += n
        self._pos += mv.nbytes

    def parity_blocks(self) -> list[np.ndarray]:
        """The ``m`` parity blocks; valid once every declared byte streamed."""
        if self._pos != self.total:
            raise CheckpointError(
                f"rs: parity read after {self._pos} of {self.total} "
                f"declared payload bytes"
            )
        return self.parity


def split(buf, k: int) -> tuple[list[np.ndarray], int]:
    """Cut a byte payload into ``k`` equal blocks (tail zero-padded);
    returns ``(blocks, original_length)``. Blocks are views over one backing
    array, so the padding copy is the only allocation."""
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    total = mv.nbytes
    block_len = max(1, (total + k - 1) // k)
    backing = np.zeros(block_len * k, dtype=np.uint8)
    backing[:total] = np.frombuffer(mv, dtype=np.uint8)
    return [backing[i * block_len : (i + 1) * block_len] for i in range(k)], total


def join(blocks: Sequence[np.ndarray], orig_len: int) -> memoryview:
    """Reassemble :func:`split`'s output (strips the tail padding)."""
    return memoryview(np.concatenate(blocks).data)[:orig_len]
