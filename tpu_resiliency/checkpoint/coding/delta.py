"""Delta checkpoints: ship only chunks that changed since the last save.

Steady-state training mutates a small fraction of the state between
checkpoint intervals (optimizer moments and touched parameters), yet the
mirror strategy re-ships every byte every round — BENCH_ckpt_save.json shows
the 1 GB save bandwidth-bound on exactly that. The ``TPURES03`` chunk
manifest (``checkpoint/format.py``) makes consecutive saves diffable for
free: the per-chunk CRCs both saves already compute ARE the diff input.

Protocol: between full **keyframes** (every ``delta_interval``-th save, and
whenever the tree signature changes), replication ships a **delta frame**
instead of the container::

    TPUDLT01 | header_len(8 LE) | header pickle | changed chunk bytes...

The header carries the new container's full prefix and trailer (they are
small and change every save — the iteration rides in meta), the base
iteration + base container digest (the chain link), the chunk size, per-leaf
sizes, and the changed ``(leaf, chunk)`` list. A receiver holding the base
container applies the delta as ranged writes: unchanged chunks stream from
its base copy, changed chunks from the frame, new prefix/trailer verbatim —
producing the exact bytes of the sender's container (METADATA-validated: the
base's digest must match the frame's chain link and every unchanged chunk's
manifest CRC must be identical between base and new trailers, so a stale or
corrupt base can never silently assemble a wrong container).

A broken chain (receiver lacks the base, digests disagree) drops that
mirror for the round — one ``ckpt_delta_applied{outcome=broken}`` event —
and the shard simply has fewer mirrors until the next keyframe re-bases
everyone; at load time the existing group-agreed fallback ladder owns any
resulting coverage gap, falling back to the newest loadable keyframe chain.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, Optional, Sequence

from tpu_resiliency.checkpoint import format as ckpt_format
from tpu_resiliency.exceptions import CheckpointError
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

DELTA_MAGIC = b"TPUDLT01"
DELTA_SCHEMA = "tpu-ckpt-delta-1"
_LEN = struct.Struct("<Q")

#: Env default for the manager's ``delta_interval`` knob (0/1 = off; N means
#: one keyframe then up to N-1 delta saves per cycle).
DELTA_ENV = "TPU_RESILIENCY_CKPT_DELTA"


def interval_from_env(value: Optional[int] = None) -> int:
    if value is not None:
        return max(0, int(value))
    try:
        return max(0, int(os.environ.get(DELTA_ENV, "0")))
    except ValueError:
        return 0


def is_delta(buf) -> bool:
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    return (
        mv.nbytes >= len(DELTA_MAGIC)
        and bytes(mv[: len(DELTA_MAGIC)]) == DELTA_MAGIC
    )


class DeltaTracker:
    """Per-manager memory of the previous save's chunk manifest.

    ``eligible()`` answers the foreground question — can the NEXT save ship a
    delta? — from the leaf signature alone; ``note_saved()`` records a
    completed save's manifest (every save, keyframe or delta, re-bases the
    chain on its own new manifest, so consecutive deltas chain
    base→base→...→keyframe)."""

    def __init__(self, interval: Optional[int] = None):
        self.interval = interval_from_env(interval)
        self._base: Optional[dict] = None
        self._since_keyframe = 0

    @property
    def enabled(self) -> bool:
        return self.interval > 1

    def eligible(self, leaf_sizes: Sequence[int]) -> Optional[dict]:
        """The base descriptor when the next save may ship a delta, else
        ``None`` (keyframe due, no base yet, or the tree signature moved)."""
        if not self.enabled or self._base is None:
            return None
        if self._since_keyframe >= self.interval - 1:
            return None
        if list(self._base["leaf_sizes"]) != [int(n) for n in leaf_sizes]:
            return None
        return self._base

    def note_saved(
        self,
        iteration: int,
        leaf_sizes: Sequence[int],
        chunk_size: int,
        leaf_chunks: Sequence[Sequence[int]],
        container_crc: int,
        keyframe: bool,
    ) -> None:
        self._since_keyframe = 0 if keyframe else self._since_keyframe + 1
        self._base = {
            "iteration": int(iteration),
            "leaf_sizes": [int(n) for n in leaf_sizes],
            "chunk_size": int(chunk_size),
            "leaf_chunks": [list(c) for c in leaf_chunks],
            "container_crc": int(container_crc),
        }

    def reset(self) -> None:
        """Drop the chain (group rebuild, reshard) — next save keyframes."""
        self._base = None
        self._since_keyframe = 0


def encode_delta(
    owner: int,
    iteration: int,
    base: dict,
    prefix: bytes,
    leaf_views: Sequence[Any],
    trailer: bytes,
) -> tuple[bytes, dict]:
    """Build a delta frame for the container ``prefix + leaf_views + trailer``
    against ``base`` (a :class:`DeltaTracker` descriptor). Returns
    ``(frame_bytes, stats)`` with ``stats`` carrying the byte economy
    (``full_bytes`` vs ``frame_bytes``, chunk counts) for events/benches.

    Raises :class:`CheckpointError` when the new container is not chain-
    compatible with the base (manifest geometry moved) — callers fall back
    to a keyframe."""
    info = ckpt_format.parse_trailer_v3(trailer, source="delta-encode")
    leaf_sizes = [memoryview(v).nbytes for v in leaf_views]
    if (
        info.chunk_size != base["chunk_size"]
        or leaf_sizes != base["leaf_sizes"]
    ):
        raise CheckpointError(
            "delta: new container's chunk geometry does not match the base"
        )
    new_chunks = info.leaf_chunk_crcs(leaf_sizes)
    changed: list[tuple[int, int]] = []
    for leaf, (old, new) in enumerate(zip(base["leaf_chunks"], new_chunks)):
        if len(old) != len(new):
            raise CheckpointError("delta: chunk count moved between saves")
        for ci, (a, b) in enumerate(zip(old, new)):
            if a != b:
                changed.append((leaf, ci))
    header = {
        "schema": DELTA_SCHEMA,
        "owner": int(owner),
        "iteration": int(iteration),
        "base_iteration": base["iteration"],
        "base_container_crc": base["container_crc"],
        "chunk_size": info.chunk_size,
        "leaf_sizes": leaf_sizes,
        "changed": changed,
        "prefix": bytes(prefix),
        "trailer": bytes(trailer),
    }
    hb = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    parts: list[Any] = [DELTA_MAGIC + _LEN.pack(len(hb)) + hb]
    cs = info.chunk_size
    sent = 0
    for leaf, ci in changed:
        mv = memoryview(leaf_views[leaf])
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        window = mv[ci * cs : min((ci + 1) * cs, leaf_sizes[leaf])]
        parts.append(window)
        sent += window.nbytes
    full = len(prefix) + sum(leaf_sizes) + len(trailer)
    frame = b"".join(bytes(p) if not isinstance(p, bytes) else p for p in parts)
    stats = {
        "full_bytes": full,
        "frame_bytes": len(frame),
        "chunks_total": len(info.chunk_crcs),
        "chunks_changed": len(changed),
        "changed_bytes": sent,
    }
    return frame, stats


def parse_delta(buf, source: str = "delta") -> tuple[dict, memoryview]:
    """``(header, changed_bytes_view)`` with structural validation."""
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    head = len(DELTA_MAGIC) + _LEN.size
    if mv.nbytes < head or bytes(mv[: len(DELTA_MAGIC)]) != DELTA_MAGIC:
        raise CheckpointError(f"{source}: not a delta frame")
    (hlen,) = _LEN.unpack(mv[len(DELTA_MAGIC) : head])
    if head + hlen > mv.nbytes:
        raise CheckpointError(f"{source}: truncated delta frame header")
    try:
        header = pickle.loads(mv[head : head + hlen])
        assert header.get("schema") == DELTA_SCHEMA
        int(header["iteration"]); int(header["base_iteration"])
        list(header["changed"]); list(header["leaf_sizes"])
    except Exception as e:
        raise CheckpointError(f"{source}: corrupt delta frame header ({e!r})") from e
    return header, mv[head + hlen :]


def apply_delta(frame, base_path: str, out_path: str) -> int:
    """Materialize the full new container at ``out_path`` from ``frame`` + the
    base container at ``base_path``; returns bytes written.

    Chain validation is metadata-only (O(trailer), no payload scan): the
    base's recorded container digest must equal the frame's chain link, and
    every UNCHANGED chunk's CRC must be identical between the base and new
    manifests (changed chunks arrive in the frame and are checked against
    the new manifest as they are written). Any disagreement raises
    :class:`CheckpointError` — a broken chain never assembles a container."""
    header, payload = parse_delta(frame, source=os.path.basename(out_path))
    try:
        base_header, base_prefix_len, base_info = ckpt_format.read_trailer(
            base_path
        )
    except (CheckpointError, OSError) as e:
        raise CheckpointError(
            f"delta: base container {base_path} unusable ({e})"
        ) from e
    if base_info is None or base_info.chunk_crcs is None:
        raise CheckpointError(
            f"delta: base container {base_path} carries no chunk manifest"
        )
    if base_info.container_crc != header["base_container_crc"]:
        raise CheckpointError(
            f"delta: base container {base_path} is not the frame's base "
            f"(digest mismatch — stale or divergent chain)"
        )
    leaf_sizes = [int(n) for n in header["leaf_sizes"]]
    base_sizes = [int(s["nbytes"]) for s in base_header["leaves"]]
    cs = int(header["chunk_size"])
    if base_sizes != leaf_sizes or base_info.chunk_size != cs:
        raise CheckpointError(
            f"delta: base container {base_path} geometry mismatch"
        )
    new_info = ckpt_format.parse_trailer_v3(
        header["trailer"], source=os.path.basename(out_path)
    )
    new_chunks = new_info.leaf_chunk_crcs(leaf_sizes)
    base_chunks = base_info.leaf_chunk_crcs(leaf_sizes)
    changed = {(int(l), int(c)) for l, c in header["changed"]}
    for leaf, (old, new) in enumerate(zip(base_chunks, new_chunks)):
        for ci, (a, b) in enumerate(zip(old, new)):
            if (leaf, ci) in changed:
                continue
            if a != b:
                raise CheckpointError(
                    f"delta: unchanged chunk (leaf {leaf}, chunk {ci}) "
                    f"disagrees between base and new manifests — broken chain"
                )
    # Frame payload offsets per changed chunk, in header['changed'] order.
    frame_off: dict[tuple[int, int], tuple[int, int]] = {}
    pos = 0
    for l, c in header["changed"]:
        l, c = int(l), int(c)
        n = min(cs, leaf_sizes[l] - c * cs)
        frame_off[(l, c)] = (pos, n)
        pos += n
    if pos > memoryview(payload).nbytes:
        raise CheckpointError("delta: frame payload shorter than its manifest")

    def chunks():
        yield header["prefix"]
        with open(base_path, "rb") as bf:
            base_offs = []
            p = base_prefix_len
            for n in leaf_sizes:
                base_offs.append(p)
                p += n
            for leaf, n in enumerate(leaf_sizes):
                for ci in range(ckpt_format.leaf_chunk_count(n, cs)):
                    clen = min(cs, n - ci * cs)
                    if (leaf, ci) in changed:
                        off, fn = frame_off[(leaf, ci)]
                        window = memoryview(payload)[off : off + fn]
                        if ckpt_format.crc32c(window) != new_chunks[leaf][ci]:
                            raise CheckpointError(
                                f"delta: shipped chunk (leaf {leaf}, chunk "
                                f"{ci}) fails its manifest CRC"
                            )
                        yield window
                    else:
                        bf.seek(base_offs[leaf] + ci * cs)
                        buf = bf.read(clen)
                        if len(buf) != clen:
                            raise CheckpointError(
                                f"delta: base container short read at leaf "
                                f"{leaf} chunk {ci}"
                            )
                        yield buf
        yield header["trailer"]

    return ckpt_format.write_stream(out_path, chunks())


def record_applied(owner: int, iteration: int, outcome: str, **extra) -> None:
    """One ``ckpt_delta_applied`` event per received delta frame →
    ``tpu_ckpt_delta_applied_total{outcome}``."""
    record_event(
        "checkpoint", "ckpt_delta_applied",
        owner=owner, iteration=iteration, outcome=outcome, **extra,
    )
