"""Elastic resharding: resume any checkpoint on any world size / mesh topology.

The local checkpoint tier saves one container per rank, each holding that
rank's *local* block of every global array (``state_dict.py`` pops leaves in
tree order; ``format.py`` records their shapes in the ``TPURES02`` header).
Until this module, a resumed world had to match the saving world's sharding
exactly — losing part of a slice meant "restart blocked until capacity
returns" (the scenario the reference's elastic agent gestures at but never
implements). This module closes that gap with pure index algebra:

- a :class:`TreeLayout` describes how every leaf's GLOBAL index space is
  block-partitioned over a rank grid (the ``parallel/mesh.py`` axis language:
  per-dim axis names over ``{dp, tp, sp, pp, ep, ...}`` sizes). The saving
  world embeds its layout in each container's header meta (``meta["layout"]``,
  schema ``tpu-reshard-1``); any *target* layout — fewer ranks, more ranks, or
  a changed DP/TP split of the same count — is just another ``TreeLayout``.
- :func:`build_plan` intersects the two grids: for each target rank it maps
  every newly-owned index range back to the source grid cell that held it,
  with the candidate source owners (replicas included) and the exact byte
  ranges inside the source leaf payload. Cells of a uniform grid never
  overlap, so the plan covers every global index exactly once by
  construction — :meth:`ReshardPlan.validate` proves it, and
  :meth:`ReshardPlan.require_available` turns "coverage impossible" into a
  :class:`CheckpointError` naming the missing source ranks.
- the execution side lives in ``local_manager.load_resharded`` (slice local
  shards, ranged-fetch the rest from clique peers) and
  ``comm.PeerExchange.fetch_ranges`` (the ranged-read wire op).

Everything here is numpy/stdlib only — the algebra must be runnable from
operator tooling (``ckpt_info --plan``) without touching JAX or tensors.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from tpu_resiliency.exceptions import CheckpointError

#: Mesh axis precedence (outermost first) — matches ``parallel.mesh.build_mesh``:
#: ``pp`` outermost (rare, large-grained hops), ``tp`` innermost (per-matmul
#: collectives on the fastest loops). Layouts may use any subset, or extra
#: axis names appended after these.
AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")

#: Header-meta schema tag for an embedded layout (``meta["layout"]``).
LAYOUT_SCHEMA = "tpu-reshard-1"
LAYOUT_META_KEY = "layout"


def _prod(xs: Iterable[int]) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


@dataclasses.dataclass(frozen=True)
class Box:
    """An axis-aligned block of a global index space: ``offset`` + ``shape``."""

    offset: tuple[int, ...]
    shape: tuple[int, ...]

    @property
    def elems(self) -> int:
        return _prod(self.shape)

    def intersect(self, other: "Box") -> Optional["Box"]:
        off, shp = [], []
        for o1, s1, o2, s2 in zip(self.offset, self.shape, other.offset, other.shape):
            lo, hi = max(o1, o2), min(o1 + s1, o2 + s2)
            if hi <= lo:
                return None
            off.append(lo)
            shp.append(hi - lo)
        return Box(tuple(off), tuple(shp))


@dataclasses.dataclass(frozen=True)
class LeafSpec:
    """One leaf's global geometry + partition spec (axis name or None per dim)."""

    global_shape: tuple[int, ...]
    dtype: str
    spec: tuple[Optional[str], ...]

    @property
    def itemsize(self) -> int:
        from tpu_resiliency.checkpoint.format import resolve_dtype

        return resolve_dtype(self.dtype).itemsize

    @property
    def global_nbytes(self) -> int:
        return _prod(self.global_shape) * self.itemsize


def _normalize_spec(spec: Any, ndim: int) -> tuple[Optional[str], ...]:
    """Accept a PartitionSpec, tuple/list, or None; pad missing trailing dims
    with None (PartitionSpec semantics). Nested tuples (multi-axis dims) are
    not supported — one axis per dim is what ``parallel/mesh.py`` uses."""
    if spec is None:
        entries: list = []
    else:
        entries = list(spec)
    out: list[Optional[str]] = []
    for e in entries:
        if e is None:
            out.append(None)
        elif isinstance(e, str):
            out.append(e)
        else:
            raise CheckpointError(
                f"reshard: unsupported partition-spec entry {e!r} "
                f"(one axis name or None per dim)"
            )
    if len(out) > ndim:
        raise CheckpointError(
            f"reshard: spec {tuple(entries)} longer than array rank {ndim}"
        )
    out.extend([None] * (ndim - len(out)))
    return tuple(out)


class TreeLayout:
    """How a whole pytree's leaves are block-partitioned over a rank grid.

    ``axes`` is an ordered ``(name, size)`` sequence (outermost first; the
    mesh axis order); ``ranks`` lists the world's rank ids in row-major grid
    order; ``leaves`` gives each leaf's global shape, dtype and per-dim axis
    spec. A leaf dim sharded on axis ``a`` is split into ``size(a)`` balanced
    contiguous blocks (``np.array_split`` bounds: block ``j`` spans
    ``[D*j//n, D*(j+1)//n)`` — uniform when divisible, off-by-one otherwise,
    which is what lets a world shrink 4→3 without a divisibility miracle);
    axes a leaf does not use replicate it across those axes — every rank
    sharing a grid cell holds an identical copy (the redundancy a shrink
    survives on).
    """

    def __init__(
        self,
        axes: Sequence[tuple[str, int]],
        ranks: Sequence[int],
        leaves: Sequence[LeafSpec],
    ):
        self.axes: tuple[tuple[str, int], ...] = tuple(
            (str(n), int(s)) for n, s in axes
        )
        self.ranks: tuple[int, ...] = tuple(int(r) for r in ranks)
        # Specs normalize to one entry per dim (short PartitionSpec-style
        # tuples pad trailing dims with None = replicated).
        self.leaves: list[LeafSpec] = [
            LeafSpec(
                global_shape=tuple(int(x) for x in l.global_shape),
                dtype=str(l.dtype),
                spec=_normalize_spec(l.spec, len(l.global_shape)),
            )
            for l in leaves
        ]
        sizes = dict(self.axes)
        if len(sizes) != len(self.axes):
            raise CheckpointError(f"reshard: duplicate axis names in {self.axes}")
        if _prod(s for _, s in self.axes) != len(self.ranks):
            raise CheckpointError(
                f"reshard: axes {dict(self.axes)} describe "
                f"{_prod(s for _, s in self.axes)} ranks, got {len(self.ranks)}"
            )
        if len(set(self.ranks)) != len(self.ranks):
            raise CheckpointError(f"reshard: duplicate rank ids in {self.ranks}")
        for i, leaf in enumerate(self.leaves):
            used = [a for a in leaf.spec if a is not None]
            if len(used) != len(set(used)):
                raise CheckpointError(
                    f"reshard: leaf {i} uses an axis on more than one dim: "
                    f"{leaf.spec}"
                )
            for d, a in enumerate(leaf.spec):
                if a is None:
                    continue
                if a not in sizes:
                    raise CheckpointError(
                        f"reshard: leaf {i} dim {d} sharded on unknown axis "
                        f"{a!r} (axes: {sorted(sizes)})"
                    )

    # -- geometry ----------------------------------------------------------

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    def coords(self, rank: int) -> dict[str, int]:
        """Grid coordinates of ``rank`` (row-major over the axis order)."""
        try:
            i = self.ranks.index(rank)
        except ValueError:
            raise CheckpointError(
                f"reshard: rank {rank} not in layout world {list(self.ranks)}"
            ) from None
        out: dict[str, int] = {}
        for name, size in reversed(self.axes):
            out[name] = i % size
            i //= size
        return out

    def box(self, leaf: int, rank: int) -> Box:
        """``rank``'s local block of leaf ``leaf``'s global index space
        (balanced ``np.array_split`` bounds per sharded dim)."""
        spec = self.leaves[leaf]
        sizes = dict(self.axes)
        coords = self.coords(rank)
        offset, shape = [], []
        for d, ax in enumerate(spec.spec):
            if ax is None:
                offset.append(0)
                shape.append(spec.global_shape[d])
            else:
                D, n, c = spec.global_shape[d], sizes[ax], coords[ax]
                lo, hi = D * c // n, D * (c + 1) // n
                offset.append(lo)
                shape.append(hi - lo)
        return Box(tuple(offset), tuple(shape))

    def local_nbytes(self, leaf: int, rank: int) -> int:
        return self.box(leaf, rank).elems * self.leaves[leaf].itemsize

    def cells(self, leaf: int) -> list[tuple[Box, tuple[int, ...]]]:
        """Distinct blocks of leaf ``leaf`` with the ranks that hold each —
        replicas grouped (identical box ⇒ identical bytes). Deterministic
        order: by block offset, owners sorted."""
        by_box: dict[tuple, list[int]] = {}
        for r in self.ranks:
            b = self.box(leaf, r)
            by_box.setdefault((b.offset, b.shape), []).append(r)
        return [
            (Box(off, shp), tuple(sorted(owners)))
            for (off, shp), owners in sorted(by_box.items())
        ]

    # -- serialization -----------------------------------------------------

    def to_meta(self) -> dict:
        """The container-meta form (rides ``meta["layout"]`` in every saved
        header, so ANY surviving container describes the whole saved world)."""
        return {
            "schema": LAYOUT_SCHEMA,
            "axes": [[n, s] for n, s in self.axes],
            "ranks": list(self.ranks),
            "leaves": [
                {
                    "global_shape": list(l.global_shape),
                    "dtype": l.dtype,
                    "spec": list(l.spec),
                }
                for l in self.leaves
            ],
        }

    @classmethod
    def from_meta(cls, meta: dict) -> "TreeLayout":
        if not isinstance(meta, dict) or meta.get("schema") != LAYOUT_SCHEMA:
            raise CheckpointError(
                f"reshard: not a {LAYOUT_SCHEMA} layout meta: "
                f"{type(meta).__name__}"
            )
        try:
            return cls(
                axes=[(n, int(s)) for n, s in meta["axes"]],
                ranks=[int(r) for r in meta["ranks"]],
                leaves=[
                    LeafSpec(
                        global_shape=tuple(int(x) for x in l["global_shape"]),
                        dtype=str(l["dtype"]),
                        spec=tuple(
                            None if a is None else str(a) for a in l["spec"]
                        ),
                    )
                    for l in meta["leaves"]
                ],
            )
        except (KeyError, TypeError, ValueError) as e:
            raise CheckpointError(f"reshard: corrupt layout meta ({e!r})") from e

    # -- construction helpers ----------------------------------------------

    @classmethod
    def for_local_tree(
        cls,
        tree: Any,
        spec_tree: Any,
        axes: Sequence[tuple[str, int]] | dict[str, int],
        ranks: Sequence[int],
        global_shapes: Optional[Sequence[tuple[int, ...]]] = None,
    ) -> "TreeLayout":
        """Build a layout from a rank's LOCAL pytree + a mirrored spec pytree.

        ``spec_tree`` mirrors ``tree`` with a per-leaf partition spec (a
        ``jax.sharding.PartitionSpec``, a tuple of axis names / ``None``, or
        ``None`` for fully replicated) at each array leaf. Global shapes are
        inferred as ``local * size(a)`` per sharded dim — exact when the dim
        divides evenly (the usual save-time world); a world holding BALANCED
        blocks (it resumed via a non-divisible reshard) passes the true
        ``global_shapes`` explicitly (or just reuses the layout
        ``load_resharded`` returned in ``meta``). Non-array leaves (step
        counters) are skipped — leaf order matches
        ``PyTreeStateDict.pop_tensors``."""
        import jax

        from tpu_resiliency.checkpoint.state_dict import _is_array

        if isinstance(axes, dict):
            order = [a for a in AXIS_ORDER if a in axes]
            order += [a for a in axes if a not in AXIS_ORDER]
            axes = [(a, axes[a]) for a in order]
        sizes = dict(axes)

        def is_spec(x) -> bool:
            if x is None:
                return True
            try:
                from jax.sharding import PartitionSpec

                if isinstance(x, PartitionSpec):
                    return True
            except ImportError:  # pragma: no cover
                pass
            return isinstance(x, (tuple, list)) and all(
                e is None or isinstance(e, str) for e in x
            )

        data_leaves = jax.tree_util.tree_flatten(tree)[0]
        spec_leaves = jax.tree_util.tree_flatten(spec_tree, is_leaf=is_spec)[0]
        arrays = [l for l in data_leaves if _is_array(l)]
        if len(spec_leaves) == len(data_leaves):
            # Mirrored structure: specs for non-array leaves are ignored.
            spec_for = [
                s for l, s in zip(data_leaves, spec_leaves) if _is_array(l)
            ]
        elif len(spec_leaves) == len(arrays):
            spec_for = list(spec_leaves)
        else:
            raise CheckpointError(
                f"reshard: spec tree has {len(spec_leaves)} leaves for a tree "
                f"with {len(data_leaves)} leaves ({len(arrays)} arrays)"
            )
        if global_shapes is not None and len(global_shapes) != len(arrays):
            raise CheckpointError(
                f"reshard: {len(global_shapes)} global shapes for "
                f"{len(arrays)} array leaves"
            )
        leaves = []
        for i, (arr, raw) in enumerate(zip(arrays, spec_for)):
            spec = _normalize_spec(raw, np.ndim(arr))
            if global_shapes is not None:
                gshape = tuple(int(x) for x in global_shapes[i])
            else:
                gshape = tuple(
                    int(s) * (sizes[a] if a is not None else 1)
                    for s, a in zip(np.shape(arr), spec)
                )
            dt = np.dtype(getattr(arr.dtype, "name", arr.dtype)).name
            leaves.append(LeafSpec(gshape, dt, spec))
        return cls(axes=list(axes), ranks=ranks, leaves=leaves)

    def retarget(
        self,
        ranks: Sequence[int],
        axes: Sequence[tuple[str, int]] | dict[str, int] | None = None,
    ) -> "TreeLayout":
        """The layout this tree would have on a DIFFERENT world.

        Default rule (elastic data-parallel practice: shrink/grow ``dp``,
        keep the model split): every axis keeps its size except ``dp``, which
        absorbs the world-size change. Pass ``axes`` explicitly for a changed
        model split (e.g. a new dp/tp factorization of the same count)."""
        ranks = [int(r) for r in ranks]
        if axes is None:
            others = _prod(s for n, s in self.axes if n != "dp")
            if len(ranks) % others != 0:
                raise CheckpointError(
                    f"reshard: cannot retarget world of {len(ranks)} ranks by "
                    f"rescaling dp: non-dp axes fix a factor of {others}"
                )
            axes = [
                (n, len(ranks) // others if n == "dp" else s)
                for n, s in self.axes
            ]
            if "dp" not in dict(self.axes):
                if others != len(ranks):
                    axes = [("dp", len(ranks) // others)] + list(axes)
        elif isinstance(axes, dict):
            order = [a for a in AXIS_ORDER if a in axes]
            order += [a for a in axes if a not in AXIS_ORDER]
            axes = [(a, axes[a]) for a in order]
        return TreeLayout(axes=list(axes), ranks=ranks, leaves=self.leaves)


def extract_layout(meta: dict) -> Optional[TreeLayout]:
    """Pull an embedded layout out of a container's ``meta`` (None if absent)."""
    raw = (meta or {}).get(LAYOUT_META_KEY)
    if raw is None:
        return None
    return TreeLayout.from_meta(raw)


# -- the plan -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Range:
    """One contiguous byte run: ``src_off`` inside the source leaf payload,
    ``dst_off`` inside the target rank's local leaf buffer."""

    src_off: int
    dst_off: int
    nbytes: int


@dataclasses.dataclass
class Segment:
    """The part of one target leaf served by one source grid cell: any of
    ``owners`` (replicas — identical bytes) can serve ``ranges``."""

    leaf: int
    owners: tuple[int, ...]
    ranges: list[Range]

    @property
    def nbytes(self) -> int:
        return sum(r.nbytes for r in self.ranges)


@dataclasses.dataclass
class RankPlan:
    """Everything one target rank must assemble."""

    rank: int
    #: per-leaf target local shape (the box this rank owns under the target layout)
    local_shapes: list[tuple[int, ...]]
    segments: list[Segment]

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.segments)


def _box_ranges(inter: Box, src: Box, dst: Box, itemsize: int) -> list[Range]:
    """Decompose the intersection box into byte runs contiguous in BOTH the
    source local array and the target local array (C order).

    Trailing dims the intersection spans fully in both collapse into the run;
    the next dim up becomes the run dim (any contiguous index interval along
    it stays contiguous in both memories). Adjacent runs coalesce."""
    n = len(inter.shape)
    if n == 0:  # scalar leaf
        return [Range(0, 0, itemsize)]
    rel_src = tuple(i - s for i, s in zip(inter.offset, src.offset))
    rel_dst = tuple(i - s for i, s in zip(inter.offset, dst.offset))
    k = n
    while k > 0 and inter.shape[k - 1] == src.shape[k - 1] == dst.shape[k - 1]:
        k -= 1
    if k == 0:
        return [Range(0, 0, inter.elems * itemsize)]
    run_elems = _prod(inter.shape[k - 1 :])
    src_strides = [_prod(src.shape[d + 1 :]) for d in range(n)]
    dst_strides = [_prod(dst.shape[d + 1 :]) for d in range(n)]
    base_src = sum(rel_src[d] * src_strides[d] for d in range(k))
    base_dst = sum(rel_dst[d] * dst_strides[d] for d in range(k))
    ranges: list[Range] = []
    for coord in np.ndindex(*inter.shape[: k - 1]):
        so = base_src + sum(c * src_strides[d] for d, c in enumerate(coord))
        do = base_dst + sum(c * dst_strides[d] for d, c in enumerate(coord))
        ranges.append(Range(so * itemsize, do * itemsize, run_elems * itemsize))
    ranges.sort(key=lambda r: r.dst_off)
    merged: list[Range] = []
    for r in ranges:
        if (
            merged
            and merged[-1].dst_off + merged[-1].nbytes == r.dst_off
            and merged[-1].src_off + merged[-1].nbytes == r.src_off
        ):
            merged[-1] = Range(
                merged[-1].src_off, merged[-1].dst_off, merged[-1].nbytes + r.nbytes
            )
        else:
            merged.append(r)
    return merged


class ReshardPlan:
    """The full repartition map for (source layout) → (target layout)."""

    def __init__(self, source: TreeLayout, target: TreeLayout):
        if len(source.leaves) != len(target.leaves):
            raise CheckpointError(
                f"reshard: leaf count mismatch (source {len(source.leaves)}, "
                f"target {len(target.leaves)})"
            )
        for i, (a, b) in enumerate(zip(source.leaves, target.leaves)):
            if a.global_shape != b.global_shape or a.dtype != b.dtype:
                raise CheckpointError(
                    f"reshard: leaf {i} geometry mismatch — source "
                    f"{a.global_shape}/{a.dtype} vs target "
                    f"{b.global_shape}/{b.dtype}"
                )
        self.source = source
        self.target = target
        self._cells = [source.cells(i) for i in range(len(source.leaves))]
        self._per_rank: dict[int, RankPlan] = {}

    @property
    def direction(self) -> str:
        n, m = self.source.world_size, self.target.world_size
        return "shrink" if m < n else ("grow" if m > n else "resplit")

    def for_rank(self, rank: int) -> RankPlan:
        if rank not in self._per_rank:
            self._per_rank[rank] = self._build_rank(rank)
        return self._per_rank[rank]

    def _build_rank(self, rank: int) -> RankPlan:
        shapes: list[tuple[int, ...]] = []
        segments: list[Segment] = []
        for i, spec in enumerate(self.target.leaves):
            tbox = self.target.box(i, rank)
            shapes.append(tbox.shape)
            for sbox, owners in self._cells[i]:
                inter = tbox.intersect(sbox)
                if inter is None:
                    continue
                segments.append(
                    Segment(
                        leaf=i,
                        owners=owners,
                        ranges=_box_ranges(inter, sbox, tbox, spec.itemsize),
                    )
                )
        return RankPlan(rank=rank, local_shapes=shapes, segments=segments)

    # -- proofs ------------------------------------------------------------

    def validate(self) -> None:
        """Prove exact cover: for every target rank, every leaf's local byte
        space is tiled by the plan's destination ranges with no gap and no
        overlap (grid cells of a uniform partition cannot overlap, but this
        check holds regardless of how the plan was built)."""
        for rank in self.target.ranks:
            rp = self.for_rank(rank)
            for i, spec in enumerate(self.target.leaves):
                want = _prod(rp.local_shapes[i]) * spec.itemsize
                runs = sorted(
                    (r.dst_off, r.nbytes)
                    for s in rp.segments
                    if s.leaf == i
                    for r in s.ranges
                )
                pos = 0
                for off, nb in runs:
                    if off != pos:
                        raise CheckpointError(
                            f"reshard plan: leaf {i} target rank {rank} "
                            f"{'overlap' if off < pos else 'gap'} at byte "
                            f"{min(off, pos)} (expected {pos}, got {off})"
                        )
                    pos = off + nb
                if pos != want:
                    raise CheckpointError(
                        f"reshard plan: leaf {i} target rank {rank} covers "
                        f"{pos} of {want} bytes"
                    )

    def missing_sources(self, available: Iterable[int]) -> dict[int, list[int]]:
        """Source ranks whose data is needed but absent: ``{leaf: [ranks]}``
        of cells where NO replica owner is in ``available``."""
        avail = set(int(r) for r in available)
        out: dict[int, set[int]] = {}
        for rank in self.target.ranks:
            for seg in self.for_rank(rank).segments:
                if not (set(seg.owners) & avail):
                    out.setdefault(seg.leaf, set()).update(seg.owners)
        return {leaf: sorted(ranks) for leaf, ranks in sorted(out.items())}

    def require_available(self, available: Iterable[int]) -> None:
        """Raise a :class:`CheckpointError` naming the missing source ranks
        when ``available`` cannot cover the target world."""
        missing = self.missing_sources(available)
        if missing:
            all_missing = sorted({r for rs in missing.values() for r in rs})
            raise CheckpointError(
                f"reshard: coverage impossible — no surviving copy of source "
                f"rank(s) {all_missing} (needed for leaf(s) "
                f"{sorted(missing)}; available: {sorted(set(available))})"
            )

    # -- summaries ---------------------------------------------------------

    def summary(
        self,
        rank: Optional[int] = None,
        local_owners: Optional[dict[int, set[int]]] = None,
    ) -> dict:
        """Byte accounting for one rank (or the whole target world).

        ``local_owners[rank]`` = source-owner containers on that rank's own
        disk; ranges servable from one of them count as ``local_bytes``,
        everything else as ``peer_bytes`` (the ranged-fetch volume)."""
        ranks = [rank] if rank is not None else list(self.target.ranks)
        local = peer = total = nranges = 0
        for r in ranks:
            held = (local_owners or {}).get(r, set())
            for seg in self.for_rank(r).segments:
                nb = seg.nbytes
                total += nb
                nranges += len(seg.ranges)
                if set(seg.owners) & set(held):
                    local += nb
                else:
                    peer += nb
        return {
            "direction": self.direction,
            "source_world": self.source.world_size,
            "target_world": self.target.world_size,
            "ranks": len(ranks),
            "total_bytes": total,
            "local_bytes": local,
            "peer_bytes": peer,
            "ranges": nranges,
        }


def build_plan(source: TreeLayout, target: TreeLayout) -> ReshardPlan:
    """Compute (and prove) the repartition plan for source → target."""
    plan = ReshardPlan(source, target)
    plan.validate()
    return plan


def assemble_rank(
    plan: ReshardPlan,
    rank: int,
    read_range,
    pick_owner=None,
) -> list[np.ndarray]:
    """Materialize ``rank``'s target-local leaves from a plan.

    ``read_range(owner, leaf, src_off, nbytes) -> bytes-like`` supplies source
    bytes; ``pick_owner(segment) -> owner`` chooses among replicas (default:
    lowest rank). The in-memory executor behind the property tests and any
    caller that already has all source shards at hand — the on-disk / ranged-
    fetch executor is ``local_manager.load_resharded``."""
    rp = plan.for_rank(rank)
    out: list[np.ndarray] = []
    buffers: list[np.ndarray] = []
    for i, spec in enumerate(plan.target.leaves):
        from tpu_resiliency.checkpoint.format import resolve_dtype

        buf = np.empty(rp.local_shapes[i], dtype=resolve_dtype(spec.dtype))
        buffers.append(buf)
        out.append(buf)
    for seg in rp.segments:
        owner = pick_owner(seg) if pick_owner is not None else seg.owners[0]
        flat = buffers[seg.leaf].reshape(-1).view(np.uint8)
        for r in seg.ranges:
            got = read_range(owner, seg.leaf, r.src_off, r.nbytes)
            view = memoryview(got)
            if view.nbytes != r.nbytes:
                raise CheckpointError(
                    f"reshard: short read from owner {owner} leaf {seg.leaf} "
                    f"({view.nbytes} of {r.nbytes} bytes)"
                )
            flat[r.dst_off : r.dst_off + r.nbytes] = np.frombuffer(
                view, dtype=np.uint8
            )
    return out


def slice_local(
    global_arrays: Sequence[np.ndarray], layout: TreeLayout, rank: int
) -> list[np.ndarray]:
    """A rank's local blocks of materialized global arrays (test/bench helper
    — production shards come off the device already local)."""
    out = []
    for i, arr in enumerate(global_arrays):
        b = layout.box(i, rank)
        sl = tuple(slice(o, o + s) for o, s in zip(b.offset, b.shape))
        out.append(np.ascontiguousarray(arr[sl]))
    return out
