"""In-job restarter state machine with the machine-parseable log-line contract.

Analogue of the reference's ``RankMonitorStateMachine``
(``fault_tolerance/rank_monitor_state_machine.py:98-145``): states with an
allowed-transition table, emitting ``[NestedRestarter] name=[InJob] state=... ...``
lines consumed by external watchers and by the layered-restart protocol that couples
the in-job and in-process restarters (``inprocess/nested_restarter.py:16-23``).
"""

from __future__ import annotations

import enum
import logging
from typing import Optional

from tpu_resiliency.exceptions import InternalError
from tpu_resiliency.utils.logging import get_logger

LOG_MARKER = "[NestedRestarter]"


class RestarterState(enum.Enum):
    UNINITIALIZED = "uninitialized"
    INITIALIZE = "initialize"
    HANDLING_START = "handling_start"
    HANDLING_PROCESSING = "handling_processing"
    HANDLING_COMPLETED = "handling_completed"
    FINALIZED = "finalized"
    ABORTED = "aborted"


_ALLOWED: dict[RestarterState, frozenset[RestarterState]] = {
    RestarterState.UNINITIALIZED: frozenset({RestarterState.INITIALIZE}),
    RestarterState.INITIALIZE: frozenset(
        {RestarterState.HANDLING_START, RestarterState.FINALIZED, RestarterState.ABORTED}
    ),
    RestarterState.HANDLING_START: frozenset(
        {RestarterState.HANDLING_PROCESSING, RestarterState.ABORTED}
    ),
    RestarterState.HANDLING_PROCESSING: frozenset(
        {RestarterState.HANDLING_COMPLETED, RestarterState.ABORTED}
    ),
    RestarterState.HANDLING_COMPLETED: frozenset(
        {RestarterState.HANDLING_START, RestarterState.FINALIZED, RestarterState.ABORTED}
    ),
    RestarterState.FINALIZED: frozenset(),
    RestarterState.ABORTED: frozenset(),
}


class RestarterStateMachine:
    """Tracks restarter state and logs every transition in the parseable format."""

    def __init__(
        self,
        name: str = "InJob",
        logger: Optional[logging.Logger] = None,
        strict: bool = True,
    ):
        self.name = name
        self.state = RestarterState.UNINITIALIZED
        self.strict = strict
        self._log = logger or get_logger(f"watchdog.restarter.{name}")

    def transition(self, new_state: RestarterState, detail: str = "") -> None:
        if new_state not in _ALLOWED[self.state]:
            msg = f"restarter {self.name}: illegal transition {self.state.name} → {new_state.name}"
            if self.strict:
                raise InternalError(msg)
            self._log.warning(msg)
        self.state = new_state
        line = f"{LOG_MARKER} name=[{self.name}] state={new_state.value}"
        if detail:
            line += f" {detail}"
        self._log.info(line)

    # convenience transitions mirroring the reference protocol
    def initialize(self):
        self.transition(RestarterState.INITIALIZE)

    def handling_start(self, detail: str = ""):
        self.transition(RestarterState.HANDLING_START, detail)

    def handling_processing(self, detail: str = ""):
        self.transition(RestarterState.HANDLING_PROCESSING, detail)

    def handling_completed(self, detail: str = ""):
        self.transition(RestarterState.HANDLING_COMPLETED, detail)

    def finalized(self, detail: str = ""):
        self.transition(RestarterState.FINALIZED, detail)

    def aborted(self, detail: str = ""):
        self.transition(RestarterState.ABORTED, detail)
