"""Per-rank monitor process: Unix-socket server + periodic timeout/health checks.

Analogue of the reference's ``RankMonitorServer`` (``fault_tolerance/rank_monitor_server.py``):
one asyncio process per rank, forked by the launcher (``:488-512``); handles
Init/Heartbeat/Section/UpdateTimeouts messages (``:307-340``); a periodic task checks
heartbeat timeout (``_is_hb_timeout_elapsed:349``), section / out-of-section timeouts
(``:369``) and optional health checks (``:411-414``); on violation it sends SIGCONT +
the configured termination signal to the rank PID (``_shutdown_rank:176``) so the
launcher's worker poll sees the death and triggers an in-job restart.
"""

from __future__ import annotations

import asyncio
import dataclasses
import multiprocessing as mp
import os
import signal
import time
from typing import Optional

from tpu_resiliency.platform import framing
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import RankLoggerAdapter, get_logger
from tpu_resiliency.watchdog.config import FaultToleranceConfig
from tpu_resiliency.watchdog.data import (
    ErrorMsg,
    HeartbeatMsg,
    HeartbeatTimeouts,
    InitMsg,
    InitReplyMsg,
    OkMsg,
    RankInfo,
    SectionAction,
    SectionMsg,
    SectionTimeouts,
    UpdateTimeoutsMsg,
)
from tpu_resiliency.watchdog.health import (
    HealthCheck,
    PeriodicHealthMonitor,
    checks_from_config,
)
from tpu_resiliency.watchdog.state_machine import RestarterStateMachine, RestarterState

log = get_logger(__name__)


@dataclasses.dataclass
class _RankSession:
    info: RankInfo
    connected_at: float
    last_hb: Optional[float] = None
    open_sections: dict = dataclasses.field(default_factory=dict)  # name -> open ts
    last_section_activity: Optional[float] = None
    terminated: bool = False
    #: heartbeat statistics for the disconnect-time ``heartbeat_stats`` record:
    #: observed gap distribution is what calibrated timeouts are judged against
    hb_count: int = 0
    max_hb_gap: float = 0.0


class RankMonitorServer:
    def __init__(
        self,
        cfg: FaultToleranceConfig,
        socket_path: str,
        health_checks: Optional[list[HealthCheck]] = None,
    ):
        self.cfg = cfg
        self.socket_path = socket_path
        self.session: Optional[_RankSession] = None
        self.hb_timeouts = HeartbeatTimeouts(
            initial=cfg.initial_rank_heartbeat_timeout,
            subsequent=cfg.rank_heartbeat_timeout,
            calculated=False,
        )
        self.section_timeouts = SectionTimeouts(
            section=dict(cfg.rank_section_timeouts),
            out_of_section=cfg.rank_out_of_section_timeout,
        )
        if health_checks is None:
            # Config-enabled built-ins (host memory floor, ICI link counters) —
            # explicit lists override, an explicit [] disables.
            health_checks = checks_from_config(cfg)
        self.health_checks = health_checks
        self._health_monitor: Optional[PeriodicHealthMonitor] = None
        self._health_failure: Optional[str] = None
        self.restarter = RestarterStateMachine("InJob", strict=False)
        self.log = RankLoggerAdapter(log, role="monitor")
        self._stop_event: Optional[asyncio.Event] = None

    # -- lifecycle ---------------------------------------------------------

    async def serve(self) -> None:
        self._stop_event = asyncio.Event()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        os.makedirs(os.path.dirname(self.socket_path) or ".", exist_ok=True)
        server = await asyncio.start_unix_server(self._handle_conn, path=self.socket_path)
        self.restarter.initialize()
        if self.health_checks and self.cfg.enable_health_checks:
            self._health_monitor = PeriodicHealthMonitor(
                self.health_checks,
                self.cfg.health_check_interval,
                self._on_health_failure,
            )
            self._health_monitor.start()
        checker = asyncio.create_task(self._periodic_check())
        try:
            async with server:
                await self._stop_event.wait()
        finally:
            checker.cancel()
            if self._health_monitor:
                self._health_monitor.stop()
            if os.path.exists(self.socket_path):
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass

    def run(self) -> None:
        asyncio.run(self.serve())

    @classmethod
    def run_in_subprocess(
        cls,
        cfg: FaultToleranceConfig,
        socket_path: str,
        health_checks: Optional[list[HealthCheck]] = None,
        start_method: str = "fork",
    ) -> mp.Process:
        """Fork a monitor process (reference ``rank_monitor_server.py:488-512``).

        Waits until the server socket exists so the worker can connect immediately.
        """
        ctx = mp.get_context(start_method)
        # A stale socket file from a SIGKILLed predecessor would satisfy the readiness
        # poll below before the child has actually bound its listener.
        if os.path.exists(socket_path):
            try:
                os.unlink(socket_path)
            except OSError:
                pass
        proc = ctx.Process(
            target=_monitor_main, args=(cfg, socket_path, health_checks), daemon=True
        )
        proc.start()
        # Generous: spawn-started monitors (used by tests to avoid forking a
        # JAX-threaded parent) pay full interpreter startup, which on TPU images
        # can be several seconds even unloaded.
        deadline = time.monotonic() + 60.0
        while not os.path.exists(socket_path):
            if time.monotonic() > deadline or not proc.is_alive():
                raise RuntimeError(f"rank monitor failed to start on {socket_path}")
            time.sleep(0.01)
        return proc

    # -- connection handling ----------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                try:
                    msg = await framing.read_obj_stream(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                reply = self._dispatch(msg)
                await framing.write_obj_stream(writer, reply)
        finally:
            if self.session is not None:
                s = self.session
                self.log.info(
                    f"rank {s.info.global_rank} disconnected from monitor"
                )
                if s.hb_count:
                    # One summary record per monitored session, not one per
                    # heartbeat: the max gap is the margin-to-timeout an
                    # operator tunes ``rank_heartbeat_timeout`` against.
                    record_event(
                        "watchdog", "heartbeat_stats",
                        global_rank=s.info.global_rank,
                        heartbeats=s.hb_count,
                        max_gap_s=round(s.max_hb_gap, 6),
                        timeout_s=self.hb_timeouts.subsequent,
                    )
            writer.close()

    def _dispatch(self, msg):
        try:
            if isinstance(msg, InitMsg):
                return self._on_init(msg)
            if isinstance(msg, HeartbeatMsg):
                return self._on_heartbeat(msg)
            if isinstance(msg, SectionMsg):
                return self._on_section(msg)
            if isinstance(msg, UpdateTimeoutsMsg):
                return self._on_update_timeouts(msg)
            return ErrorMsg(f"unknown message {type(msg).__name__}")
        except Exception as e:
            self.log.exception("monitor dispatch failed")
            return ErrorMsg(repr(e))

    def _on_init(self, msg: InitMsg):
        self.session = _RankSession(info=msg.rank_info, connected_at=time.monotonic())
        if msg.client_state:
            hb = msg.client_state.get("hb_timeouts")
            if hb is not None:
                self.hb_timeouts = hb
            st = msg.client_state.get("section_timeouts")
            if st is not None:
                self.section_timeouts = st
        self.log.rank = msg.rank_info.global_rank
        self.log.info(f"monitoring rank {msg.rank_info.global_rank} pid {msg.rank_info.pid}")
        return InitReplyMsg(
            config=self.cfg,
            hb_timeouts=self.hb_timeouts,
            section_timeouts=self.section_timeouts,
        )

    def _on_heartbeat(self, msg: HeartbeatMsg):
        if self.session is None:
            return ErrorMsg("heartbeat before init")
        s = self.session
        now = time.monotonic()
        if s.last_hb is not None:
            s.max_hb_gap = max(s.max_hb_gap, now - s.last_hb)
        s.hb_count += 1
        s.last_hb = now
        return OkMsg()

    def _on_section(self, msg: SectionMsg):
        if self.session is None:
            return ErrorMsg("section message before init")
        now = time.monotonic()
        s = self.session
        if msg.action is SectionAction.OPEN:
            if msg.name in s.open_sections:
                return ErrorMsg(f"section {msg.name!r} already open")
            s.open_sections[msg.name] = now
        elif msg.action is SectionAction.CLOSE:
            if msg.name not in s.open_sections:
                return ErrorMsg(f"section {msg.name!r} not open")
            del s.open_sections[msg.name]
        elif msg.action is SectionAction.CLOSE_ALL:
            s.open_sections.clear()
        s.last_section_activity = now
        return OkMsg()

    def _on_update_timeouts(self, msg: UpdateTimeoutsMsg):
        if msg.hb_timeouts is not None:
            self.hb_timeouts = msg.hb_timeouts
        if msg.section_timeouts is not None:
            self.section_timeouts = msg.section_timeouts
        self.log.info(
            f"timeouts updated: hb={self.hb_timeouts} sections={self.section_timeouts}"
        )
        return OkMsg()

    # -- periodic checks ---------------------------------------------------

    def _hb_timeout_elapsed(self, now: float) -> Optional[str]:
        s = self.session
        if s.last_hb is None:
            t = self.hb_timeouts.initial
            if t is not None and now - s.connected_at > t:
                return f"no initial heartbeat within {t:.1f}s"
        else:
            t = self.hb_timeouts.subsequent
            if t is not None and now - s.last_hb > t:
                return f"heartbeat gap exceeded {t:.1f}s"
        return None

    def _section_timeout_elapsed(self, now: float) -> Optional[str]:
        s = self.session
        for name, opened in s.open_sections.items():
            t = self.section_timeouts.section.get(name)
            if t is not None and now - opened > t:
                return f"section {name!r} open for more than {t:.1f}s"
        t = self.section_timeouts.out_of_section
        if t is not None and not s.open_sections and s.last_section_activity is not None:
            if now - s.last_section_activity > t:
                return f"out-of-section for more than {t:.1f}s"
        return None

    async def _periodic_check(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.workload_check_interval)
            try:
                if self.session is None or self.session.terminated:
                    continue
                now = time.monotonic()
                cause = "hang"
                via = "heartbeat"
                reason = self._hb_timeout_elapsed(now)
                if reason is None:
                    reason = self._section_timeout_elapsed(now)
                    via = "section"
                if reason is None and self._health_failure is not None:
                    reason = f"health check failed: {self._health_failure}"
                    cause, via = "health", "health"
                if reason is not None:
                    self._terminate_rank(reason, cause, via)
            except asyncio.CancelledError:
                raise
            except Exception:
                # The checker must survive anything (e.g. os.kill PermissionError on a
                # reused PID) — a dead checker silently disables hang detection.
                self.log.exception("periodic check iteration failed; continuing")

    def _on_health_failure(self, check: HealthCheck) -> None:
        self._health_failure = check.describe()

    def _terminate_rank(self, reason: str, cause: str = "hang", via: str = "?") -> None:
        s = self.session
        s.terminated = True
        # Distinct kinds: hang (heartbeat/section timeout) vs health (device/node
        # check failure) — consumers triage the two very differently. ``via``
        # splits the hang kind further (heartbeat gap vs section timeout).
        record_event(
            "watchdog",
            "hang_detected" if cause == "hang" else "health_terminated",
            global_rank=s.info.global_rank,
            pid=s.info.pid, reason=reason, via=via,
        )
        # The monitor holds the heartbeat/section story the dying rank cannot
        # tell: snapshot this process's ring before the kill ladder runs, so
        # the incident artifact carries the detection side even if the
        # monitor itself is torn down right after.
        from tpu_resiliency.utils import flight_recorder

        flight_recorder.flush(
            "kill_ladder", detail=f"rank {s.info.global_rank}: {reason}"
        )
        self.restarter.handling_start(f"reason={reason!r}")
        self.log.error(f"terminating rank {s.info.global_rank} (pid {s.info.pid}): {reason}")
        self.restarter.handling_processing()
        try:
            # Each rung of the kill ladder is its own record: the step that
            # actually ended the rank (this signal, or the launcher's later
            # SIGKILL escalation) is reconstructable from the stream.
            os.kill(s.info.pid, signal.SIGCONT)  # wake a stopped process first
            self._record_kill("SIGCONT", s)
            term = self.cfg.rank_termination_signal
            os.kill(s.info.pid, term)
            try:
                term_name = signal.Signals(term).name
            except ValueError:
                term_name = str(term)
            self._record_kill(term_name, s)
        except ProcessLookupError:
            self.log.info("rank process already gone")
        self.restarter.handling_completed()

    @staticmethod
    def _record_kill(step: str, s: _RankSession) -> None:
        record_event(
            "watchdog", "kill_ladder", step=step,
            global_rank=s.info.global_rank, pid=s.info.pid,
        )

    def request_stop(self) -> None:
        if self._stop_event is not None:
            self._stop_event.set()


def _monitor_main(cfg, socket_path, health_checks) -> None:
    # A forked monitor must never touch the parent's TPU runtime.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    RankMonitorServer(cfg, socket_path, health_checks).run()
