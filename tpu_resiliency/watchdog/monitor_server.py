"""Per-rank monitor process: Unix-socket server + periodic timeout/health checks.

Analogue of the reference's ``RankMonitorServer`` (``fault_tolerance/rank_monitor_server.py``):
one asyncio process per rank, forked by the launcher (``:488-512``); handles
Init/Heartbeat/Section/UpdateTimeouts messages (``:307-340``); a periodic task checks
heartbeat timeout (``_is_hb_timeout_elapsed:349``), section / out-of-section timeouts
(``:369``) and optional health checks (``:411-414``); on violation it sends SIGCONT +
the configured termination signal to the rank PID (``_shutdown_rank:176``) so the
launcher's worker poll sees the death and triggers an in-job restart.
"""

from __future__ import annotations

import asyncio
import dataclasses
import glob
import multiprocessing as mp
import os
import signal
import threading
import time
from typing import Optional

from tpu_resiliency.platform import framing
from tpu_resiliency.utils import location as location_mod
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import RankLoggerAdapter, get_logger
from tpu_resiliency.watchdog.config import FaultToleranceConfig
from tpu_resiliency.watchdog.data import (
    DumpStacksMsg,
    ErrorMsg,
    HeartbeatMsg,
    HeartbeatTimeouts,
    InitMsg,
    InitReplyMsg,
    OkMsg,
    RankInfo,
    SectionAction,
    SectionMsg,
    SectionTimeouts,
    StatusMsg,
    UpdateTimeoutsMsg,
    WaitDumpMsg,
)
from tpu_resiliency.watchdog.health import (
    HealthCheck,
    PeriodicHealthMonitor,
    checks_from_config,
)
from tpu_resiliency.watchdog.state_machine import RestarterStateMachine, RestarterState

log = get_logger(__name__)


@dataclasses.dataclass
class _RankSession:
    info: RankInfo
    connected_at: float
    last_hb: Optional[float] = None
    open_sections: dict = dataclasses.field(default_factory=dict)  # name -> open ts
    last_section_activity: Optional[float] = None
    terminated: bool = False
    #: heartbeat statistics for the disconnect-time ``heartbeat_stats`` record:
    #: observed gap distribution is what calibrated timeouts are judged against
    hb_count: int = 0
    max_hb_gap: float = 0.0
    #: last location beacon received (``utils/location.py`` payload) and the
    #: monotonic instant it arrived — the hang-forensics "last seen" record
    location: Optional[dict] = None
    location_rx: float = 0.0
    #: whether the rank installed a SIGUSR1 dump trigger (InitMsg
    #: capabilities): gates the signal nudge — SIGUSR1's default disposition
    #: kills, so a rank that never declared a handler is never signalled
    dump_signal_ok: bool = False
    #: violation pending the pre-kill stack-dump grace:
    #: (reason, cause, via) + the deadline the kill ladder fires at
    kill_pending: Optional[tuple] = None
    dump_deadline: float = 0.0


class RankMonitorServer:
    def __init__(
        self,
        cfg: FaultToleranceConfig,
        socket_path: str,
        health_checks: Optional[list[HealthCheck]] = None,
    ):
        self.cfg = cfg
        self.socket_path = socket_path
        self.session: Optional[_RankSession] = None
        self.hb_timeouts = HeartbeatTimeouts(
            initial=cfg.initial_rank_heartbeat_timeout,
            subsequent=cfg.rank_heartbeat_timeout,
            calculated=False,
        )
        self.section_timeouts = SectionTimeouts(
            section=dict(cfg.rank_section_timeouts),
            out_of_section=cfg.rank_out_of_section_timeout,
        )
        if health_checks is None:
            # Config-enabled built-ins (host memory floor, ICI link counters) —
            # explicit lists override, an explicit [] disables.
            health_checks = checks_from_config(cfg)
        self.health_checks = health_checks
        self._health_monitor: Optional[PeriodicHealthMonitor] = None
        self._health_failure: Optional[str] = None
        self.restarter = RestarterStateMachine("InJob", strict=False)
        self.log = RankLoggerAdapter(log, role="monitor")
        self._stop_event: Optional[asyncio.Event] = None
        #: stack-dump request generation: every request bumps it; the rank's
        #: WaitDumpMsg long-poll parks until the generation moves
        self._dump_gen = 0
        self._dump_reason = ""
        self._dump_event: Optional[asyncio.Event] = None

    # -- lifecycle ---------------------------------------------------------

    async def serve(self) -> None:
        self._stop_event = asyncio.Event()
        self._dump_event = asyncio.Event()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        os.makedirs(os.path.dirname(self.socket_path) or ".", exist_ok=True)
        server = await asyncio.start_unix_server(self._handle_conn, path=self.socket_path)
        self.restarter.initialize()
        if self.health_checks and self.cfg.enable_health_checks:
            self._health_monitor = PeriodicHealthMonitor(
                self.health_checks,
                self.cfg.health_check_interval,
                self._on_health_failure,
            )
            self._health_monitor.start()
        checker = asyncio.create_task(self._periodic_check())
        try:
            async with server:
                await self._stop_event.wait()
        finally:
            checker.cancel()
            if self._health_monitor:
                self._health_monitor.stop()
            if os.path.exists(self.socket_path):
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass

    def run(self) -> None:
        asyncio.run(self.serve())

    @classmethod
    def run_in_subprocess(
        cls,
        cfg: FaultToleranceConfig,
        socket_path: str,
        health_checks: Optional[list[HealthCheck]] = None,
        start_method: str = "fork",
    ) -> mp.Process:
        """Fork a monitor process (reference ``rank_monitor_server.py:488-512``).

        Waits until the server socket exists so the worker can connect immediately.
        """
        ctx = mp.get_context(start_method)
        # A stale socket file from a SIGKILLed predecessor would satisfy the readiness
        # poll below before the child has actually bound its listener.
        if os.path.exists(socket_path):
            try:
                os.unlink(socket_path)
            except OSError:
                pass
        proc = ctx.Process(
            target=_monitor_main, args=(cfg, socket_path, health_checks), daemon=True
        )
        proc.start()
        # Generous: spawn-started monitors (used by tests to avoid forking a
        # JAX-threaded parent) pay full interpreter startup, which on TPU images
        # can be several seconds even unloaded.
        deadline = time.monotonic() + 60.0
        while not os.path.exists(socket_path):
            if time.monotonic() > deadline or not proc.is_alive():
                raise RuntimeError(f"rank monitor failed to start on {socket_path}")
            time.sleep(0.01)
        return proc

    # -- connection handling ----------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        # Only the connection that carried this session's InitMsg narrates the
        # rank's disconnect: the socket now also serves dump long-polls,
        # status probes (/hangz census), and sibling dump broadcasts, whose
        # closes must not fabricate heartbeat_stats records.
        inited = False
        try:
            while True:
                try:
                    msg = await framing.read_obj_stream(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if isinstance(msg, InitMsg):
                    inited = True
                if isinstance(msg, WaitDumpMsg):
                    # Parks this connection's coroutine only; other
                    # connections (heartbeats, probes) keep being served.
                    reply = await self._wait_dump(msg)
                else:
                    reply = self._dispatch(msg)
                await framing.write_obj_stream(writer, reply)
        finally:
            if inited and self.session is not None:
                s = self.session
                self.log.info(
                    f"rank {s.info.global_rank} disconnected from monitor"
                )
                if s.hb_count:
                    # One summary record per monitored session, not one per
                    # heartbeat: the max gap is the margin-to-timeout an
                    # operator tunes ``rank_heartbeat_timeout`` against.
                    record_event(
                        "watchdog", "heartbeat_stats",
                        global_rank=s.info.global_rank,
                        heartbeats=s.hb_count,
                        max_gap_s=round(s.max_hb_gap, 6),
                        timeout_s=self.hb_timeouts.subsequent,
                    )
            writer.close()

    def _dispatch(self, msg):
        try:
            if isinstance(msg, InitMsg):
                return self._on_init(msg)
            if isinstance(msg, HeartbeatMsg):
                return self._on_heartbeat(msg)
            if isinstance(msg, SectionMsg):
                return self._on_section(msg)
            if isinstance(msg, UpdateTimeoutsMsg):
                return self._on_update_timeouts(msg)
            if isinstance(msg, DumpStacksMsg):
                self.request_stack_dump(getattr(msg, "reason", "operator"))
                return OkMsg(payload={"gen": self._dump_gen})
            if isinstance(msg, StatusMsg):
                return OkMsg(payload=self.status())
            return ErrorMsg(f"unknown message {type(msg).__name__}")
        except Exception as e:
            self.log.exception("monitor dispatch failed")
            return ErrorMsg(repr(e))

    def _on_init(self, msg: InitMsg):
        prev = self.session
        self.session = _RankSession(info=msg.rank_info, connected_at=time.monotonic())
        caps = getattr(msg, "capabilities", None)
        if isinstance(caps, dict):
            self.session.dump_signal_ok = bool(caps.get("dump_signal"))
        if prev is not None and prev.info.pid == msg.rank_info.pid:
            # A reconnect re-init (client self-heal) keeps the forensics
            # story: the last beacon must survive the socket blip.
            self.session.location = prev.location
            self.session.location_rx = prev.location_rx
        if msg.client_state:
            hb = msg.client_state.get("hb_timeouts")
            if hb is not None:
                self.hb_timeouts = hb
            st = msg.client_state.get("section_timeouts")
            if st is not None:
                self.section_timeouts = st
        self.log.rank = msg.rank_info.global_rank
        self.log.info(f"monitoring rank {msg.rank_info.global_rank} pid {msg.rank_info.pid}")
        return InitReplyMsg(
            config=self.cfg,
            hb_timeouts=self.hb_timeouts,
            section_timeouts=self.section_timeouts,
        )

    @staticmethod
    def _absorb_location(s: _RankSession, msg, now: float) -> None:
        """Version-skew-tolerant beacon intake: a location-less message from
        an old-build worker (or a non-dict payload from a confused one) is
        simply no update — the watchdog keeps its last good beacon."""
        loc = getattr(msg, "location", None)
        if isinstance(loc, dict):
            s.location = loc
            s.location_rx = now

    def _on_heartbeat(self, msg: HeartbeatMsg):
        if self.session is None:
            return ErrorMsg("heartbeat before init")
        s = self.session
        now = time.monotonic()
        if s.last_hb is not None:
            s.max_hb_gap = max(s.max_hb_gap, now - s.last_hb)
        s.hb_count += 1
        s.last_hb = now
        self._absorb_location(s, msg, now)
        return OkMsg()

    def _on_section(self, msg: SectionMsg):
        if self.session is None:
            return ErrorMsg("section message before init")
        now = time.monotonic()
        s = self.session
        self._absorb_location(s, msg, now)
        if msg.action is SectionAction.OPEN:
            if msg.name in s.open_sections:
                return ErrorMsg(f"section {msg.name!r} already open")
            s.open_sections[msg.name] = now
        elif msg.action is SectionAction.CLOSE:
            if msg.name not in s.open_sections:
                return ErrorMsg(f"section {msg.name!r} not open")
            del s.open_sections[msg.name]
        elif msg.action is SectionAction.CLOSE_ALL:
            s.open_sections.clear()
        s.last_section_activity = now
        return OkMsg()

    def _on_update_timeouts(self, msg: UpdateTimeoutsMsg):
        if msg.hb_timeouts is not None:
            self.hb_timeouts = msg.hb_timeouts
        if msg.section_timeouts is not None:
            self.section_timeouts = msg.section_timeouts
        self.log.info(
            f"timeouts updated: hb={self.hb_timeouts} sections={self.section_timeouts}"
        )
        return OkMsg()

    # -- hang forensics: stack dumps + status -------------------------------

    def request_stack_dump(self, reason: str) -> None:
        """Ask the monitored rank for an all-thread stack dump (loop thread).

        Two delivery paths, because each covers the other's blind spot: the
        parked ``WaitDumpMsg`` long-poll (works when the main thread is stuck
        in a GIL-releasing native call, where a Python signal handler can
        never run) and a SIGUSR1 nudge (works for a rank that skipped the
        listener but installed the signal trigger)."""
        self._dump_gen += 1
        self._dump_reason = reason
        if self._dump_event is not None:
            # set() resolves every currently-parked waiter; the immediate
            # clear() re-arms for the next request (gen-compare catches any
            # request landing between a waiter's polls).
            self._dump_event.set()
            self._dump_event.clear()
        s = self.session
        if s is not None and s.dump_signal_ok and not s.terminated:
            try:
                from tpu_resiliency.utils import stackdump

                os.kill(s.info.pid, stackdump.DUMP_SIGNAL)
            except (ProcessLookupError, PermissionError):
                pass

    async def _wait_dump(self, msg: WaitDumpMsg) -> OkMsg:
        """Park the rank's dump-listener long-poll until the dump generation
        moves past ``seen_gen`` or the poll times out (reply carries the
        current generation either way)."""
        timeout = min(max(float(getattr(msg, "timeout", 0.0) or 0.0), 0.0), 300.0)
        seen = getattr(msg, "seen_gen", 0)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while self._dump_gen == seen:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                await asyncio.wait_for(self._dump_event.wait(), remaining)
            except asyncio.TimeoutError:
                break
        return OkMsg(
            payload={"gen": self._dump_gen, "reason": self._dump_reason or None}
        )

    def _broadcast_dump_request(self, reason: str) -> None:
        """Fan a ``DumpStacksMsg`` out to every sibling monitor socket in this
        run dir — in a collective hang the *waiting* ranks' stacks are as
        diagnostic as the victim's. Best-effort, off the event loop (a stuck
        sibling must not stall our own rank's dump delivery)."""
        pattern = os.path.join(
            os.path.dirname(self.socket_path) or ".", "monitor_*.sock"
        )

        def fan_out() -> None:
            from tpu_resiliency.platform import ipc

            for path in sorted(glob.glob(pattern)):
                if os.path.abspath(path) == os.path.abspath(self.socket_path):
                    continue
                try:
                    sock = ipc.connect(path, timeout=2.0)
                    try:
                        sock.settimeout(2.0)
                        ipc.write_object(sock, DumpStacksMsg(reason=reason))
                        ipc.read_object(sock)
                    finally:
                        sock.close()
                except (OSError, EOFError, ConnectionError):
                    continue

        threading.Thread(
            target=fan_out, name="monitor-dump-fanout", daemon=True
        ).start()

    def status(self) -> dict:
        """The per-rank census document for the launcher's ``/hangz``."""
        s = self.session
        if s is None:
            return {"connected": False}
        now = time.monotonic()
        return {
            "connected": True,
            "rank": s.info.global_rank,
            "pid": s.info.pid,
            "host": s.info.host,
            "terminated": s.terminated,
            "last_hb_age_s": (
                round(now - s.last_hb, 3) if s.last_hb is not None else None
            ),
            "connected_age_s": round(now - s.connected_at, 3),
            "open_sections": {
                name: round(now - opened, 3)
                for name, opened in s.open_sections.items()
            },
            "location": s.location,
            "location_age_s": self._location_age(s, now),
            "hb_timeout_s": self.hb_timeouts.subsequent,
            "kill_pending": s.kill_pending[0] if s.kill_pending else None,
        }

    @staticmethod
    def _location_age(s: _RankSession, now: float) -> Optional[float]:
        """Seconds the rank has been in its beacon's location: the beacon's
        own age at send time plus how long ago we received it."""
        if s.location is None:
            return None
        base = 0.0
        for key in ("barrier_age_s", "section_age_s", "step_age_s"):
            v = s.location.get(key)
            if isinstance(v, (int, float)):
                base = float(v)
                break
        return round(base + max(0.0, now - s.location_rx), 3)

    def _location_line(self, s: _RankSession, now: float) -> str:
        """``; last seen in section=step barrier=... for 612s`` or ''."""
        frag = location_mod.describe(s.location, age_s=self._location_age(s, now))
        return f"; last seen in {frag}" if frag else ""

    # -- periodic checks ---------------------------------------------------

    def _hb_timeout_elapsed(self, now: float) -> Optional[str]:
        s = self.session
        if s.last_hb is None:
            t = self.hb_timeouts.initial
            if t is not None and now - s.connected_at > t:
                return f"no initial heartbeat within {t:.1f}s"
        else:
            t = self.hb_timeouts.subsequent
            if t is not None and now - s.last_hb > t:
                return f"heartbeat gap exceeded {t:.1f}s"
        return None

    def _section_timeout_elapsed(self, now: float) -> Optional[str]:
        s = self.session
        for name, opened in s.open_sections.items():
            t = self.section_timeouts.section.get(name)
            if t is not None and now - opened > t:
                return f"section {name!r} open for more than {t:.1f}s"
        t = self.section_timeouts.out_of_section
        if t is not None and not s.open_sections and s.last_section_activity is not None:
            if now - s.last_section_activity > t:
                return f"out-of-section for more than {t:.1f}s"
        return None

    async def _periodic_check(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.workload_check_interval)
            try:
                s = self.session
                if s is None or s.terminated:
                    continue
                now = time.monotonic()
                if s.kill_pending is not None:
                    # Dump grace in progress: the ladder fires at the
                    # deadline whether or not the dumps landed (a dead rank
                    # must not stay undead because forensics is slow).
                    if now >= s.dump_deadline:
                        self._terminate_rank(*s.kill_pending)
                    continue
                cause = "hang"
                via = "heartbeat"
                reason = self._hb_timeout_elapsed(now)
                if reason is None:
                    reason = self._section_timeout_elapsed(now)
                    via = "section"
                if reason is None and self._health_failure is not None:
                    reason = f"health check failed: {self._health_failure}"
                    cause, via = "health", "health"
                if reason is not None:
                    grace = float(getattr(self.cfg, "stack_dump_grace", 0.0) or 0.0)
                    if cause == "hang" and grace > 0 and getattr(
                        self.cfg, "stack_dump_on_hang", True
                    ):
                        # Capture-before-kill: request stacks from this rank
                        # AND every sibling rank's monitor (the blocked
                        # waiters are half the story), then give the dumps
                        # one grace window before the ladder.
                        s.kill_pending = (reason, cause, via)
                        s.dump_deadline = now + grace
                        self.log.error(
                            f"hang detected for rank {s.info.global_rank} "
                            f"({reason}); capturing stacks for {grace:.1f}s "
                            f"before the kill ladder"
                        )
                        self.request_stack_dump(f"hang: {reason}")
                        self._broadcast_dump_request(
                            f"peer-hang: rank {s.info.global_rank}: {reason}"
                        )
                    else:
                        self._terminate_rank(reason, cause, via)
            except asyncio.CancelledError:
                raise
            except Exception:
                # The checker must survive anything (e.g. os.kill PermissionError on a
                # reused PID) — a dead checker silently disables hang detection.
                self.log.exception("periodic check iteration failed; continuing")

    def _on_health_failure(self, check: HealthCheck) -> None:
        self._health_failure = check.describe()

    def _terminate_rank(self, reason: str, cause: str = "hang", via: str = "?") -> None:
        s = self.session
        s.terminated = True
        now = time.monotonic()
        # Fold the last-known-location beacon into the cause the operator
        # reads: "heartbeat gap exceeded 45s; last seen in section=step
        # barrier=rdzv/round-3 for 612s" answers the postmortem's first
        # question at detection time.
        reason = reason + self._location_line(s, now)
        blocked_s = now - (s.last_hb if s.last_hb is not None else s.connected_at)
        # Distinct kinds: hang (heartbeat/section timeout) vs health (device/node
        # check failure) — consumers triage the two very differently. ``via``
        # splits the hang kind further (heartbeat gap vs section timeout).
        record_event(
            "watchdog",
            "hang_detected" if cause == "hang" else "health_terminated",
            global_rank=s.info.global_rank,
            pid=s.info.pid, reason=reason, via=via,
            blocked_s=round(max(0.0, blocked_s), 3),
            location=s.location,
        )
        # The monitor holds the heartbeat/section story the dying rank cannot
        # tell: snapshot this process's ring before the kill ladder runs, so
        # the incident artifact carries the detection side even if the
        # monitor itself is torn down right after.
        from tpu_resiliency.utils import flight_recorder

        flight_recorder.flush(
            "kill_ladder", detail=f"rank {s.info.global_rank}: {reason}"
        )
        self.restarter.handling_start(f"reason={reason!r}")
        self.log.error(f"terminating rank {s.info.global_rank} (pid {s.info.pid}): {reason}")
        self.restarter.handling_processing()
        try:
            # Each rung of the kill ladder is its own record: the step that
            # actually ended the rank (this signal, or the launcher's later
            # SIGKILL escalation) is reconstructable from the stream.
            os.kill(s.info.pid, signal.SIGCONT)  # wake a stopped process first
            self._record_kill("SIGCONT", s)
            term = self.cfg.rank_termination_signal
            os.kill(s.info.pid, term)
            try:
                term_name = signal.Signals(term).name
            except ValueError:
                term_name = str(term)
            self._record_kill(term_name, s)
        except ProcessLookupError:
            self.log.info("rank process already gone")
        self.restarter.handling_completed()

    @staticmethod
    def _record_kill(step: str, s: _RankSession) -> None:
        record_event(
            "watchdog", "kill_ladder", step=step,
            global_rank=s.info.global_rank, pid=s.info.pid,
        )

    def request_stop(self) -> None:
        if self._stop_event is not None:
            self._stop_event.set()


def _monitor_main(cfg, socket_path, health_checks) -> None:
    # A forked monitor must never touch the parent's TPU runtime.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    RankMonitorServer(cfg, socket_path, health_checks).run()
