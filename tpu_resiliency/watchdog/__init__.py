from tpu_resiliency.watchdog.config import FaultToleranceConfig
from tpu_resiliency.watchdog.data import (
    HeartbeatTimeouts,
    RankInfo,
    SectionTimeouts,
    WorkloadAction,
    WorkloadControlRequest,
)
from tpu_resiliency.watchdog.health import (
    CallbackHealthCheck,
    DeviceLivenessCheck,
    HealthCheck,
    HostMemoryCheck,
    IciLinkCheck,
    PeriodicHealthMonitor,
    SysfsCounterCheck,
    TpuRuntimeCheck,
)
from tpu_resiliency.watchdog.monitor_client import RankMonitorClient
from tpu_resiliency.watchdog.monitor_server import RankMonitorServer
from tpu_resiliency.watchdog.state_machine import (
    LOG_MARKER,
    RestarterState,
    RestarterStateMachine,
)
from tpu_resiliency.watchdog.timeouts import TimeoutsCalc

__all__ = [
    "FaultToleranceConfig",
    "HeartbeatTimeouts",
    "RankInfo",
    "SectionTimeouts",
    "WorkloadAction",
    "WorkloadControlRequest",
    "CallbackHealthCheck",
    "HostMemoryCheck",
    "IciLinkCheck",
    "TpuRuntimeCheck",
    "DeviceLivenessCheck",
    "HealthCheck",
    "PeriodicHealthMonitor",
    "SysfsCounterCheck",
    "RankMonitorClient",
    "RankMonitorServer",
    "LOG_MARKER",
    "RestarterState",
    "RestarterStateMachine",
    "TimeoutsCalc",
]
