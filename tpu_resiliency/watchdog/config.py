"""Fault-tolerance configuration: dataclass ← YAML ← CLI overrides.

Analogue of the reference's ``FaultToleranceConfig`` (``fault_tolerance/config.py:28-283``):
same knob set and defaults (heartbeat timeouts 3600/2700 s, check every 5 s,
safety_factor 5.0, SIGKILL termination — ``config.py:59-71``), same YAML behavior
(``fault_tolerance`` section found at any nesting depth) and ``--ft-param-*`` CLI
override namespace.
"""

from __future__ import annotations

import dataclasses
import signal
from typing import Any, Mapping, Optional

import yaml


@dataclasses.dataclass
class FaultToleranceConfig:
    # heartbeat-based detection
    initial_rank_heartbeat_timeout: Optional[float] = 60.0 * 60.0
    rank_heartbeat_timeout: Optional[float] = 45.0 * 60.0
    workload_check_interval: float = 5.0
    # section-based detection
    rank_section_timeouts: dict[str, Optional[float]] = dataclasses.field(default_factory=dict)
    rank_out_of_section_timeout: Optional[float] = None
    # timeout auto-calibration
    safety_factor: float = 5.0
    # enforcement
    rank_termination_signal: int = signal.SIGKILL
    log_level: str = "INFO"
    # hang forensics: on a hang verdict the monitor first requests an
    # all-thread stack dump from its rank AND every sibling rank's monitor
    # (the blocked waiters are half the story), waits out the grace, then
    # runs the kill ladder. 0 (or stack_dump_on_hang=False) kills immediately.
    stack_dump_on_hang: bool = True
    stack_dump_grace: float = 1.5
    # restart policy knobs consumed by the launcher
    restart_check_interval: float = 1.0
    # pluggable host/device health checks run by the monitor
    enable_health_checks: bool = False
    health_check_interval: float = 5.0
    # built-in health sources (watchdog/health.py), config-enabled like the
    # reference's GPU/NIC checks; None disables each. TpuRuntimeCheck is NOT
    # listed: it must run in the process that owns the TPU (wire it into the
    # in-process restart health chain instead).
    host_memory_min_fraction: Optional[float] = None  # e.g. 0.05
    ici_link_device_glob: Optional[str] = None  # e.g. /sys/class/accel/accel*
    ici_link_down_path_template: Optional[str] = None  # e.g. .../{device}/link_downed

    SECTION_NAME = "fault_tolerance"
    PARAM_PREFIX = "ft_param_"

    def __post_init__(self):
        if isinstance(self.rank_termination_signal, str):
            name = self.rank_termination_signal.upper()
            if not name.startswith("SIG"):
                name = "SIG" + name
            self.rank_termination_signal = getattr(signal, name)
        if isinstance(self.rank_termination_signal, signal.Signals):
            self.rank_termination_signal = int(self.rank_termination_signal)

    # -- construction ------------------------------------------------------

    @classmethod
    def _field_names(cls) -> set[str]:
        return {f.name for f in dataclasses.fields(cls)}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any], strict: bool = True) -> "FaultToleranceConfig":
        known = cls._field_names()
        unknown = set(d) - known
        if unknown and strict:
            raise ValueError(f"unknown fault_tolerance config keys: {sorted(unknown)}")
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_yaml_file(cls, path: str, strict: bool = True) -> "FaultToleranceConfig":
        """Load, finding the ``fault_tolerance`` section at any nesting depth
        (reference ``config.py:224-239``)."""
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        section = cls._find_section(doc)
        if section is None:
            raise ValueError(f"no '{cls.SECTION_NAME}' section found in {path}")
        return cls.from_dict(section, strict=strict)

    @classmethod
    def _find_section(cls, node: Any) -> Optional[Mapping[str, Any]]:
        if isinstance(node, Mapping):
            if cls.SECTION_NAME in node and isinstance(node[cls.SECTION_NAME], Mapping):
                return node[cls.SECTION_NAME]
            for v in node.values():
                found = cls._find_section(v)
                if found is not None:
                    return found
        return None

    @classmethod
    def from_args(cls, args, base: Optional["FaultToleranceConfig"] = None):
        """Apply ``--ft-param-*`` CLI overrides (argparse namespace attributes named
        ``ft_param_<field>``; reference ``config.py:144``)."""
        cfg = base or cls()
        known = cls._field_names()
        for key, value in vars(args).items():
            if not key.startswith(cls.PARAM_PREFIX) or value is None:
                continue
            field = key[len(cls.PARAM_PREFIX) :]
            if field not in known:
                raise ValueError(f"unknown --ft-param '{field}'")
            setattr(cfg, field, _coerce(cfg, field, value))
        cfg.__post_init__()
        return cfg

    def to_yaml_file(self, path: str) -> None:
        with open(path, "w") as f:
            yaml.safe_dump({self.SECTION_NAME: dataclasses.asdict(self)}, f)

    def merged(self, **overrides) -> "FaultToleranceConfig":
        return dataclasses.replace(self, **overrides)


def _coerce(cfg: FaultToleranceConfig, field: str, value: Any) -> Any:
    current = getattr(cfg, field)
    if isinstance(value, str):
        if field == "rank_section_timeouts":
            return yaml.safe_load(value)
        if isinstance(current, bool):
            return value.lower() in ("1", "true", "yes")
        if isinstance(current, (int, float)) or current is None:
            try:
                return float(value) if "." in value or "e" in value.lower() else int(value)
            except ValueError:
                return value
    return value
