"""Wire messages and shared types for the rank↔monitor↔launcher control plane.

Analogue of the reference's ``fault_tolerance/data.py`` (RankInfo ``:34``, timeout
bundles ``:71-138``, Init/Heartbeat/Section/UpdateConfig/Ok/Error messages ``:141-233``,
WorkloadAction + WorkloadControlRequest ``:236-260``). Messages travel as pickled frames
over filesystem-protected Unix sockets (``platform/ipc.py``).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class RankInfo:
    global_rank: int
    local_rank: int
    host: str
    pid: int

    @staticmethod
    def of_current_process(global_rank: int, local_rank: int) -> "RankInfo":
        import os
        import socket

        return RankInfo(
            global_rank=global_rank,
            local_rank=local_rank,
            host=socket.gethostname(),
            pid=os.getpid(),
        )


@dataclasses.dataclass
class HeartbeatTimeouts:
    """Effective heartbeat timeouts; ``calculated`` marks auto-calibrated values
    (reference ``data.py:71``)."""

    initial: Optional[float] = None
    subsequent: Optional[float] = None
    calculated: bool = False

    @property
    def are_valid(self) -> bool:
        return self.initial is not None and self.subsequent is not None


@dataclasses.dataclass
class SectionTimeouts:
    """Per-section + out-of-section timeouts (reference ``data.py:104``)."""

    section: dict[str, Optional[float]] = dataclasses.field(default_factory=dict)
    out_of_section: Optional[float] = None
    calculated_sections: frozenset = frozenset()
    calculated_out_of_section: bool = False


class SectionAction(enum.Enum):
    OPEN = "open"
    CLOSE = "close"
    CLOSE_ALL = "close_all"


class WorkloadAction(enum.Enum):
    """Actions a rank can request from the launcher (reference ``data.py:236``)."""

    Continue = "continue"
    ExcludeThisNode = "exclude_this_node"
    ShutdownWorkload = "shutdown_workload"


@dataclasses.dataclass(frozen=True)
class WorkloadControlRequest:
    action: WorkloadAction
    sender: RankInfo
    reason: str = ""


# -- rank ↔ monitor messages ----------------------------------------------


@dataclasses.dataclass
class InitMsg:
    rank_info: RankInfo
    # client pushes any previously persisted state (calculated timeouts)
    client_state: Optional[dict] = None
    #: what forensics paths this rank supports (``{"dump_signal": bool,
    #: "dump_poll": bool}``). Read with ``getattr`` server-side — absent on
    #: old-build clients (version skew). ``dump_signal`` gates the monitor's
    #: SIGUSR1 nudge: the default SIGUSR1 disposition kills, so it is only
    #: sent to ranks that declared a handler.
    capabilities: Optional[dict] = None


@dataclasses.dataclass
class InitReplyMsg:
    config: Any  # effective FaultToleranceConfig
    hb_timeouts: HeartbeatTimeouts
    section_timeouts: SectionTimeouts


@dataclasses.dataclass
class HeartbeatMsg:
    rank: int
    timestamp: float = dataclasses.field(default_factory=time.monotonic)
    state: Optional[dict] = None  # optional piggy-backed client state
    #: last-known-location beacon (``utils/location.py`` snapshot). Optional
    #: and read with ``getattr`` server-side: a mixed old/new fleet during an
    #: in-job restart must interoperate in both directions (version skew).
    location: Optional[dict] = None


@dataclasses.dataclass
class SectionMsg:
    rank: int
    action: SectionAction
    name: Optional[str] = None
    timestamp: float = dataclasses.field(default_factory=time.monotonic)
    #: same skew contract as :class:`HeartbeatMsg.location`
    location: Optional[dict] = None


@dataclasses.dataclass
class DumpStacksMsg:
    """Ask a monitor to trigger an all-thread stack dump in its rank.

    Anyone holding the monitor socket may send it: the watchdog's sibling
    broadcast before the kill ladder, an operator tool, or a test. The
    monitor wakes the rank's parked :class:`WaitDumpMsg` long-poll (and
    nudges the rank with SIGUSR1 as a belt-and-braces second path)."""

    reason: str = "operator"


@dataclasses.dataclass
class WaitDumpMsg:
    """The rank's dump-listener long-poll: parks server-side until a dump is
    requested (``seen_gen`` differs from the server's dump generation) or
    ``timeout`` elapses. Reply is ``OkMsg(payload={"gen", "reason"})``; the
    client dumps whenever the generation moved."""

    seen_gen: int = 0
    timeout: float = 30.0


@dataclasses.dataclass
class StatusMsg:
    """Monitor introspection for the launcher's ``/hangz`` census: replies
    ``OkMsg(payload={rank, pid, last_hb_age_s, location, location_age_s,
    open_sections, terminated, ...})``."""


@dataclasses.dataclass
class UpdateTimeoutsMsg:
    hb_timeouts: Optional[HeartbeatTimeouts] = None
    section_timeouts: Optional[SectionTimeouts] = None


@dataclasses.dataclass
class OkMsg:
    payload: Optional[dict] = None


@dataclasses.dataclass
class ErrorMsg:
    error: str
