"""Wire messages and shared types for the rank↔monitor↔launcher control plane.

Analogue of the reference's ``fault_tolerance/data.py`` (RankInfo ``:34``, timeout
bundles ``:71-138``, Init/Heartbeat/Section/UpdateConfig/Ok/Error messages ``:141-233``,
WorkloadAction + WorkloadControlRequest ``:236-260``). Messages travel as pickled frames
over filesystem-protected Unix sockets (``platform/ipc.py``).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class RankInfo:
    global_rank: int
    local_rank: int
    host: str
    pid: int

    @staticmethod
    def of_current_process(global_rank: int, local_rank: int) -> "RankInfo":
        import os
        import socket

        return RankInfo(
            global_rank=global_rank,
            local_rank=local_rank,
            host=socket.gethostname(),
            pid=os.getpid(),
        )


@dataclasses.dataclass
class HeartbeatTimeouts:
    """Effective heartbeat timeouts; ``calculated`` marks auto-calibrated values
    (reference ``data.py:71``)."""

    initial: Optional[float] = None
    subsequent: Optional[float] = None
    calculated: bool = False

    @property
    def are_valid(self) -> bool:
        return self.initial is not None and self.subsequent is not None


@dataclasses.dataclass
class SectionTimeouts:
    """Per-section + out-of-section timeouts (reference ``data.py:104``)."""

    section: dict[str, Optional[float]] = dataclasses.field(default_factory=dict)
    out_of_section: Optional[float] = None
    calculated_sections: frozenset = frozenset()
    calculated_out_of_section: bool = False


class SectionAction(enum.Enum):
    OPEN = "open"
    CLOSE = "close"
    CLOSE_ALL = "close_all"


class WorkloadAction(enum.Enum):
    """Actions a rank can request from the launcher (reference ``data.py:236``)."""

    Continue = "continue"
    ExcludeThisNode = "exclude_this_node"
    ShutdownWorkload = "shutdown_workload"


@dataclasses.dataclass(frozen=True)
class WorkloadControlRequest:
    action: WorkloadAction
    sender: RankInfo
    reason: str = ""


# -- rank ↔ monitor messages ----------------------------------------------


@dataclasses.dataclass
class InitMsg:
    rank_info: RankInfo
    # client pushes any previously persisted state (calculated timeouts)
    client_state: Optional[dict] = None


@dataclasses.dataclass
class InitReplyMsg:
    config: Any  # effective FaultToleranceConfig
    hb_timeouts: HeartbeatTimeouts
    section_timeouts: SectionTimeouts


@dataclasses.dataclass
class HeartbeatMsg:
    rank: int
    timestamp: float = dataclasses.field(default_factory=time.monotonic)
    state: Optional[dict] = None  # optional piggy-backed client state


@dataclasses.dataclass
class SectionMsg:
    rank: int
    action: SectionAction
    name: Optional[str] = None
    timestamp: float = dataclasses.field(default_factory=time.monotonic)


@dataclasses.dataclass
class UpdateTimeoutsMsg:
    hb_timeouts: Optional[HeartbeatTimeouts] = None
    section_timeouts: Optional[SectionTimeouts] = None


@dataclasses.dataclass
class OkMsg:
    payload: Optional[dict] = None


@dataclasses.dataclass
class ErrorMsg:
    error: str
