"""Auto-calibration of heartbeat and section timeouts from observed behavior.

Analogue of the reference's ``TimeoutsCalc`` (``fault_tolerance/timeouts_calc.py``):
track the max observed initial/subsequent heartbeat gap and per-section durations,
cross-rank all-reduce MAX, multiply by a safety factor, and EMA-merge with previously
calculated values (``timeouts_calc.py:74-91,146-271``). The cross-rank merge goes
through the coordination store (calibration is rare) instead of a torch collective.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from tpu_resiliency.exceptions import FaultToleranceError
from tpu_resiliency.watchdog.data import HeartbeatTimeouts, SectionTimeouts

MERGE_WEIGHT = 0.5  # EMA weight for new measurements vs previous calculated values


@dataclasses.dataclass
class TimeoutsCalc:
    safety_factor: float = 5.0
    start_time: Optional[float] = None
    last_hb_time: Optional[float] = None
    initial_max_gap: float = 0.0
    subsequent_max_gap: float = 0.0
    hb_count: int = 0
    # sections
    section_max_elapsed: dict[str, float] = dataclasses.field(default_factory=dict)
    section_open_since: dict[str, float] = dataclasses.field(default_factory=dict)
    out_of_section_max: float = 0.0
    last_section_close: Optional[float] = None
    # Local collective-round counter: every rank calls synchronize_all the same number
    # of times, so a local counter keys the round namespace without a store read (a
    # store-read epoch races: a fast rank can re-enter before rank 0 bumps it).
    sync_epoch: int = 0

    def _now(self) -> float:
        return time.monotonic()

    def reset(self) -> None:
        self.start_time = self._now()
        self.last_hb_time = None
        self.hb_count = 0

    # -- heartbeat tracking ------------------------------------------------

    def update_on_heartbeat(self, hb_time: Optional[float] = None) -> None:
        now = self._now() if hb_time is None else hb_time
        if self.start_time is None:
            self.start_time = now
        if self.last_hb_time is None:
            self.initial_max_gap = max(self.initial_max_gap, now - self.start_time)
        else:
            self.subsequent_max_gap = max(self.subsequent_max_gap, now - self.last_hb_time)
        self.last_hb_time = now
        self.hb_count += 1

    @property
    def can_get_hb_timeouts(self) -> bool:
        return self.hb_count >= 2

    # -- section tracking --------------------------------------------------

    def update_on_section_open(self, name: str, ts: Optional[float] = None) -> None:
        now = self._now() if ts is None else ts
        if name in self.section_open_since:
            raise FaultToleranceError(f"section {name!r} already open")
        if self.last_section_close is not None and not self.section_open_since:
            self.out_of_section_max = max(self.out_of_section_max, now - self.last_section_close)
        self.section_open_since[name] = now

    def update_on_section_close(self, name: str, ts: Optional[float] = None) -> None:
        now = self._now() if ts is None else ts
        opened = self.section_open_since.pop(name, None)
        if opened is None:
            raise FaultToleranceError(f"section {name!r} is not open")
        self.section_max_elapsed[name] = max(
            self.section_max_elapsed.get(name, 0.0), now - opened
        )
        if not self.section_open_since:
            self.last_section_close = now

    # -- cross-rank merge + final timeouts ---------------------------------

    def synchronize_all(self, store, rank: int, world_size: int, key: str = "ft/timeouts") -> None:
        """All-reduce MAX of every tracked statistic across ranks via the store
        (reference ``timeouts_calc.py:74-91``)."""
        if world_size <= 1 or store is None:
            return
        ns = f"{key}/{self.sync_epoch}"
        self.sync_epoch += 1
        store.set(f"{ns}/rank/{rank}", self._stats())
        # Fixed barrier names: the server's generation-counted reentrant barriers make
        # them reusable across epochs without leaking per-epoch barrier state.
        store.barrier(f"{key}/sync", rank, world_size, 300.0)
        merged = [store.get(f"{ns}/rank/{r}", timeout=60.0) for r in range(world_size)]
        self._merge_max(merged)
        store.barrier(f"{key}/done", rank, world_size, 300.0)
        if rank == 0:
            for r in range(world_size):
                store.delete(f"{ns}/rank/{r}")

    def _stats(self) -> dict:
        return {
            "initial_max_gap": self.initial_max_gap,
            "subsequent_max_gap": self.subsequent_max_gap,
            "section_max_elapsed": dict(self.section_max_elapsed),
            "out_of_section_max": self.out_of_section_max,
        }

    def _merge_max(self, stats_list: list[dict]) -> None:
        for st in stats_list:
            self.initial_max_gap = max(self.initial_max_gap, st["initial_max_gap"])
            self.subsequent_max_gap = max(self.subsequent_max_gap, st["subsequent_max_gap"])
            for name, v in st["section_max_elapsed"].items():
                self.section_max_elapsed[name] = max(self.section_max_elapsed.get(name, 0.0), v)
            self.out_of_section_max = max(self.out_of_section_max, st["out_of_section_max"])

    def get_hb_timeouts(
        self, previous: Optional[HeartbeatTimeouts] = None
    ) -> HeartbeatTimeouts:
        """safety_factor × max gap, EMA-merged with previous calculated values
        (reference ``timeouts_calc.py:146-271``)."""
        if not self.can_get_hb_timeouts:
            raise FaultToleranceError("need ≥2 heartbeats to calculate timeouts")
        initial = self.safety_factor * max(self.initial_max_gap, self.subsequent_max_gap)
        subsequent = self.safety_factor * self.subsequent_max_gap
        if previous is not None and previous.calculated and previous.are_valid:
            initial = MERGE_WEIGHT * initial + (1 - MERGE_WEIGHT) * previous.initial
            subsequent = MERGE_WEIGHT * subsequent + (1 - MERGE_WEIGHT) * previous.subsequent
        return HeartbeatTimeouts(initial=initial, subsequent=subsequent, calculated=True)

    def get_section_timeouts(
        self, previous: Optional[SectionTimeouts] = None
    ) -> SectionTimeouts:
        section = {
            name: self.safety_factor * v for name, v in self.section_max_elapsed.items()
        }
        oos = self.safety_factor * self.out_of_section_max if self.out_of_section_max else None
        if previous is not None:
            for name in previous.calculated_sections:
                if name in section and previous.section.get(name) is not None:
                    section[name] = (
                        MERGE_WEIGHT * section[name]
                        + (1 - MERGE_WEIGHT) * previous.section[name]
                    )
            if (
                oos is not None
                and previous.calculated_out_of_section
                and previous.out_of_section is not None
            ):
                oos = MERGE_WEIGHT * oos + (1 - MERGE_WEIGHT) * previous.out_of_section
        return SectionTimeouts(
            section=section,
            out_of_section=oos,
            calculated_sections=frozenset(section),
            calculated_out_of_section=oos is not None,
        )
