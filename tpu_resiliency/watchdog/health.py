"""Pluggable host/device health checks run by the rank monitor.

Analogue of the reference's ``shared_utils/health_check.py`` (``GPUHealthCheck:148``,
``NicHealthCheck:306``). On TPU there is no NVML; the equivalents are:

- :class:`DeviceLivenessCheck` — submits tiny device work under a watchdog thread
  (must run in a process that owns the TPU; workers use it inside restart health
  checks, see ``inprocess/health_check``),
- :class:`SysfsCounterCheck` — watches a sysfs error-counter delta, the generalization
  of the reference's IB ``link_downed`` monitoring (``health_check.py:527-559``); the
  path template is injectable so tests fake the counter exactly as the reference does
  (``health_check.py:325``),
- :class:`CallbackHealthCheck` — wraps any ``() -> bool``.

All checks expose sync ``__call__() -> bool`` and can be polled periodically by the
monitor with an ``on_failure`` callback (reference ``async_check`` loop,
``health_check.py:148-303``).
"""

from __future__ import annotations

import abc
import glob
import threading
import time
from typing import Callable, Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class HealthCheck(abc.ABC):
    @abc.abstractmethod
    def __call__(self) -> bool:
        """True = healthy."""

    def describe(self) -> str:
        return type(self).__name__


class CallbackHealthCheck(HealthCheck):
    def __init__(self, fn: Callable[[], bool], name: str = "callback"):
        self._fn = fn
        self._name = name

    def __call__(self) -> bool:
        try:
            return bool(self._fn())
        except Exception:
            log.exception("health check %s raised", self._name)
            return False

    def describe(self) -> str:
        return self._name


class DeviceLivenessCheck(HealthCheck):
    """Tiny compiled add + block_until_ready under a timeout thread
    (the reference ``CudaHealthCheck`` double-sync analogue,
    ``inprocess/health_check.py:70-110``)."""

    def __init__(self, timeout: float = 60.0):
        self.timeout = timeout

    def __call__(self) -> bool:
        from tpu_resiliency.platform.device import device_liveness_probe

        return device_liveness_probe(timeout=self.timeout)


class SysfsCounterCheck(HealthCheck):
    """Healthy while monitored counters do not increase between polls.

    ``path_glob``: glob of counter files (each containing one integer). The first poll
    snapshots baselines; any later increase marks unhealthy (sticky until ``reset``) —
    the failed source names are recorded in ``failed`` so policy layers can exclude
    the right failure domain. Subclasses override :meth:`_sources` to change how
    counters are discovered/named (see :class:`IciLinkCheck`).
    """

    def __init__(self, path_glob: str = ""):
        self.path_glob = path_glob
        self._baseline: Optional[dict[str, int]] = None
        self.failed: list[str] = []

    def _sources(self) -> dict[str, str]:
        """Counter name -> file path."""
        return {p: p for p in sorted(glob.glob(self.path_glob))}

    def _read(self) -> dict[str, int]:
        values = {}
        for name, path in self._sources().items():
            try:
                with open(path) as f:
                    values[name] = int(f.read().strip() or 0)
            except (OSError, ValueError):
                continue
        return values

    def reset(self) -> None:
        self._baseline = None
        self.failed = []

    def __call__(self) -> bool:
        current = self._read()
        if self._baseline is None:
            self._baseline = current
            return True
        for name, value in current.items():
            if value > self._baseline.get(name, value):
                log.error("counter increased: %s %d -> %d",
                          name, self._baseline.get(name, 0), value)
                if name not in self.failed:
                    self.failed.append(name)
        self._baseline.update(current)
        return not self.failed


class TpuRuntimeCheck(HealthCheck):
    """TPU runtime state: device inventory + HBM pressure.

    The analogue of the reference's NVML device/recovery-state poll
    (``shared_utils/health_check.py:148-303``) for a runtime with no out-of-process
    query API: the check must run in a process that owns the TPU (the worker — wire
    it into the in-process restart health chain or poll it from the train loop; a
    rank-monitor process cannot open a second client to the same chips).

    Unhealthy when: the backend can no longer enumerate devices, the visible device
    count drops below ``expect_devices``, or any device's HBM usage exceeds
    ``hbm_usage_threshold`` (``bytes_in_use / bytes_limit``, from
    ``device.memory_stats()``; runtimes without memory stats skip that criterion).
    """

    def __init__(
        self,
        expect_devices: Optional[int] = None,
        hbm_usage_threshold: float = 0.98,
    ):
        self.expect_devices = expect_devices
        self.hbm_usage_threshold = hbm_usage_threshold
        self.last_failure: Optional[str] = None

    def __call__(self) -> bool:
        import jax

        self.last_failure = None
        try:
            devices = jax.local_devices()
        except Exception as e:
            self.last_failure = f"device enumeration failed: {e!r}"
            log.error(self.last_failure)
            return False
        if not devices:
            self.last_failure = "no local devices visible"
            log.error(self.last_failure)
            return False
        if self.expect_devices is not None and len(devices) < self.expect_devices:
            self.last_failure = (
                f"device count dropped: {len(devices)} < expected {self.expect_devices}"
            )
            log.error(self.last_failure)
            return False
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                continue  # backend without memory stats (e.g. CPU): skip criterion
            if not stats:
                continue
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit")
            if in_use is None or not limit:
                continue
            usage = in_use / limit
            if usage > self.hbm_usage_threshold:
                self.last_failure = (
                    f"HBM pressure on {d}: {usage:.1%} > "
                    f"{self.hbm_usage_threshold:.0%} ({in_use}/{limit} bytes)"
                )
                log.error(self.last_failure)
                return False
        return True

    def describe(self) -> str:
        return f"TpuRuntimeCheck({self.last_failure or 'ok'})"


class HostMemoryCheck(HealthCheck):
    """Host memory pressure: unhealthy when ``MemAvailable / MemTotal`` falls below
    ``min_available_fraction`` — an early signal before the OOM killer takes a
    worker (the host-side analogue of device-memory health). ``meminfo_path`` is
    injectable so tests fake the kernel file, like the reference's
    ``link_down_path_template`` (``health_check.py:325``)."""

    def __init__(
        self,
        min_available_fraction: float = 0.05,
        meminfo_path: str = "/proc/meminfo",
    ):
        self.min_available_fraction = min_available_fraction
        self.meminfo_path = meminfo_path

    def _read(self) -> Optional[tuple[int, int]]:
        try:
            fields = {}
            with open(self.meminfo_path) as f:
                for line in f:
                    name, _, rest = line.partition(":")
                    fields[name.strip()] = rest
            total = int(fields["MemTotal"].split()[0])
            avail = int(fields["MemAvailable"].split()[0])
            return avail, total
        except (OSError, KeyError, ValueError, IndexError):
            return None

    def __call__(self) -> bool:
        parsed = self._read()
        if parsed is None:
            return True  # unreadable meminfo must not take the job down
        avail, total = parsed
        frac = avail / max(total, 1)
        if frac < self.min_available_fraction:
            log.error(
                "host memory pressure: %.1f%% available < %.1f%% floor",
                frac * 100, self.min_available_fraction * 100,
            )
            return False
        return True


class IciLinkCheck(SysfsCounterCheck):
    """Per-link interconnect error monitoring with topology mapping.

    The analogue of the reference's ``NicHealthCheck`` (GPU→NIC mapping via PCI-tree
    walk + IB ``link_downed`` counter delta, ``health_check.py:352-465,527-559``),
    generalized for TPU hosts: ``device_glob`` discovers this host's accelerator
    device nodes (e.g. ``/sys/class/accel/accel*`` or a vfio path), and
    ``link_down_path_template`` maps each to its link-error counter file with
    ``{device}`` substituted — injectable so tests fake the counters exactly as the
    reference does (``link_down_path_template``, ``:325``). Delta/sticky semantics
    come from :class:`SysfsCounterCheck`; ``failed_links`` names the bad links so
    the policy layer can exclude the right failure domain.
    """

    def __init__(
        self,
        device_glob: str,
        link_down_path_template: str,
    ):
        super().__init__()
        self.device_glob = device_glob
        self.template = link_down_path_template

    def discover(self) -> dict[str, str]:
        """device name -> counter path, for every discovered device whose counter
        file exists."""
        import os

        out = {}
        for dev_path in sorted(glob.glob(self.device_glob)):
            name = os.path.basename(dev_path.rstrip("/"))
            counter = self.template.format(device=name)
            if os.path.exists(counter):
                out[name] = counter
        return out

    _sources = discover

    @property
    def failed_links(self) -> list[str]:
        return self.failed

    def describe(self) -> str:
        if self.failed:
            return f"IciLinkCheck(failed={self.failed})"
        return "IciLinkCheck"


def checks_from_config(cfg) -> list[HealthCheck]:
    """Build the config-enabled built-in checks (the reference enables its GPU/NIC
    checks the same way, ``shared_utils/health_check.py`` via FT config)."""
    checks: list[HealthCheck] = []
    if getattr(cfg, "host_memory_min_fraction", None):
        checks.append(HostMemoryCheck(cfg.host_memory_min_fraction))
    glob_set = bool(getattr(cfg, "ici_link_device_glob", None))
    tmpl_set = bool(getattr(cfg, "ici_link_down_path_template", None))
    if glob_set != tmpl_set:
        # Half-configured monitoring must fail loudly, not silently not-watch.
        raise ValueError(
            "ici_link_device_glob and ici_link_down_path_template must be set "
            "together (got only one)"
        )
    if glob_set:
        checks.append(
            IciLinkCheck(cfg.ici_link_device_glob, cfg.ici_link_down_path_template)
        )
    return checks


class PeriodicHealthMonitor:
    """Polls a set of checks on an interval in a daemon thread; fires ``on_failure``
    once per failed check (reference async_check loop)."""

    def __init__(
        self,
        checks: list[HealthCheck],
        interval: float,
        on_failure: Callable[[HealthCheck], None],
    ):
        self.checks = list(checks)
        self.interval = interval
        self.on_failure = on_failure
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._failed: set[int] = set()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="health-monitor", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            for i, check in enumerate(self.checks):
                if i in self._failed:
                    continue
                if not check():
                    self._failed.add(i)
                    try:
                        self.on_failure(check)
                    except Exception:
                        log.exception("health on_failure callback failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
