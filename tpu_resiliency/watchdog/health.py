"""Pluggable host/device health checks run by the rank monitor.

Analogue of the reference's ``shared_utils/health_check.py`` (``GPUHealthCheck:148``,
``NicHealthCheck:306``). On TPU there is no NVML; the equivalents are:

- :class:`DeviceLivenessCheck` — submits tiny device work under a watchdog thread
  (must run in a process that owns the TPU; workers use it inside restart health
  checks, see ``inprocess/health_check``),
- :class:`SysfsCounterCheck` — watches a sysfs error-counter delta, the generalization
  of the reference's IB ``link_downed`` monitoring (``health_check.py:527-559``); the
  path template is injectable so tests fake the counter exactly as the reference does
  (``health_check.py:325``),
- :class:`CallbackHealthCheck` — wraps any ``() -> bool``.

All checks expose sync ``__call__() -> bool`` and can be polled periodically by the
monitor with an ``on_failure`` callback (reference ``async_check`` loop,
``health_check.py:148-303``).
"""

from __future__ import annotations

import abc
import glob
import threading
import time
from typing import Callable, Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class HealthCheck(abc.ABC):
    @abc.abstractmethod
    def __call__(self) -> bool:
        """True = healthy."""

    def describe(self) -> str:
        return type(self).__name__


class CallbackHealthCheck(HealthCheck):
    def __init__(self, fn: Callable[[], bool], name: str = "callback"):
        self._fn = fn
        self._name = name

    def __call__(self) -> bool:
        try:
            return bool(self._fn())
        except Exception:
            log.exception("health check %s raised", self._name)
            return False

    def describe(self) -> str:
        return self._name


class DeviceLivenessCheck(HealthCheck):
    """Tiny compiled add + block_until_ready under a timeout thread
    (the reference ``CudaHealthCheck`` double-sync analogue,
    ``inprocess/health_check.py:70-110``)."""

    def __init__(self, timeout: float = 60.0):
        self.timeout = timeout

    def __call__(self) -> bool:
        from tpu_resiliency.platform.device import device_liveness_probe

        return device_liveness_probe(timeout=self.timeout)


class SysfsCounterCheck(HealthCheck):
    """Healthy while monitored counters do not increase between polls.

    ``path_glob``: glob of counter files (each containing one integer). The first poll
    snapshots baselines; any later increase marks unhealthy (sticky until ``reset``).
    """

    def __init__(self, path_glob: str):
        self.path_glob = path_glob
        self._baseline: Optional[dict[str, int]] = None
        self._tripped = False

    def _read(self) -> dict[str, int]:
        values = {}
        for path in sorted(glob.glob(self.path_glob)):
            try:
                with open(path) as f:
                    values[path] = int(f.read().strip() or 0)
            except (OSError, ValueError):
                continue
        return values

    def reset(self) -> None:
        self._baseline = None
        self._tripped = False

    def __call__(self) -> bool:
        current = self._read()
        if self._baseline is None:
            self._baseline = current
            return True
        for path, value in current.items():
            if value > self._baseline.get(path, value):
                log.error("sysfs counter increased: %s %d -> %d",
                          path, self._baseline.get(path, 0), value)
                self._tripped = True
        self._baseline.update(current)
        return not self._tripped


class PeriodicHealthMonitor:
    """Polls a set of checks on an interval in a daemon thread; fires ``on_failure``
    once per failed check (reference async_check loop)."""

    def __init__(
        self,
        checks: list[HealthCheck],
        interval: float,
        on_failure: Callable[[HealthCheck], None],
    ):
        self.checks = list(checks)
        self.interval = interval
        self.on_failure = on_failure
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._failed: set[int] = set()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="health-monitor", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            for i, check in enumerate(self.checks):
                if i in self._failed:
                    continue
                if not check():
                    self._failed.add(i)
                    try:
                        self.on_failure(check)
                    except Exception:
                        log.exception("health on_failure callback failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
