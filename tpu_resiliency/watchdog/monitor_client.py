"""In-rank client API for workload monitoring.

Analogue of the reference's ``RankMonitorClient`` (``fault_tolerance/rank_monitor_client.py``):
``init_workload_monitoring`` connects to the per-rank monitor socket and receives the
effective config (``:281-321``); ``send_heartbeat`` (``:333``) and
``start_section``/``end_section``/``end_all_sections`` (``:339-367``) are the per-step
signals; ``calculate_and_set_*_timeouts`` auto-calibrate from observed behavior
(``:144-219``); ``state_dict``/``load_state_dict`` persist calculated timeouts across
restarts (``:369-423``); ``send_workload_control_request`` messages the launcher
(``:425``).
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional

from tpu_resiliency.exceptions import FaultToleranceError
from tpu_resiliency.platform import ipc
from tpu_resiliency.utils import location as location_mod
from tpu_resiliency.utils import stackdump
from tpu_resiliency.utils.logging import RankLoggerAdapter, get_logger
from tpu_resiliency.watchdog.data import (
    ErrorMsg,
    HeartbeatMsg,
    HeartbeatTimeouts,
    InitMsg,
    InitReplyMsg,
    OkMsg,
    RankInfo,
    SectionAction,
    SectionMsg,
    SectionTimeouts,
    UpdateTimeoutsMsg,
    WaitDumpMsg,
    WorkloadAction,
    WorkloadControlRequest,
)
from tpu_resiliency.watchdog.timeouts import TimeoutsCalc

log = get_logger(__name__)

#: server-side park per dump long-poll; the listener's socket timeout rides
#: comfortably above it
DUMP_POLL_S = 20.0


class RankMonitorClient:
    #: reconnect-and-retry attempts per request on a transport fault (the
    #: monitor's UDS link is an out-of-band channel: a reset must not crash the
    #: rank it exists to protect). The server re-inits sessions on reconnect.
    RECONNECT_RETRIES = 2

    def __init__(self, enable_stack_dumps: bool = True):
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._socket_path: Optional[str] = None
        self.rank_info: Optional[RankInfo] = None
        self.cfg = None
        self.hb_timeouts: Optional[HeartbeatTimeouts] = None
        self.section_timeouts: Optional[SectionTimeouts] = None
        self.timeouts_calc: Optional[TimeoutsCalc] = None
        self._loaded_state: Optional[dict] = None
        #: hang forensics: SIGUSR1 trigger + dump-listener long-poll thread
        self.enable_stack_dumps = enable_stack_dumps
        self._dump_stop = threading.Event()
        self._dump_thread: Optional[threading.Thread] = None
        self._dump_sock: Optional[socket.socket] = None
        self.log = RankLoggerAdapter(log, role="client")

    @property
    def is_initialized(self) -> bool:
        return self._sock is not None

    # -- lifecycle ---------------------------------------------------------

    def init_workload_monitoring(
        self,
        socket_path: Optional[str] = None,
        rank_info: Optional[RankInfo] = None,
    ) -> None:
        if self.is_initialized:
            raise FaultToleranceError("workload monitoring already initialized")
        socket_path = socket_path or os.environ.get(ipc.MONITOR_SOCKET_ENV)
        if not socket_path:
            raise FaultToleranceError(
                f"no monitor socket: pass socket_path or set ${ipc.MONITOR_SOCKET_ENV}"
            )
        if rank_info is None:
            rank_info = RankInfo.of_current_process(
                global_rank=int(os.environ.get("RANK", "0")),
                local_rank=int(os.environ.get("LOCAL_RANK", "0")),
            )
        self.rank_info = rank_info
        self.log.rank = rank_info.global_rank
        self._socket_path = socket_path
        # Install the operator dump path BEFORE the session exists: once the
        # monitor sees our InitMsg capabilities it may SIGUSR1 us, so the
        # handler must already be chained (main-thread init only; elsewhere
        # the capability is simply not declared).
        signal_ok = (
            stackdump.install_signal_trigger() if self.enable_stack_dumps else False
        )
        self._sock = ipc.connect(socket_path)
        reply = self._request(InitMsg(
            rank_info=rank_info,
            client_state=self._loaded_state,
            capabilities={
                "dump_signal": signal_ok,
                "dump_poll": self.enable_stack_dumps,
            },
        ))
        if not isinstance(reply, InitReplyMsg):
            raise FaultToleranceError(f"bad init reply: {reply!r}")
        self.cfg = reply.config
        self.hb_timeouts = reply.hb_timeouts
        self.section_timeouts = reply.section_timeouts
        self.timeouts_calc = TimeoutsCalc(safety_factor=self.cfg.safety_factor)
        self.timeouts_calc.reset()
        if self.enable_stack_dumps:
            self._dump_stop.clear()
            self._dump_thread = threading.Thread(
                target=self._dump_listener, args=(socket_path,),
                name="monitor-dump-listener", daemon=True,
            )
            self._dump_thread.start()
        self.log.info(f"workload monitoring initialized via {socket_path}")

    def shutdown_workload_monitoring(self) -> None:
        self._dump_stop.set()
        dump_sock = self._dump_sock
        if dump_sock is not None:
            try:
                dump_sock.close()  # unblocks the listener's parked recv
            except OSError:
                pass
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def _dump_listener(self, socket_path: str) -> None:
        """Long-poll the monitor for stack-dump requests on a DEDICATED
        connection (the shared request socket must stay free for heartbeats).

        This thread is the capture path that works when the main thread is
        parked in a GIL-releasing native wait (a wedged collective,
        ``block_until_ready``) where CPython can never run a signal handler;
        a genuinely GIL-holding hang defers the capture to the next moment
        the GIL frees (see ``utils/stackdump.py``)."""
        seen: Optional[int] = None
        while not self._dump_stop.is_set():
            try:
                sock = ipc.connect(socket_path, timeout=5.0)
            except (OSError, ConnectionError):
                if self._dump_stop.wait(2.0):
                    return
                continue
            self._dump_sock = sock
            try:
                sock.settimeout(DUMP_POLL_S + 30.0)
                while not self._dump_stop.is_set():
                    # First poll syncs to the server's current generation
                    # without dumping: a request fired before we attached
                    # belongs to a previous incarnation.
                    ipc.write_object(
                        sock,
                        WaitDumpMsg(
                            seen_gen=-1 if seen is None else seen,
                            timeout=0.0 if seen is None else DUMP_POLL_S,
                        ),
                    )
                    reply = ipc.read_object(sock)
                    payload = getattr(reply, "payload", None)
                    if not isinstance(payload, dict):
                        continue
                    gen = payload.get("gen")
                    if not isinstance(gen, int):
                        continue
                    if seen is not None and gen != seen:
                        stackdump.dump_stacks(
                            str(payload.get("reason") or "monitor_request")
                        )
                    seen = gen
            except (OSError, EOFError, ConnectionError):
                pass
            finally:
                self._dump_sock = None
                try:
                    sock.close()
                except OSError:
                    pass
            if self._dump_stop.wait(0.5):
                return

    def _request(self, msg):
        """One request/reply round trip, self-healing across transport faults.

        A reset or truncated reply on the monitor link reconnects, replays the
        session ``InitMsg`` (the server rebuilds its ``_RankSession`` — same
        re-init path a fresh client takes), and reissues ``msg`` — bounded by
        :data:`RECONNECT_RETRIES`. Heartbeats and section signals are
        idempotent per-session, so replay is safe; the alternative (raising
        into the training loop) converts a socket blip into a rank death.
        """
        with self._lock:
            if self._sock is None:
                raise FaultToleranceError("monitor client is not initialized")
            for attempt in range(self.RECONNECT_RETRIES + 1):
                try:
                    ipc.write_object(self._sock, msg)
                    reply = ipc.read_object(self._sock)
                    break
                except (OSError, EOFError) as e:
                    if attempt >= self.RECONNECT_RETRIES:
                        raise FaultToleranceError(
                            f"monitor link failed after {attempt + 1} attempts: {e!r}"
                        ) from e
                    self.log.warning(
                        f"monitor link fault ({e!r}); reconnecting "
                        f"({attempt + 1}/{self.RECONNECT_RETRIES})"
                    )
                    try:
                        self._reconnect_locked()
                    except (OSError, EOFError):
                        # Reconnect itself faulted: the next attempt's write
                        # fails fast on the dead socket and burns one retry.
                        pass
        if isinstance(reply, ErrorMsg):
            raise FaultToleranceError(f"monitor error: {reply.error}")
        return reply

    def _reconnect_locked(self) -> None:
        """Dial a fresh connection and re-init the session (lock held). If the
        caller's message WAS an InitMsg the follow-up resend is a harmless
        second re-init."""
        try:
            self._sock.close()
        except OSError:
            pass
        # Short dial budget: a monitor that is genuinely gone should surface
        # within the retry window, not block a train step for 30 s per attempt.
        self._sock = ipc.connect(self._socket_path, timeout=5.0)
        if self.rank_info is not None and self.cfg is not None:
            # Re-establish the session the dead connection carried; skipped
            # during the very first init (no reply processed yet) where the
            # retried InitMsg itself re-inits.
            ipc.write_object(
                self._sock,
                InitMsg(
                    rank_info=self.rank_info,
                    client_state=self.state_dict(),
                    capabilities={
                        "dump_signal": self.enable_stack_dumps
                        and stackdump._trigger_pipe is not None,
                        "dump_poll": self.enable_stack_dumps,
                    },
                ),
            )
            reply = ipc.read_object(self._sock)
            if not isinstance(reply, InitReplyMsg):
                raise FaultToleranceError(f"bad re-init reply: {reply!r}")

    # -- per-step signals --------------------------------------------------

    def send_heartbeat(self) -> None:
        # Every heartbeat carries the last-known-location beacon: the
        # monitor's "last seen in ..." hang diagnosis is only as fresh as the
        # final message that got through before the stall.
        self._request(HeartbeatMsg(
            rank=self.rank_info.global_rank, location=location_mod.snapshot(),
        ))
        self.timeouts_calc.update_on_heartbeat()

    def start_section(self, name: str) -> None:
        location_mod.enter_section(name)
        self._request(SectionMsg(
            rank=self.rank_info.global_rank, action=SectionAction.OPEN,
            name=name, location=location_mod.snapshot(),
        ))
        self.timeouts_calc.update_on_section_open(name)

    def end_section(self, name: str) -> None:
        location_mod.exit_section(name)
        self._request(SectionMsg(
            rank=self.rank_info.global_rank, action=SectionAction.CLOSE,
            name=name, location=location_mod.snapshot(),
        ))
        self.timeouts_calc.update_on_section_close(name)

    def end_all_sections(self) -> None:
        location_mod.exit_section(None)
        self._request(SectionMsg(
            rank=self.rank_info.global_rank, action=SectionAction.CLOSE_ALL,
            location=location_mod.snapshot(),
        ))
        for name in list(self.timeouts_calc.section_open_since):
            self.timeouts_calc.update_on_section_close(name)

    # -- timeout calibration ----------------------------------------------

    def calculate_and_set_hb_timeouts(
        self, store=None, rank: int = 0, world_size: int = 1
    ) -> HeartbeatTimeouts:
        """safety_factor × max observed gaps (cross-rank MAX via store when given),
        EMA-merged with previous calculated values, pushed to the monitor."""
        self.timeouts_calc.synchronize_all(store, rank, world_size)
        new = self.timeouts_calc.get_hb_timeouts(previous=self.hb_timeouts)
        self.hb_timeouts = new
        self._request(UpdateTimeoutsMsg(hb_timeouts=new))
        return new

    def calculate_and_set_section_timeouts(
        self, store=None, rank: int = 0, world_size: int = 1
    ) -> SectionTimeouts:
        self.timeouts_calc.synchronize_all(store, rank, world_size)
        new = self.timeouts_calc.get_section_timeouts(previous=self.section_timeouts)
        self.section_timeouts = new
        self._request(UpdateTimeoutsMsg(section_timeouts=new))
        return new

    # -- persistence -------------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "hb_timeouts": self.hb_timeouts,
            "section_timeouts": self.section_timeouts,
        }

    def load_state_dict(self, state: dict) -> None:
        """Apply persisted calculated timeouts; if already connected, push them to the
        monitor immediately, otherwise they ride the next InitMsg."""
        self._loaded_state = state
        if self.is_initialized:
            hb = state.get("hb_timeouts")
            st = state.get("section_timeouts")
            if hb is not None:
                self.hb_timeouts = hb
            if st is not None:
                self.section_timeouts = st
            self._request(UpdateTimeoutsMsg(hb_timeouts=hb, section_timeouts=st))

    # -- launcher control --------------------------------------------------

    def send_workload_control_request(
        self, action: WorkloadAction, reason: str = ""
    ) -> None:
        """Fire a control request at the launcher's IPC socket
        (reference ``rank_monitor_client.py:425``)."""
        path = os.environ.get(ipc.LAUNCHER_SOCKET_ENV)
        if not path:
            raise FaultToleranceError(f"${ipc.LAUNCHER_SOCKET_ENV} is not set")
        ipc.send_to(
            path, WorkloadControlRequest(action=action, sender=self.rank_info, reason=reason)
        )
