"""Package-wide exception types.

Analogue of the reference's ``inprocess/exception.py`` (RestartError / RestartAbort /
HealthCheckError / InternalError / TimeoutError family).
"""

from __future__ import annotations


class ResiliencyError(Exception):
    """Base class for all tpu_resiliency errors."""


class StoreError(ResiliencyError):
    """Coordination-store protocol or transport failure."""


class StoreTransportError(StoreError):
    """The store connection died mid-operation (reset, EOF, socket error).

    Distinct from :class:`StoreError` proper so the client's retry layer can
    tell a recoverable transport blip (reconnect and reissue) from a server-side
    failure (an error *response* — retrying would repeat the same answer)."""


class StoreShutdownError(StoreTransportError):
    """The server announced teardown while this op was parked: it did not
    complete and the endpoint is going away.

    A transport-class failure (HA clique clients fail it over to the
    successor shard exactly like a SIGKILL'd shard) — but definitive, so the
    retry layer fails fast instead of burning its budget reconnecting to a
    server that just said goodbye."""


class StoreTimeoutError(StoreError, TimeoutError):
    """A blocking store operation (get/wait/barrier) timed out."""


class BarrierTimeout(StoreTimeoutError):
    """A distributed barrier did not complete within its timeout."""


class BarrierOverflow(StoreError):
    """More participants arrived at a barrier than its declared world size.

    The reference detects the same condition in ``inprocess/store.py:200-202``.
    """


class RestartError(ResiliencyError):
    """Base class for in-process restart errors."""


class RestartAbort(RestartError):
    """Terminal condition: the restart loop must stop retrying (reference
    ``inprocess/initialize.py:53-93`` raises this from RetryController)."""


class HealthCheckError(ResiliencyError):
    """A rank failed its post-fault health check and must not rejoin."""


class InternalError(ResiliencyError):
    """Invariant violation inside the resiliency machinery itself."""


class FaultToleranceError(ResiliencyError):
    """Watchdog / rank-monitor protocol failure."""


class CheckpointError(ResiliencyError):
    """Checkpoint save/load/replication failure."""
