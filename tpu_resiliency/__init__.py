"""tpu_resiliency — TPU-native resiliency framework for distributed JAX/XLA training.

A from-scratch re-design of the capabilities of NVIDIA's Resiliency Extension (NVRx,
reference: ajayvohra2005/nvidia-resiliency-ext-x) for TPUs:

- ``platform``:   coordination KV store with server-side barriers, UDS IPC, mesh/topology
                  introspection (the analogue of NVRx's TCPStore + device_utils substrate).
- ``telemetry``:  straggler / slow-rank detection with on-device scoring — per-rank signals
                  batched into a sharded ``[ranks, signals]`` array and reduced by a Pallas
                  robust-z/EWMA kernel (the analogue of NVRx's straggler package + CUPTI ext).
- ``watchdog``:   per-host rank monitor (heartbeats, timed sections, auto-calibrated
                  timeouts) — the analogue of NVRx's fault_tolerance rank monitor.
- ``checkpoint``: async background checkpointing + node-local checkpoints with clique
                  replication — the analogue of NVRx's checkpointing package.
- ``inprocess``:  restart of the training function without killing the process — the
                  analogue of NVRx's inprocess.Wrapper.
- ``launcher``:   per-host elastic agent + rendezvous + ``tpu-ft-launcher`` CLI — the
                  analogue of NVRx's ft_launcher.
- ``integrations``: train-loop callbacks wiring it all into a JAX training loop (the
                  analogue of NVRx's ptl_resiliency).
- ``models`` / ``parallel`` / ``ops``: flagship sharded transformer, mesh + ring-attention
                  sequence parallelism, and Pallas kernels used by the framework.
"""

__version__ = "0.1.0"
