"""Per-host elastic agent: rendezvous → spawn monitors + workers → supervise → restart.

Re-design of the reference's launcher/agent stack (``fault_tolerance/launcher.py``
``LocalElasticAgent:126`` / ``_invoke_run_with_*_policy:281,350`` +
``_torch_elastic_compat/agent/server/api.py`` ``SimpleElasticAgent``) on TPU-native
substrate: membership and restart signalling ride the coordination KV store
(``rendezvous.py``) instead of a c10d TCPStore fork; per-rank hang detection is the
``watchdog`` monitor process (UDS), reference ``launcher.py:454 setup_rank_monitors``;
rank control requests (exclude-node / shutdown, reference
``_handle_control_requests_from_rank``, ``_ft_rendezvous.py:785-804``) arrive on the
launcher's UDS socket.

Restart policies (reference ``launcher.py:270-449``):

- ``any-failed``: any worker failure anywhere triggers a full restart round.
- ``min-healthy``: a failed node reports unhealthy and the job restarts only once at
  least ``min_nodes`` healthy nodes are available — no thrash while hosts churn.
"""

from __future__ import annotations

import dataclasses
import os
import socket as socketmod
import threading
import time
import uuid
from typing import Optional

from tpu_resiliency.exceptions import FaultToleranceError, StoreError
from tpu_resiliency.launcher.proc import GroupState, WorkerGroup
from tpu_resiliency.launcher.rendezvous import (
    RendezvousOutcome,
    RendezvousSettings,
    StoreRendezvous,
)
from tpu_resiliency.platform import ipc
from tpu_resiliency.platform.store import StoreView
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.tracing import child_env, span
from tpu_resiliency.watchdog.config import FaultToleranceConfig
from tpu_resiliency.watchdog.data import WorkloadAction, WorkloadControlRequest
from tpu_resiliency.watchdog.monitor_server import RankMonitorServer
from tpu_resiliency.watchdog.state_machine import RestarterStateMachine

log = get_logger(__name__)


@dataclasses.dataclass
class AgentConfig:
    argv: list[str]
    nproc_per_node: int = 1
    min_nodes: int = 1
    max_nodes: int = 1
    node_id: str = ""
    max_restarts: int = 3
    restart_policy: str = "any-failed"  # or "min-healthy"
    monitor_interval: float = 0.5
    last_call_timeout: float = 1.0
    keep_alive_interval: float = 2.0
    keep_alive_timeout: float = 20.0
    upscaling_enabled: bool = False
    term_grace: float = 15.0
    run_dir: str = ""
    log_dir: Optional[str] = None
    use_python: bool = True
    enable_ft_monitors: bool = True
    store_host: str = "127.0.0.1"
    store_port: int = 0
    #: parked pre-imported interpreters kept warm per node: restart rounds
    #: promote one instead of paying the measured multi-second spawn+import
    #: serialization (BENCH_restart.json decomposition). 0 disables.
    warm_spares: int = 0
    warm_spare_preload: str = "jax"
    #: park phase for spares: "imports" (preloads only), "runtime" (the
    #: platform-safe device.warm_runtime pre-init), or a custom
    #: "module:function" spec. Deeper-warmed spares are promoted first.
    warm_spare_warmup: str = "imports"
    #: restart fast-path rendezvous (round reuse): replacement rounds with
    #: unchanged agent membership close with one CAS + one barrier instead of
    #: the full open/join/close ladder
    rdzv_fast_path: bool = True
    #: directory for incident artifacts + flight-recorder dumps; empty
    #: disables the incident plane (``launcher/incident.py``). Exported to
    #: workers as $TPU_RESILIENCY_FLIGHT_DIR so every rank keeps a
    #: crash-surviving ring of its last events.
    incidents_dir: str = ""
    #: None disables the live telemetry endpoint (``launcher/telemetry.py``);
    #: 0 binds an ephemeral port (the bound port lands in
    #: ``<run_dir>/telemetry.port`` — the port-file handshake). Enabling it
    #: also exports $TPU_RESILIENCY_METRICS_PUSH to workers so every rank
    #: publishes its metrics snapshot up the coordination store for the
    #: merged job-level /metrics view.
    telemetry_port: Optional[int] = None
    #: store key prefix the ranks publish metrics snapshots under (namespaced
    #: by --rdzv-id at the CLI so jobs sharing a store endpoint never merge
    #: each other's metrics)
    metrics_push_prefix: str = "jobmetrics/default/"
    #: fleet-federation discovery directory (``--fleet-dir``): the telemetry
    #: server registers this job's endpoint as a heartbeat-refreshed lease
    #: file there so ``tpu-fleetd`` can scrape it (``fleet/registry.py``);
    #: empty disables registration. Requires telemetry to be enabled.
    fleet_dir: str = ""
    #: fleet job identity (the CLI passes --rdzv-id): the lease's job key and
    #: the ``job=`` label fleetd injects when merging this job's metrics
    job_id: str = "default"
    #: goodput-optimal autoscale controller (``launcher/autoscale.py``):
    #: "off" disables it; "advise" computes and audits every decision but
    #: actuates nothing (the safe mode to trust the model first); "act"
    #: routes decisions through the remediation actuators and restart rounds.
    autoscale: str = "off"
    #: SLO watchtower (``telemetry/watchtower.py``): "on" runs the burn-rate
    #: alert engine off the telemetry server's events tail and serves it at
    #: ``GET /alerts``; "off" disables it. Requires telemetry to be enabled
    #: to matter. Rule overrides ride $TPU_RESILIENCY_ALERT_RULES.
    alerts: str = "on"

    def __post_init__(self):
        if not self.node_id:
            self.node_id = f"{socketmod.gethostname()}-{uuid.uuid4().hex[:8]}"
        if not self.run_dir:
            self.run_dir = os.path.join(
                os.environ.get("TMPDIR", "/tmp"), f"tpu_ft_{os.getpid()}"
            )
        if self.restart_policy not in ("any-failed", "min-healthy"):
            raise ValueError(f"unknown restart policy {self.restart_policy!r}")
        if self.autoscale not in ("off", "advise", "act"):
            raise ValueError(
                f"unknown autoscale mode {self.autoscale!r}: "
                f"want off | advise | act"
            )
        if self.alerts not in ("off", "on"):
            raise ValueError(
                f"unknown alerts mode {self.alerts!r}: want off | on"
            )


class WorkersFailed(RuntimeError):
    def __init__(self, message: str, exitcodes: dict):
        super().__init__(message)
        self.exitcodes = exitcodes


class ElasticAgent:
    def __init__(self, cfg: AgentConfig, ft_cfg: FaultToleranceConfig, store: StoreView):
        self.cfg = cfg
        self.ft = ft_cfg
        self.store = store
        self.rdzv = StoreRendezvous(
            store.scoped("rdzv"),
            cfg.node_id,
            RendezvousSettings(
                min_nodes=cfg.min_nodes,
                max_nodes=cfg.max_nodes,
                last_call_timeout=cfg.last_call_timeout,
                keep_alive_interval=cfg.keep_alive_interval,
                keep_alive_timeout=cfg.keep_alive_timeout,
                upscaling_enabled=cfg.upscaling_enabled,
                fast_path=cfg.rdzv_fast_path,
            ),
        )
        self.restarter = RestarterStateMachine("InJob", strict=False)
        self._monitors: list = []
        self._monitor_sockets: list[str] = []
        self._ipc: Optional[ipc.IpcReceiver] = None
        self._launcher_socket = os.path.join(self.cfg.run_dir, "launcher.sock")
        self._restarts_used = 0
        self._last_exitcodes: dict[int, int] = {}
        #: last placed round's world size — a delta means the job elastically
        #: shrank (partial-slice preemption, exclusion) or re-expanded (spares
        #: returned); the resharded resume inside the workers is what makes
        #: the new world trainable, the launcher records the transition.
        self._last_world_size: Optional[int] = None
        self._spare_pool = None
        #: set by restart watchers so spare/completion waits wake on a peer's
        #: restart request instead of sleeping out their poll tick
        self._wake = threading.Event()
        #: the health decision /healthz reflects: True while the last round's
        #: workers were healthy, False from a worker failure until the
        #: replacement round's workers spawn
        self._healthy = True
        self.telemetry = None
        self.autoscale = None
        self.watchtower = None
        self._metrics_store = None
        self.incidents: Optional["IncidentEngine"] = None
        if cfg.incidents_dir:
            from tpu_resiliency.launcher.incident import IncidentEngine
            from tpu_resiliency.utils.events import FLIGHT_DIR_ENV

            # One export wires every child's flight recorder (and this
            # process's own, through the lazy events env wiring).
            os.environ[FLIGHT_DIR_ENV] = cfg.incidents_dir
            self.incidents = IncidentEngine(
                cfg.incidents_dir, node_id=cfg.node_id
            )
            self.incidents.attach()

    def _pause(self, timeout: float) -> None:
        if self._wake.wait(timeout):
            self._wake.clear()

    # -- telemetry ---------------------------------------------------------

    def _start_telemetry(self) -> None:
        from tpu_resiliency.launcher.telemetry import PORT_FILE_NAME, TelemetryServer
        from tpu_resiliency.platform.shardstore import connect_store
        from tpu_resiliency.platform.store import AUTH_KEY_ENV
        from tpu_resiliency.utils.events import EVENTS_FILE_ENV

        # A dedicated store client for the snapshot pull: the server thread
        # must not share the agent's coordination connection. Built by the
        # shard-aware factory so a clique's snapshot keys are found on
        # whichever shard they hashed to.
        self._metrics_store = connect_store(
            self.cfg.store_host, self.cfg.store_port,
            prefix=self.cfg.metrics_push_prefix, timeout=10.0,
            auth_key=os.environ.get(AUTH_KEY_ENV) or None,
        )
        store = self._metrics_store

        def fetch_snapshots() -> list:
            return [v for v in store.prefix_get("").values() if isinstance(v, dict)]

        def store_stats() -> dict:
            # The /storez source: the store's own self-telemetry op, over the
            # same dedicated client the snapshot pull uses. A pre-telemetry
            # store's unknown-op error (or a dead store) degrades the /storez
            # document inside TelemetryServer — never the endpoint.
            return store.client.store_stats()

        watchtower = None
        if self.cfg.alerts != "off":
            from tpu_resiliency.telemetry.watchtower import Watchtower

            # rules=None picks up $TPU_RESILIENCY_ALERT_RULES overrides; the
            # server's refresh() feeds it the events tail (stream clock), and
            # start() pumps that tail from the watchtower's timer thread.
            watchtower = Watchtower(job=self.cfg.job_id)
        self.watchtower = watchtower
        self.telemetry = TelemetryServer(
            port=self.cfg.telemetry_port or 0,
            port_file=os.path.join(self.cfg.run_dir, PORT_FILE_NAME),
            events_file=os.environ.get(EVENTS_FILE_ENV) or None,
            fetch_snapshots=fetch_snapshots,
            health_fn=self.health,
            census_fn=self.hang_census,
            autoscale_fn=(
                self.autoscale.status if self.autoscale is not None else None
            ),
            store_stats_fn=store_stats,
            fleet_dir=self.cfg.fleet_dir or None,
            job=self.cfg.job_id,
            node_id=self.cfg.node_id,
            incidents_dir=self.cfg.incidents_dir or None,
            watchtower=watchtower,
        )
        self.telemetry.start()

    # -- autoscale ---------------------------------------------------------

    def _spare_capacity(self) -> int:
        if self._spare_pool is None:
            return 0
        try:
            return int(self._spare_pool.stats().get("warm", 0))
        except Exception:
            return 0

    def _start_autoscale(self) -> None:
        """Wire the goodput-optimal controller (``launcher/autoscale.py``):
        signals from the shared events stream, actuators through a
        remediation engine (swap/exclude audit semantics) and restart-round
        requests (shrink/re-expand — the workers' ``load_resharded`` resume
        makes the resized world trainable)."""
        from tpu_resiliency.launcher.autoscale import (
            AutoscaleController,
            CostModel,
        )
        from tpu_resiliency.telemetry.remediation import RemediationEngine
        from tpu_resiliency.utils.events import EVENTS_FILE_ENV

        engine = RemediationEngine(
            spare_capacity_fn=self._spare_capacity,
            request_restart_fn=lambda reason: self.rdzv.request_restart(
                f"autoscale: {reason}"
            ),
            publish_degraded_fn=lambda degraded: None,
            cooldown=10.0,
        )
        watchtower = self.watchtower
        self.autoscale = AutoscaleController(
            mode=self.cfg.autoscale,
            cost_model=CostModel.from_bench(os.getcwd()),
            remediation=engine,
            spare_capacity_fn=self._spare_capacity,
            active_alerts_fn=(
                watchtower.active_alerts if watchtower is not None else None
            ),
            shrink_fn=lambda victims, reason: self.rdzv.request_restart(
                f"autoscale shrink {victims}: {reason}"
            ),
            expand_fn=lambda reason: self.rdzv.request_restart(
                f"autoscale re-expand: {reason}"
            ),
            target_world=self.cfg.max_nodes * self.cfg.nproc_per_node,
            events_file=os.environ.get(EVENTS_FILE_ENV) or None,
            interval=max(0.25, self.cfg.monitor_interval),
        )
        self.autoscale.start()
        if self.telemetry is not None:
            self.telemetry.autoscale_fn = self.autoscale.status

    # -- hang forensics ----------------------------------------------------

    def hang_census(self) -> dict:
        """The live blocked-collective census (the ``/hangz`` document).

        Three sources folded into one answer to "who is stuck where, and who
        never arrived": every rank monitor's ``StatusMsg`` (last-known
        location beacon + heartbeat staleness), the coordination store's
        ``barrier_census`` op (open barrier rounds with waiter ages and
        missing ranks), and a deterministic suspect ranking over both.
        Best-effort by design: an unreachable monitor or store degrades the
        census, never the caller.
        """
        from tpu_resiliency.utils import location as location_mod

        ranks: list[dict] = []
        for path in list(self._monitor_sockets):
            payload = self._monitor_status(path)
            if not payload:
                continue
            stuck = payload.get("last_hb_age_s")
            if not isinstance(stuck, (int, float)):
                stuck = payload.get("connected_age_s")
            ranks.append({
                "rank": payload.get("rank"),
                "pid": payload.get("pid"),
                "stuck_s": round(stuck, 3) if isinstance(stuck, (int, float)) else None,
                "last_hb_age_s": payload.get("last_hb_age_s"),
                "hb_timeout_s": payload.get("hb_timeout_s"),
                "location": payload.get("location"),
                "location_age_s": payload.get("location_age_s"),
                "where": location_mod.describe(
                    payload.get("location"), age_s=payload.get("location_age_s")
                ) or None,
                "open_sections": payload.get("open_sections"),
                "terminated": payload.get("terminated"),
                "kill_pending": payload.get("kill_pending"),
            })
        ranks.sort(key=lambda r: (r["rank"] is None, r["rank"]))
        barriers: list[dict] = []
        census_error = None
        try:
            raw = self.store.client.barrier_census()
        except Exception as e:  # store wedged/gone: serve what we have
            raw, census_error = {}, repr(e)
        for name in sorted(raw):
            b = raw[name]
            arrived = b.get("arrived") or {}
            barriers.append({
                "name": name,
                "generation": b.get("generation"),
                "world_size": b.get("world_size"),
                "arrived": arrived,
                "missing": b.get("missing") or [],
                "absent": b.get("absent") or [],
                "waiters": len(arrived),
                "oldest_wait_s": max(arrived.values(), default=0.0),
                "open_age_s": b.get("open_age_s"),
            })
        doc = {
            "schema": "tpu-hangz-1",
            "ts": time.time(),
            "node_id": self.cfg.node_id,
            "ranks": ranks,
            "barriers": barriers,
            "barrier_waiters": sum(b["waiters"] for b in barriers),
            "suspects": self._rank_suspects(ranks, barriers),
        }
        if census_error:
            doc["barrier_census_error"] = census_error
        return doc

    @staticmethod
    def _monitor_status(path: str) -> Optional[dict]:
        from tpu_resiliency.watchdog.data import StatusMsg

        try:
            sock = ipc.connect(path, timeout=1.0)
        except (OSError, ConnectionError):
            return None
        try:
            sock.settimeout(2.0)
            ipc.write_object(sock, StatusMsg())
            reply = ipc.read_object(sock)
        except (OSError, EOFError, ConnectionError):
            return None
        finally:
            try:
                sock.close()
            except OSError:
                pass
        payload = getattr(reply, "payload", None)
        if isinstance(payload, dict) and payload.get("connected"):
            return payload
        return None

    @staticmethod
    def _rank_suspects(ranks: list[dict], barriers: list[dict]) -> list[dict]:
        """Deterministic suspect ranking: a rank missing from barriers that
        others are parked in is the prime suspect; heartbeat silence past the
        timeout and a watchdog verdict corroborate."""
        scores: dict[int, float] = {}
        reasons: dict[int, list[str]] = {}

        def implicate(rank, weight: float, why: str) -> None:
            if not isinstance(rank, int):
                return
            scores[rank] = scores.get(rank, 0.0) + weight
            reasons.setdefault(rank, []).append(why)

        for b in barriers:
            if not b["waiters"]:
                continue  # nobody is blocked on this round yet
            for r in b["missing"]:
                implicate(
                    r, 2.0,
                    f"missing from barrier {b['name']!r} "
                    f"({b['waiters']} waiting, oldest {b['oldest_wait_s']:.0f}s)",
                )
        for row in ranks:
            r = row.get("rank")
            hb_age, hb_timeout = row.get("last_hb_age_s"), row.get("hb_timeout_s")
            if (
                isinstance(hb_age, (int, float))
                and isinstance(hb_timeout, (int, float))
                and hb_age > hb_timeout
            ):
                implicate(
                    r, 1.0,
                    f"heartbeat silent for {hb_age:.0f}s (timeout {hb_timeout:.0f}s)",
                )
            if row.get("kill_pending"):
                implicate(r, 3.0, f"watchdog verdict: {row['kill_pending']}")
            elif row.get("terminated"):
                implicate(r, 3.0, "terminated by watchdog")
        return [
            {"rank": r, "score": round(scores[r], 3), "reasons": reasons[r]}
            for r in sorted(scores, key=lambda r: (-scores[r], r))
        ]

    def health(self) -> dict:
        """The /healthz document: this agent's current health decision."""
        budget_ok = self._restarts_used <= self.cfg.max_restarts
        doc = {
            "healthy": bool(self._healthy and budget_ok),
            "node_id": self.cfg.node_id,
            "workers_healthy": bool(self._healthy),
            "restarts_used": self._restarts_used,
            "max_restarts": self.cfg.max_restarts,
            "restart_budget_ok": budget_ok,
        }
        if self.incidents is not None:
            doc["incident_open"] = bool(self.incidents.is_open)
        if self._spare_pool is not None:
            # Warm-spare pool state: is there standby capacity for the next
            # restart round, and how deep is it warmed?
            try:
                doc["warm_spares"] = self._spare_pool.stats()
            except Exception:
                pass
        return doc

    # -- lifecycle ---------------------------------------------------------

    def run(self) -> dict[int, int]:
        """Supervise until success, shutdown, exclusion, or restart budget exhausted.
        Returns {global_rank: exitcode} of this node's last round on success."""
        os.makedirs(self.cfg.run_dir, exist_ok=True)
        self._ipc = ipc.IpcReceiver(self._launcher_socket)
        self._ipc.start()
        # --fleet-dir implies telemetry: a fleet registration without an
        # endpoint to scrape would be a lease pointing at nothing.
        if self.cfg.telemetry_port is not None or self.cfg.fleet_dir:
            self._start_telemetry()
        if self.cfg.autoscale != "off":
            self._start_autoscale()
        self.restarter.initialize()
        prev_round = -1
        try:
            # Inside the try: an exception anywhere past this point must run
            # the finally's pool.close() (spares also self-release on the
            # pipe-EOF tether if this process dies outright).
            if self.cfg.warm_spares > 0 and self.cfg.use_python:
                from tpu_resiliency.launcher.park import WarmSparePool

                self._spare_pool = WarmSparePool(
                    self.cfg.warm_spares,
                    self.cfg.run_dir,
                    preload=self.cfg.warm_spare_preload,
                    warmup=self.cfg.warm_spare_warmup,
                )
            while True:
                try:
                    outcome = self.rdzv.next_round(prev_round)
                except (StoreError, FaultToleranceError):
                    # Store lost while re-entering rendezvous. If we carry no
                    # failure of our own — our last round's workers all
                    # succeeded, or we were a spare that never ran any — the
                    # likeliest story is "the job finished and the
                    # store-hosting agent left while a late restart request
                    # was pulling us back in": the same benign race
                    # _await_group_completion and _spare_loop already treat
                    # as completion. A node re-rendezvousing to retry its own
                    # FAILED round keeps this fatal.
                    if prev_round >= 0 and all(
                        c == 0 for c in self._last_exitcodes.values()
                    ):
                        log.info(
                            f"[{self.cfg.node_id}] store gone while "
                            f"re-rendezvousing after round {prev_round} with no "
                            f"local failure; treating job as complete"
                        )
                        return self._last_exitcodes
                    raise
                # The restart budget is charged once per restart *round*, whoever
                # caused it — a job whose failures rotate across N nodes must not
                # get N × max_restarts rounds, and a correlated k-node failure that
                # bumps the epoch k times is still one round. Round numbers are
                # global and bump exactly once per re-rendezvous, so the delta is
                # the right unit (upscale rounds count too; they are rare and the
                # alternative lets an epoch-less reopened round slip uncharged).
                if prev_round >= 0 and outcome.round > prev_round:
                    self._restarts_used += outcome.round - prev_round
                    record_event(
                        "launcher", "restart_budget", round=outcome.round,
                        node_id=self.cfg.node_id, used=self._restarts_used,
                        max=self.cfg.max_restarts,
                    )
                prev_round = outcome.round
                if self._restarts_used > self.cfg.max_restarts:
                    self.rdzv.request_shutdown(
                        f"restart budget exhausted ({self.cfg.max_restarts})"
                    )
                    self.restarter.aborted()
                    record_event(
                        "launcher", "budget_exhausted",
                        node_id=self.cfg.node_id, max_restarts=self.cfg.max_restarts,
                    )
                    raise WorkersFailed(
                        f"restart budget ({self.cfg.max_restarts}) exhausted", {}
                    )
                reason = self.rdzv.shutdown_reason()
                if reason is not None:
                    raise WorkersFailed(f"workload shut down: {reason}", {})
                if outcome.is_spare:
                    action = self._wait_as_spare(outcome)
                else:
                    action = self._run_round(outcome)
                if action == "done":
                    return self._last_exitcodes
                if action == "excluded":
                    log.info(f"[{self.cfg.node_id}] leaving the job (excluded)")
                    if self.incidents is not None and self.incidents.is_open:
                        self.incidents.close(outcome="excluded")
                    self.rdzv.leave()
                    return {}
                # action == "restart": loop into the next rendezvous round
        finally:
            if self.incidents is not None and self.incidents.is_open:
                # Leaving run() with an incident still open means the job never
                # recovered from it (budget exhausted, shutdown, store loss) —
                # the artifact must say so rather than silently vanish.
                try:
                    self.incidents.close(outcome="unrecovered")
                except Exception:
                    pass
            if self.incidents is not None:
                self.incidents.detach()
            try:
                self.rdzv.mark_exited()
            except Exception:
                pass
            self.rdzv.stop_keepalive()
            if self._ipc is not None:
                self._ipc.stop()
            if self._spare_pool is not None:
                self._spare_pool.close()
            if self.autoscale is not None:
                try:
                    # stop() finalizes pending outcomes so every decision the
                    # run audited carries a realized delta in the stream.
                    self.autoscale.stop()
                except Exception:
                    pass
                self.autoscale = None
            if self.telemetry is not None:
                try:
                    self.telemetry.stop()
                except Exception:
                    pass
                self.telemetry = None
            if self._metrics_store is not None:
                try:
                    self._metrics_store.close()
                except Exception:
                    pass
                self._metrics_store = None

    # -- spare path --------------------------------------------------------

    def _wait_as_spare(self, outcome: RendezvousOutcome) -> str:
        """Idle in reserve: poll for a restart round (our chance to be promoted),
        shutdown, or job completion (reference redundancy ranks,
        ``_ft_rendezvous.py:302-338``)."""
        log.info(f"[{self.cfg.node_id}] spare for round {outcome.round}; standing by")
        epoch0 = outcome.epoch
        try:
            watcher = self.rdzv.watch_restart(self._wake.set)
        except Exception:
            watcher = None  # accelerator only; polling still covers it
        try:
            # Standby time is a first-class phase: in the trace it shows how
            # long warm capacity sat idle before promotion (or job end).
            with span(
                "launcher", "launcher.spare_wait",
                round=outcome.round, node_id=self.cfg.node_id,
            ):
                return self._spare_loop(outcome, epoch0)
        finally:
            if watcher is not None:
                watcher.stop()

    def _spare_loop(self, outcome: RendezvousOutcome, epoch0: int) -> str:
        while True:
            self._pause(self.cfg.monitor_interval)
            try:
                if self.rdzv.shutdown_reason() is not None:
                    self._last_exitcodes = {}
                    return "done"
                if self.rdzv.restart_epoch() != epoch0:
                    return "restart"
                done = self.rdzv.done_nodes(outcome.round)
                if done and set(outcome.active) <= done:
                    self._last_exitcodes = {}
                    return "done"
                # A spare must also watch active liveness: if every active died at
                # once (host loss), no survivor is left to request the restart that
                # would promote us.
                dead = self.rdzv.dead_nodes() & set(outcome.active)
                if dead - done:
                    self.rdzv.request_restart(
                        f"spare {self.cfg.node_id} saw dead actives: {sorted(dead - done)}"
                    )
                    return "restart"
            except StoreError:
                # The store host left — the job is over; spares have nothing to do.
                self._last_exitcodes = {}
                return "done"
            req = self._poll_control()
            if req == "excluded":
                return "excluded"

    # -- active path -------------------------------------------------------

    def _run_round(self, outcome: RendezvousOutcome) -> str:
        # One span per placed round: workers spawned inside inherit it as their
        # parent (child_env below), so a restart's causal chain — fault →
        # restart request → next round → respawn — nests under round spans in
        # the exported trace.
        with span(
            "launcher", "launcher.round", round=outcome.round,
            node_rank=outcome.node_rank, node_id=self.cfg.node_id,
        ):
            return self._run_placed_round(outcome)

    def _run_placed_round(self, outcome: RendezvousOutcome) -> str:
        cfg = self.cfg
        node_rank = outcome.node_rank
        world_size = outcome.num_nodes * cfg.nproc_per_node
        first_rank = node_rank * cfg.nproc_per_node
        log.info(
            f"[{cfg.node_id}] round {outcome.round}: node_rank={node_rank} "
            f"world={world_size} nodes={outcome.active} spares={outcome.spares}"
        )
        record_event(
            "launcher", "rendezvous_round", round=outcome.round,
            node_id=cfg.node_id, node_rank=node_rank, world_size=world_size,
            active=list(outcome.active), spares=list(outcome.spares),
            fast=bool(outcome.fast),
        )
        if (
            self._last_world_size is not None
            and world_size != self._last_world_size
        ):
            # The elastic transition itself: the workers' resharded resume
            # makes the new world trainable; this record ties the shrink /
            # re-expand to the round that performed it.
            record_event(
                "launcher", "world_resized", round=outcome.round,
                node_id=cfg.node_id,
                direction="shrink" if world_size < self._last_world_size
                else "grow",
                from_world=self._last_world_size, to_world=world_size,
            )
        self._last_world_size = world_size
        base_env = {
            "NODE_RANK": str(node_rank),
            "GROUP_RANK": str(node_rank),
            "TPU_RESILIENCY_STORE_HOST": cfg.store_host,
            "TPU_RESILIENCY_STORE_PORT": str(cfg.store_port),
            # Tells an inprocess.Wrapper in the worker to ride this store as a
            # client (scoped by launcher round) instead of hosting its own —
            # the layered in-job + in-process coupling.
            "TPU_RESILIENCY_STORE_EXTERNAL": "1",
            ipc.LAUNCHER_SOCKET_ENV: self._launcher_socket,
            # Workers' events/spans parent to THIS round's span, not to
            # whatever the env held when the launcher started.
            **child_env(),
        }
        if self.telemetry is not None:
            from tpu_resiliency.utils.events import METRICS_PUSH_ENV

            # Each rank publishes its metrics snapshot up the coordination
            # store (utils/metrics.py:MetricsPublisher); the telemetry
            # server's /metrics merges the published set into the job view.
            base_env[METRICS_PUSH_ENV] = (
                f"{cfg.store_host}:{cfg.store_port}:{cfg.metrics_push_prefix}"
            )
        group = WorkerGroup(
            argv=cfg.argv,
            nproc=cfg.nproc_per_node,
            base_env=base_env,
            run_dir=cfg.run_dir,
            log_dir=cfg.log_dir,
            use_python=cfg.use_python,
            spare_pool=self._spare_pool,
        )
        watcher = None
        try:
            # The spawn segment is the restart-latency hot path (BENCH_restart
            # decomposition) — give it its own slice in the trace.
            with span(
                "launcher", "worker.spawn",
                round=outcome.round, nproc=cfg.nproc_per_node,
            ):
                self._start_monitors(outcome.round)
                if self._monitor_sockets:
                    sockets = list(self._monitor_sockets)
                    group.per_rank_env = (
                        lambda local: {ipc.MONITOR_SOCKET_ENV: sockets[local]}
                    )
                group.start(outcome.round, first_rank, world_size)
            if self.incidents is not None and self.incidents.is_open:
                # The fault's replacement round is up and training again:
                # that IS the recovery the SLO clock measures (waiting for the
                # round to *succeed* would count hours of healthy training as
                # time-to-recover on long jobs).
                self.incidents.close(outcome="recovered")
            # A peer's restart request wakes the supervise loop through the
            # same event as a local worker death: multi-node respawn is then
            # notification-bound on every surviving node, not poll-bound.
            try:
                watcher = self.rdzv.watch_restart(
                    lambda: (group.notify_change(), self._wake.set())
                )
            except Exception:
                watcher = None  # accelerator only; polling still covers it
            self.restarter.handling_start(f"round={outcome.round}")
            self.restarter.handling_processing()
            result = self._supervise(group, outcome)
            self.restarter.handling_completed()
            return result
        finally:
            if watcher is not None:
                watcher.stop()
            if group.workers and group.poll() is GroupState.RUNNING:
                # Unwinding on an exception (e.g. store loss) must not orphan the
                # round's workers — they'd keep holding the TPU devices.
                group.stop(cfg.term_grace)
            self._stop_monitors()
            # Post-round: re-digest the compile-cache manifest so entries this
            # round's workers wrote are integrity-covered even if the workers
            # died without their exit hooks (SIGKILL, OOM). On a thread — a
            # large cache's CRC pass must not sit on the restart path.
            try:
                from tpu_resiliency.platform import compile_cache

                threading.Thread(
                    target=compile_cache.refresh_manifest_from_env,
                    daemon=True, name="compile-cache-manifest",
                ).start()
            except Exception:
                pass

    def _supervise(self, group: WorkerGroup, outcome: RendezvousOutcome) -> str:
        cfg = self.cfg
        epoch0 = outcome.epoch
        i_am_leader = outcome.node_rank == 0
        self._healthy = True  # this round's workers are up: /healthz recovers
        self.rdzv.set_health(True)
        while True:
            # Event-driven: a worker exit wakes this immediately (ms detection
            # on the respawn path); the timeout bounds control-plane polling.
            group.wait_change(cfg.monitor_interval)
            state = group.poll()
            if state is GroupState.SUCCEEDED:
                group.reap()
                self._last_exitcodes = {k: v for k, v in group.exitcodes().items()}
                self.rdzv.mark_done(outcome.round)
                record_event(
                    "launcher", "round_succeeded", round=outcome.round,
                    node_id=cfg.node_id, exitcodes=dict(self._last_exitcodes),
                )
                return self._await_group_completion(outcome, epoch0)
            if state is GroupState.FAILED:
                # Stamped the instant wait_change returned with a failure —
                # BEFORE error-file reads, the hang census, or teardown — so
                # the bench's "detect" segment measures exactly fault
                # injection → reaper-event wakeup, on cold and promoted
                # workers alike.
                record_event(
                    "launcher", "failure_detected", round=outcome.round,
                    node_id=cfg.node_id,
                )
                return self._handle_failure(group, outcome)
            # -- running: watch the control plane --------------------------
            if self.rdzv.shutdown_reason() is not None:
                group.stop(cfg.term_grace)
                raise WorkersFailed(
                    f"workload shut down: {self.rdzv.shutdown_reason()}", group.exitcodes()
                )
            if self.rdzv.restart_epoch() != epoch0:
                log.info(f"[{cfg.node_id}] restart requested elsewhere; stopping workers")
                group.stop(cfg.term_grace)
                return "restart"
            req = self._poll_control()
            if req == "excluded":
                if self.incidents is not None and not self.incidents.is_open:
                    # Rank-requested exclusion (often the remediation engine's
                    # doing) is an incident even though no worker died here.
                    self.incidents.open("exclude_request")
                group.stop(cfg.term_grace)
                self.rdzv.request_restart(f"node {cfg.node_id} excluded by rank request")
                return "excluded"
            if req == "shutdown":
                group.stop(cfg.term_grace)
                raise WorkersFailed("workload shut down by rank request", group.exitcodes())
            if i_am_leader:
                self._leader_duties(outcome)

    def _await_group_completion(self, outcome: RendezvousOutcome, epoch0: int) -> str:
        """Local workers succeeded; hold until every active node reports done (or a
        failure elsewhere pulls us into another round — any-failed semantics)."""
        while True:
            try:
                done = self.rdzv.done_nodes(outcome.round)
                if set(outcome.active) <= done:
                    return "done"
                if self.rdzv.shutdown_reason() is not None:
                    return "done"
                if self.rdzv.restart_epoch() != epoch0:
                    return "restart"
                dead = self.rdzv.dead_nodes() & set(outcome.active)
                if dead - done:
                    self.rdzv.request_restart(f"nodes died after our completion: {dead - done}")
                    return "restart"
            except StoreError:
                # Store host gone after our own success ⇒ treat the round as done.
                return "done"
            # The round watcher (still active here) wakes this on a restart.
            self._pause(self.cfg.monitor_interval)

    def _handle_failure(self, group: WorkerGroup, outcome: RendezvousOutcome) -> str:
        cfg = self.cfg
        self._healthy = False  # /healthz reports 503 until the next round spawns
        failures = group.failures()
        for f in failures:
            log.error(f"[{cfg.node_id}] worker failed: {f.describe()}")
            record_event(
                "launcher", "worker_failed", round=outcome.round,
                node_id=cfg.node_id, global_rank=f.global_rank,
                exitcode=f.exitcode, detail=f.describe(),
            )
        # Snapshot the hang census NOW, while the surviving ranks' monitors
        # still hold their sessions and the blocked barriers are still open —
        # group.stop() below destroys both halves of the evidence. One
        # ``hang_census`` record per failure (not per /hangz scrape) feeds
        # tpu_hang_suspects_total / tpu_rank_blocked_seconds.
        census: Optional[dict] = None
        if self._monitor_sockets:
            try:
                census = self.hang_census()
                record_event(
                    "launcher", "hang_census",
                    node_id=cfg.node_id, round=outcome.round,
                    suspects=census.get("suspects"),
                    blocked={
                        str(r["rank"]): r["stuck_s"]
                        for r in census.get("ranks", [])
                        if r.get("rank") is not None and r.get("stuck_s") is not None
                    },
                    barrier_waiters=census.get("barrier_waiters"),
                    open_barriers=len(census.get("barriers", [])),
                )
            except Exception:
                log.exception("hang census at failure time failed; continuing")
        if self.incidents is not None:
            # After the worker_failed records: the engine's pre-buffer scan
            # anchors time-to-detect on the earliest fault evidence.
            self.incidents.open(
                "worker_failed",
                detail="; ".join(f.describe() for f in failures),
                ranks=sorted(f.global_rank for f in failures),
                census=census,
            )
        group.stop(cfg.term_grace)
        # Budget accounting lives in run() (epoch deltas); here we only pre-check
        # whether the round we are about to request would bust it.
        if self._restarts_used + 1 > cfg.max_restarts:
            self.rdzv.request_shutdown(
                f"restart budget exhausted ({cfg.max_restarts}) after: "
                f"{failures[0].describe() if failures else 'unknown'}"
            )
            self.restarter.aborted()
            raise WorkersFailed(
                f"workers failed and restart budget ({cfg.max_restarts}) exhausted: "
                + "; ".join(f.describe() for f in failures),
                group.exitcodes(),
            )
        if cfg.restart_policy == "min-healthy":
            self.rdzv.set_health(False, failures[0].describe() if failures else "")
            self._wait_min_healthy()
        record_event(
            "launcher", "restart_requested", round=outcome.round, node_id=cfg.node_id,
            reason="; ".join(f.describe() for f in failures),
        )
        self.rdzv.request_restart(
            f"node {cfg.node_id}: " + "; ".join(f.describe() for f in failures)
        )
        return "restart"

    def _wait_min_healthy(self) -> None:
        """min-healthy policy: hold the restart until at least ``min_nodes`` *live*
        agents exist (reference ``_invoke_run_with_min_healthy_policy``,
        ``launcher.py:350``). Liveness — a fresh keep-alive — is the criterion, not
        last round's health flags: after a correlated failure every node flags
        unhealthy, yet all of them are alive and ready for the next round; counting
        flags would deadlock the whole fleet."""
        cfg = self.cfg
        epoch0 = self.rdzv.restart_epoch()
        while True:
            live = self.rdzv.live_nodes()
            if len(live) >= cfg.min_nodes:
                return
            if self.rdzv.shutdown_reason() is not None:
                return
            if self.rdzv.restart_epoch() != epoch0:
                return  # someone else already judged the fleet ready
            log.info(
                f"[{cfg.node_id}] min-healthy hold: {len(live)}/{cfg.min_nodes} live agents"
            )
            time.sleep(max(cfg.monitor_interval, 1.0))

    def _leader_duties(self, outcome: RendezvousOutcome) -> None:
        """Node-rank-0 extras each tick: evict dead nodes, trigger upscale rounds."""
        dead = self.rdzv.dead_nodes() & set(outcome.active)
        if dead:
            self.rdzv.request_restart(f"dead nodes: {sorted(dead)}")
            return
        if self.cfg.upscaling_enabled and len(outcome.active) < self.cfg.max_nodes:
            if self.rdzv.waiting_count() > 0:
                self.rdzv.request_restart("upscale: new nodes waiting")

    # -- control requests --------------------------------------------------

    def _poll_control(self) -> Optional[str]:
        """Drain rank → launcher control messages (reference
        ``_handle_control_requests_from_rank``, ``_ft_rendezvous.py:785-804``)."""
        if self._ipc is None:
            return None
        for msg in self._ipc.fetch():
            if not isinstance(msg, WorkloadControlRequest):
                log.warning(f"ignoring unknown control message {type(msg).__name__}")
                continue
            log.info(
                f"[{self.cfg.node_id}] control request {msg.action.name} "
                f"from rank {msg.sender.global_rank if msg.sender else '?'}: {msg.reason}"
            )
            record_event(
                "launcher", "control_request", node_id=self.cfg.node_id,
                action=msg.action.name, reason=msg.reason,
                sender=msg.sender.global_rank if msg.sender else None,
            )
            if msg.action is WorkloadAction.ExcludeThisNode:
                return "excluded"
            if msg.action is WorkloadAction.ShutdownWorkload:
                self.rdzv.request_shutdown(f"rank requested shutdown: {msg.reason}")
                return "shutdown"
        return None

    # -- per-rank FT monitors ----------------------------------------------

    def _start_monitors(self, round_no: int) -> None:
        if not self.cfg.enable_ft_monitors:
            return
        self._monitor_sockets = []
        for local in range(self.cfg.nproc_per_node):
            path = os.path.join(self.cfg.run_dir, f"monitor_{local}.sock")
            proc = RankMonitorServer.run_in_subprocess(self.ft, path)
            self._monitors.append(proc)
            self._monitor_sockets.append(path)

    def _stop_monitors(self) -> None:
        for proc in self._monitors:
            proc.terminate()
        for proc in self._monitors:
            proc.join(timeout=10.0)
            if proc.is_alive():
                proc.kill()
        self._monitors = []
        self._monitor_sockets = []
