"""Goodput-optimal autoscale controller: close the detect→decide→act loop.

Every plane this stack built stops one step short of autonomy: the goodput
ledger (``utils/goodput.py``) prices every second, the health-vector policy
(``telemetry/policy.py``) names the stragglers, the remediation engine
(``telemetry/remediation.py``) can checkpoint/swap/exclude, elastic resharding
(``checkpoint/reshard.py``) can shrink-and-continue, and warm spares
(``launcher/park.py``) make the transitions cheap — but an operator (or a
hard-coded policy) still decides *whether a straggler is worth a swap* or *a
preemption notice is worth a shrink*. The reference NVRx stack never closes
this loop either: its elastic agent reacts to membership, it never optimizes
a decision.

The :class:`AutoscaleController` closes it. A control loop in the launcher
consumes the signals the planes already emit — straggler scores
(``degraded_set`` events / :class:`~tpu_resiliency.telemetry.policy.
HealthDecision` sink), warm-spare depth (``warm_spare_pool`` events or a
live callable), preemption notices *including later rescinds*
(``preemption_sync_point`` / ``preemption_rescinded``), step cadence and
checkpoint recency (``iteration_start`` / ``ckpt_saved``) — and selects among

====================  =======================================================
action                when it wins
====================  =======================================================
``noop``              every candidate's predicted goodput delta is ≤ 0
``swap``              a straggler gates the job and warm spares exist: pay
                      one warm respawn, shed the slow rank
``exclude``           a straggler gates the job and NO spare exists: reshape
                      around it (capacity loss < straggler loss)
``checkpoint``        a preemption notice is pending and unbanked progress
                      exceeds the proactive save's cost
``shrink``            a notice outlived its rescind window (or its deadline
                      is imminent): shrink via ``load_resharded`` beats dying
                      at the deadline
``expand``            capacity returned, the world is below target, and the
                      hysteresis dwell passed
====================  =======================================================

using an **explicit, testable cost model**: :meth:`CostModel.estimate` turns
one candidate action into a predicted goodput delta in seconds over a fixed
horizon, from constants seeded by the measured benchmarks
(``BENCH_restart.json`` / ``BENCH_reshard.json`` — :meth:`CostModel.
from_bench`) and refined online from realized outcomes
(:meth:`CostModel.note_outcome`, a bounded per-action EWMA correction).

Audit is the contract. Every decision is an ``autoscale_decision`` event
(action, victims, mode, actuation outcome, ``predicted_delta_s``, reason) →
``tpu_autoscale_decisions_total{action,outcome}``; once its measurement
window closes, an ``autoscale_outcome`` event pairs the prediction with the
**realized** delta (training seconds gained versus the decision-time trend)
→ ``tpu_autoscale_predicted_vs_realized{action}`` — the controller's own
forecast accuracy is a first-class metric. Decisions route through the
:class:`~tpu_resiliency.telemetry.remediation.RemediationEngine` actuators
(``execute_action``) with its cooldown/dry-run audit semantics; shrink and
re-expand go through injected callables (the launcher wires restart-round
requests; the workers' ``load_resharded`` makes the new world trainable). A
hysteresis band (minimum predicted gain + a dwell between opposite resizes)
prevents shrink/expand flapping, and a rescinded notice simply removes the
shrink candidate before the dwell expires — the job never pays for a
reclamation that didn't happen.

Modes (the launcher's ``--autoscale`` flag): ``off`` (no controller),
``advise`` — the safe default when enabling: every decision is computed,
audited, and served on ``/autoscale``, but nothing actuates — and ``act``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Optional

from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

SCHEMA = "tpu-autoscale-1"

#: action names (the ``action`` label of ``tpu_autoscale_decisions_total``)
ACTION_NOOP = "noop"
ACTION_SWAP = "swap"
ACTION_EXCLUDE = "exclude"
ACTION_CHECKPOINT = "checkpoint"
ACTION_SHRINK = "shrink"
ACTION_EXPAND = "expand"

ACTIONS = (
    ACTION_NOOP, ACTION_SWAP, ACTION_EXCLUDE, ACTION_CHECKPOINT,
    ACTION_SHRINK, ACTION_EXPAND,
)

MODE_OFF = "off"
MODE_ADVISE = "advise"
MODE_ACT = "act"
MODES = (MODE_OFF, MODE_ADVISE, MODE_ACT)

#: actuation outcomes (the ``outcome`` label)
OUTCOME_ADVISED = "advised"
OUTCOME_OK = "ok"
OUTCOME_FAILED = "failed"
OUTCOME_SKIPPED = "skipped"


@dataclasses.dataclass
class Notice:
    """One pending preemption notice. ``deadline`` is an absolute timestamp
    when known (the scheduler's grace window), else None — the rescind grace
    then stands in for it."""

    key: str
    rank: Optional[int] = None
    noticed_at: float = 0.0
    deadline: Optional[float] = None


@dataclasses.dataclass
class ControllerView:
    """One tick's snapshot of every signal the cost model prices. Assembled
    by the controller, but constructible by hand — the cost model and the
    decision function are pure over it (the unit-test surface)."""

    now: float
    world_size: int
    target_world: int
    #: rank -> perf score (1.0 healthy, lower is slower) for currently
    #: degraded ranks
    stragglers: dict[int, float]
    spares: int
    notices: list[Notice]
    #: EWMA training-step wall clock (None before the first delta)
    step_s: Optional[float]
    steps_since_ckpt: int
    #: active watchtower alerts ({rule, severity, ...} rows) — the SLO
    #: plane's early warning, biasing checkpoint/swap ahead of the hang
    #: verdict. Appended last with a default so hand-built views predate it.
    active_alerts: list = dataclasses.field(default_factory=list)

    def page_alerts(self) -> list:
        """The page-severity subset — the only grade the cost model prices."""
        return [
            a for a in self.active_alerts
            if isinstance(a, dict) and a.get("severity") == "page"
        ]


@dataclasses.dataclass
class Decision:
    """One audited controller decision."""

    decision_id: int
    action: str
    victims: list[int]
    predicted_delta_s: float
    reason: str
    ts: float
    mode: str
    outcome: str = OUTCOME_ADVISED
    realized_delta_s: Optional[float] = None
    settled: bool = False


class CostModel:
    """Predicted goodput delta, in seconds over ``horizon_s``, per action.

    The constants are the measured world: ``warm_restart_s`` and
    ``cold_restart_s`` from ``BENCH_restart.json`` (warm-spare vs cold
    respawn chains), ``reshard_s`` from ``BENCH_reshard.json`` (the ranged
    resharded-resume wall time), ``ckpt_s`` the proactive save's
    caller-visible stall. ``estimate`` is pure over a
    :class:`ControllerView`; :meth:`note_outcome` folds realized outcomes
    into a bounded per-action EWMA correction factor so a systematically
    optimistic forecast self-deflates instead of repeating its mistake.
    """

    def __init__(
        self,
        *,
        horizon_s: float = 60.0,
        warm_restart_s: float = 0.06,
        cold_restart_s: float = 0.75,
        reshard_s: float = 0.15,
        ckpt_s: float = 0.10,
        #: probability a notice that reaches its deadline actually reclaims
        #: the capacity (rescinds make this < 1)
        p_preempt: float = 0.7,
        #: probability a page-severity watchtower alert (pre-hang straggler,
        #: SLO burn) escalates into lost progress if nothing is banked
        p_alert_risk: float = 0.35,
        #: extra outage beyond the cold restart when a preemption kills a
        #: rank with no shrink prepared (blocked re-rendezvous, fallback loss)
        preempt_block_s: float = 2.0,
        #: fraction of nominal throughput one excluded/shrunk rank is worth
        #: (data-parallel capacity is roughly linear in ranks)
        capacity_weight: float = 1.0,
        #: EWMA weight of each realized outcome on the per-action correction
        ewma_alpha: float = 0.3,
    ):
        self.horizon_s = horizon_s
        self.warm_restart_s = warm_restart_s
        self.cold_restart_s = cold_restart_s
        self.reshard_s = reshard_s
        self.ckpt_s = ckpt_s
        self.p_preempt = p_preempt
        self.p_alert_risk = p_alert_risk
        self.preempt_block_s = preempt_block_s
        self.capacity_weight = capacity_weight
        self.ewma_alpha = ewma_alpha
        #: per-action multiplicative correction, refined from realized
        #: outcomes and clamped to [0.25, 4.0] so one outlier can neither
        #: mute nor explode the model
        self.corrections: dict[str, float] = {}
        #: per-action (n, sum_predicted, sum_realized) — forecast accuracy
        self.outcomes: dict[str, list[float]] = {}

    @classmethod
    def from_bench(cls, bench_dir: str, **overrides) -> "CostModel":
        """Seed the constants from the repo's measured benchmarks when the
        artifacts exist; silently keep the defaults where they don't (a fresh
        checkout prices conservatively instead of crashing)."""
        kw: dict[str, float] = {}
        try:
            with open(os.path.join(bench_dir, "BENCH_restart.json")) as f:
                b = json.load(f)
            warm = b.get("in_job_warm_spares") or {}
            cold = b.get("in_job") or {}
            w = sum(
                warm.get(k, 0.0) or 0.0
                for k in ("detect_ms", "teardown_ms", "rendezvous_ms",
                          "respawn_ms")
            ) / 1e3
            c = sum(
                cold.get(k, 0.0) or 0.0
                for k in ("detect_ms", "teardown_ms", "rendezvous_ms",
                          "respawn_ms")
            ) / 1e3
            if w > 0:
                kw["warm_restart_s"] = w
            if c > 0:
                kw["cold_restart_s"] = c
        except (OSError, ValueError):
            pass
        try:
            with open(os.path.join(bench_dir, "BENCH_reshard.json")) as f:
                r = json.load(f)
            # Prefer the phase decomposition (PR 13): plan + fetch is the
            # true per-rank resize stall once serve/fetch/assembly overlap —
            # the top-line ranged_s also charges the local assembly that now
            # hides under the fetch, so pricing from it overstates elasticity
            # cost and the controller under-chooses shrink/expand.
            phases = r.get("phases") or {}
            plan_s = phases.get("plan_s")
            fetch_s = phases.get("fetch_s")
            if (
                isinstance(plan_s, (int, float))
                and isinstance(fetch_s, (int, float))
                and plan_s >= 0 and fetch_s > 0
            ):
                kw["reshard_s"] = float(plan_s) + float(fetch_s)
            elif isinstance(r.get("ranged_s"), (int, float)) and r["ranged_s"] > 0:
                kw["reshard_s"] = float(r["ranged_s"])
        except (OSError, ValueError):
            pass
        kw.update(overrides)
        return cls(**kw)

    # -- the estimates ------------------------------------------------------

    def _corr(self, action: str) -> float:
        return self.corrections.get(action, 1.0)

    @staticmethod
    def _slow_frac(view: ControllerView) -> float:
        """How much of the job's throughput the stragglers eat: synchronous
        training is gated by its slowest rank, so the worst score bounds the
        whole job's step inflation."""
        if not view.stragglers:
            return 0.0
        worst = min(view.stragglers.values())
        return min(1.0, max(0.0, 1.0 - worst))

    def estimate(self, action: str, view: ControllerView) -> float:
        """Predicted goodput delta (training seconds gained over
        ``horizon_s`` versus doing nothing) for ``action`` under ``view``.
        Negative means the action costs more than it saves."""
        H = self.horizon_s
        k = max(1, len(view.stragglers))
        W = max(1, view.world_size)
        if action == ACTION_NOOP:
            return 0.0
        if action == ACTION_SWAP:
            # Shed the straggler for one warm respawn; capacity unchanged.
            return self._slow_frac(view) * H * self._corr(action) - self.warm_restart_s
        if action == ACTION_EXCLUDE:
            # No spare: reshape around the slow ranks. Gain = straggler drag
            # minus the excluded ranks' share of nominal capacity.
            gain = (self._slow_frac(view) - self.capacity_weight * k / W) * H
            return gain * self._corr(action) - self.reshard_s
        if action == ACTION_CHECKPOINT:
            # Bank unbanked progress before a notice can kill the rank — or,
            # absent a notice, before a page-severity watchtower alert
            # (pre-hang straggler, SLO burn) turns into the hang verdict.
            pages = view.page_alerts()
            if view.step_s is None or not (view.notices or pages):
                return -self.ckpt_s
            at_risk = min(view.steps_since_ckpt * view.step_s, H)
            p = self.p_preempt if view.notices else self.p_alert_risk
            return p * at_risk * self._corr(action) - self.ckpt_s
        if action == ACTION_SHRINK:
            # Ride out the reclamation training at W-k instead of dying at
            # the deadline (cold restart + blocked re-rendezvous + the
            # progress the fallback loses). The shrunk ranks' capacity is NOT
            # charged here: the scheduler reclaims them under no-op too — the
            # delta between the branches is only the death it avoids.
            avoided = self.p_preempt * (self.cold_restart_s + self.preempt_block_s)
            return avoided * self._corr(action) - self.reshard_s
        if action == ACTION_EXPAND:
            missing = max(0, view.target_world - view.world_size)
            gain = self.capacity_weight * missing / max(1, view.target_world) * H
            return gain * self._corr(action) - self.reshard_s
        raise ValueError(f"unknown autoscale action {action!r}")

    def note_outcome(self, action: str, predicted: float, realized: float) -> None:
        """Fold one realized outcome into the per-action correction: the
        EWMA of realized/predicted, clamped, applied multiplicatively to
        future estimates of the same action."""
        st = self.outcomes.setdefault(action, [0.0, 0.0, 0.0])
        st[0] += 1
        st[1] += predicted
        st[2] += realized
        if abs(predicted) < 1e-9:
            return
        ratio = max(0.25, min(4.0, realized / predicted))
        prev = self.corrections.get(action, 1.0)
        a = self.ewma_alpha
        self.corrections[action] = max(
            0.25, min(4.0, (1 - a) * prev + a * ratio)
        )

    def constants(self) -> dict:
        """The explicit model, for the ``/autoscale`` document and the docs'
        decision-matrix table."""
        return {
            "horizon_s": self.horizon_s,
            "warm_restart_s": self.warm_restart_s,
            "cold_restart_s": self.cold_restart_s,
            "reshard_s": self.reshard_s,
            "ckpt_s": self.ckpt_s,
            "p_preempt": self.p_preempt,
            "p_alert_risk": self.p_alert_risk,
            "preempt_block_s": self.preempt_block_s,
            "capacity_weight": self.capacity_weight,
            "corrections": {
                a: round(c, 4) for a, c in sorted(self.corrections.items())
            },
        }


class AutoscaleController:
    """The control loop. Feed it signals (``observe`` event records, or the
    direct ``note_*`` calls), tick it (own thread via :meth:`start`, or
    explicitly via :meth:`tick` — the deterministic path the chaos scenario
    drives), and it decides, actuates, and audits.

    Actuation routing (``act`` mode):

    - ``swap`` / ``exclude`` / ``checkpoint`` run through the wired
      :class:`~tpu_resiliency.telemetry.remediation.RemediationEngine`
      (``execute_action``), inheriting its cooldown/dry-run audit semantics —
      one audit trail for policy-driven and controller-driven remediations.
    - ``shrink`` / ``expand`` run the injected ``shrink_fn(victims, reason)``
      / ``expand_fn(reason)`` callables (the launcher wires restart-round
      requests; the workers' ``load_resharded`` resume does the real work).

    ``advise`` mode computes, audits, and serves every decision but actuates
    nothing (``outcome="advised"``) — the safe way to trust the model before
    handing it the keys.

    Realized outcomes: the controller keeps a minimal internal train ledger
    (consecutive ``iteration_start`` deltas, gap-capped) and, once a
    decision's ``outcome_window_s`` elapses, scores it as *training seconds
    gained versus the decision-time trend*::

        realized = (train(t1) - train(t0)) - ratio(t0) * (t1 - t0)

    then feeds (predicted, realized) back into the cost model and emits the
    paired ``autoscale_outcome`` event.
    """

    def __init__(
        self,
        *,
        mode: str = MODE_ADVISE,
        cost_model: Optional[CostModel] = None,
        remediation: Any = None,
        spare_capacity_fn: Optional[Callable[[], int]] = None,
        #: the watchtower's ``active_alerts`` — polled per tick, so the SLO
        #: plane's early warning reaches the view before the hang verdict
        active_alerts_fn: Optional[Callable[[], list]] = None,
        shrink_fn: Optional[Callable[[list, str], None]] = None,
        expand_fn: Optional[Callable[[str], None]] = None,
        target_world: Optional[int] = None,
        events_file: Optional[str] = None,
        interval: float = 1.0,
        #: a notice younger than this is still rescindable — shrink waits it
        #: out (unless an explicit deadline is closer)
        rescind_grace_s: float = 5.0,
        #: shrink this long before a known deadline
        shrink_lead_s: float = 1.0,
        #: hysteresis: minimum predicted gain for a world resize, and the
        #: dwell both resize directions must respect
        hysteresis_s: float = 0.5,
        dwell_s: float = 5.0,
        #: identical (action, victims) decisions inside this window are
        #: suppressed (advise mode would otherwise narrate every tick)
        decision_cooldown_s: float = 30.0,
        #: how long after a decision its realized delta is measured
        outcome_window_s: float = 10.0,
        max_step_s: float = 300.0,
        now_fn: Callable[[], float] = time.time,
    ):
        if mode not in (MODE_ADVISE, MODE_ACT):
            raise ValueError(
                f"autoscale mode {mode!r}: want {MODE_ADVISE!r} or {MODE_ACT!r} "
                f"(off means: no controller)"
            )
        self.mode = mode
        self.model = cost_model if cost_model is not None else CostModel()
        self.remediation = remediation
        self.spare_capacity_fn = spare_capacity_fn
        self.active_alerts_fn = active_alerts_fn
        self.shrink_fn = shrink_fn
        self.expand_fn = expand_fn
        self.target_world = target_world
        self.events_file = events_file
        self.interval = interval
        self.rescind_grace_s = rescind_grace_s
        self.shrink_lead_s = shrink_lead_s
        self.hysteresis_s = hysteresis_s
        self.dwell_s = dwell_s
        self.decision_cooldown_s = decision_cooldown_s
        self.outcome_window_s = outcome_window_s
        self.max_step_s = max_step_s
        self._now = now_fn
        # -- signal state ---------------------------------------------------
        self._lock = threading.RLock()
        self._world_size = 0
        self._stragglers: dict[int, float] = {}
        self._spares_seen = 0
        self._notices: dict[str, Notice] = {}
        self._rescinds = 0
        self._step_ewma: Optional[float] = None
        self._steps_since_ckpt = 0
        self._last_step: dict[Any, tuple[float, int]] = {}
        # -- internal train ledger (realized-outcome scoring) ---------------
        self._wall0: Optional[float] = None
        self._wall1: Optional[float] = None
        self._train_s = 0.0
        # -- audit ----------------------------------------------------------
        self.decisions: list[Decision] = []
        self._next_id = 0
        self._last_decided: dict[tuple, float] = {}
        self._last_resize_ts = float("-inf")
        # -- thread/tail ----------------------------------------------------
        self._offset = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal ingestion ---------------------------------------------------

    def observe_many(self, recs) -> None:
        for rec in recs:
            if isinstance(rec, dict):
                self.observe(rec)

    def observe(self, rec: dict) -> None:
        """One flat event record (the JSONL line shape). The controller's
        inputs all ride the same stream everything else narrates to."""
        kind = rec.get("kind")
        ts = rec.get("ts")
        if not isinstance(kind, str) or not isinstance(ts, (int, float)):
            return
        with self._lock:
            if self._wall0 is None or ts < self._wall0:
                self._wall0 = ts
            if self._wall1 is None or ts > self._wall1:
                self._wall1 = ts
            if kind == "iteration_start":
                it = rec.get("iteration")
                if not isinstance(it, int):
                    return
                pid = rec.get("pid")
                prev = self._last_step.get(pid)
                if (
                    prev is not None and it == prev[1] + 1
                    and 0 < ts - prev[0] <= self.max_step_s
                ):
                    d = ts - prev[0]
                    self._train_s += d
                    self._step_ewma = (
                        d if self._step_ewma is None
                        else 0.7 * self._step_ewma + 0.3 * d
                    )
                    self._steps_since_ckpt += 1
                self._last_step[pid] = (ts, it)
            elif kind == "ckpt_saved":
                self._steps_since_ckpt = 0
            elif kind == "degraded_set":
                degraded = rec.get("degraded")
                if isinstance(degraded, list):
                    scores = rec.get("scores") or {}
                    self._stragglers = {
                        int(r): float(scores.get(str(r), scores.get(r, 0.0)))
                        for r in degraded
                    }
            elif kind == "warm_spare_pool":
                if isinstance(rec.get("warm"), (int, float)):
                    self._spares_seen = int(rec["warm"])
            elif kind in ("rendezvous_round", "world_resized"):
                ws = rec.get("world_size", rec.get("to_world"))
                if isinstance(ws, (int, float)) and ws > 0:
                    self._world_size = int(ws)
                    if self.target_world is None or ws > self.target_world:
                        self.target_world = int(ws)
            elif kind == "preemption_sync_point":
                rank = rec.get("rank")
                key = f"r{rank}" if isinstance(rank, int) else f"n{len(self._notices)}"
                self._notices.setdefault(
                    key, Notice(key=key, rank=rank if isinstance(rank, int)
                                else None, noticed_at=ts)
                )
            elif kind == "preemption_rescinded":
                rank = rec.get("rank")
                key = f"r{rank}" if isinstance(rank, int) else None
                if key is not None and key in self._notices:
                    del self._notices[key]
                    self._rescinds += 1
                elif self._notices:
                    # Rankless rescind: clear the oldest notice — a withdrawn
                    # reclamation must stop driving shrink decisions.
                    oldest = min(self._notices.values(), key=lambda n: n.noticed_at)
                    del self._notices[oldest.key]
                    self._rescinds += 1

    # -- direct feeds (launcher wiring / tests) -----------------------------

    def note_health(self, decision) -> None:
        """A :class:`~tpu_resiliency.telemetry.policy.HealthDecision` sink:
        wire as ``HealthVectorPolicy(sinks=[controller.note_health])``."""
        with self._lock:
            scores = decision.scores or {}
            self._stragglers = {
                int(r): float(scores.get(r, 0.0)) for r in decision.degraded
            }

    def note_preemption(
        self, key: str, rank: Optional[int] = None,
        deadline: Optional[float] = None,
    ) -> None:
        with self._lock:
            self._notices.setdefault(
                key, Notice(key=key, rank=rank, noticed_at=self._now(),
                            deadline=deadline)
            )

    def note_rescind(self, key: str) -> None:
        with self._lock:
            if self._notices.pop(key, None) is not None:
                self._rescinds += 1

    def note_world_size(self, world: int) -> None:
        with self._lock:
            self._world_size = int(world)
            if self.target_world is None or world > self.target_world:
                self.target_world = int(world)

    # -- the view -----------------------------------------------------------

    def view(self) -> ControllerView:
        spares = self._spares_seen
        if self.spare_capacity_fn is not None:
            try:
                spares = int(self.spare_capacity_fn())
            except Exception:
                pass
        alerts: list = []
        if self.active_alerts_fn is not None:
            try:
                alerts = list(self.active_alerts_fn())
            except Exception:
                pass  # a watchtower bug must not take the controller down
        with self._lock:
            return ControllerView(
                now=self._now(),
                world_size=self._world_size,
                target_world=self.target_world or self._world_size,
                stragglers=dict(self._stragglers),
                spares=spares,
                notices=sorted(self._notices.values(), key=lambda n: n.noticed_at),
                step_s=self._step_ewma,
                steps_since_ckpt=self._steps_since_ckpt,
                active_alerts=alerts,
            )

    # -- decide -------------------------------------------------------------

    def _candidates(self, view: ControllerView) -> list[tuple[str, list, str]]:
        """(action, victims, reason) triples eligible under ``view`` — the
        cost model prices them; this is only feasibility."""
        out: list[tuple[str, list, str]] = []
        if view.stragglers:
            victims = sorted(view.stragglers)
            worst = min(view.stragglers.values())
            if view.spares > 0:
                out.append((
                    ACTION_SWAP, victims,
                    f"straggler(s) {victims} gate the job at score "
                    f"{worst:.2f}; {view.spares} warm spare(s) standing by",
                ))
            else:
                out.append((
                    ACTION_EXCLUDE, victims,
                    f"straggler(s) {victims} at score {worst:.2f} and no "
                    f"warm capacity; reshape around them",
                ))
        pages = view.page_alerts()
        if pages and not view.notices:
            rules = sorted({str(a.get("rule")) for a in pages})
            out.append((
                ACTION_CHECKPOINT, [],
                f"page alert(s) {rules} firing with "
                f"{view.steps_since_ckpt} unbanked step(s); bank progress "
                f"before the hang verdict lands",
            ))
        if view.notices:
            victims = sorted(
                n.rank for n in view.notices if n.rank is not None
            )
            keys = [n.key for n in view.notices]
            out.append((
                ACTION_CHECKPOINT, victims,
                f"preemption notice(s) {keys} pending with "
                f"{view.steps_since_ckpt} unbanked step(s)",
            ))
            ripe = [
                n for n in view.notices
                if (n.deadline is not None
                    and n.deadline - view.now <= self.shrink_lead_s)
                or (n.deadline is None
                    and view.now - n.noticed_at >= self.rescind_grace_s)
            ]
            if ripe and view.world_size > 1:
                out.append((
                    ACTION_SHRINK,
                    sorted(n.rank for n in ripe if n.rank is not None),
                    f"notice(s) {[n.key for n in ripe]} outlived the rescind "
                    f"window; shrink beats dying at the deadline",
                ))
        if (
            not view.notices
            and not view.stragglers
            and view.target_world
            and view.world_size
            and view.world_size < view.target_world
            and view.spares > 0
        ):
            out.append((
                ACTION_EXPAND, [],
                f"capacity returned ({view.spares} spare(s)); world "
                f"{view.world_size} below target {view.target_world}",
            ))
        return out

    def decide(self, view: Optional[ControllerView] = None) -> Optional[Decision]:
        """Price every feasible candidate, apply hysteresis, pick the best
        positive one. Returns None for no-op (no event — a healthy job's
        controller is silent)."""
        view = self.view() if view is None else view
        best: Optional[tuple[float, str, list, str]] = None
        for action, victims, reason in self._candidates(view):
            predicted = self.model.estimate(action, view)
            threshold = (
                self.hysteresis_s
                if action in (ACTION_SHRINK, ACTION_EXPAND) else 0.0
            )
            if predicted <= threshold:
                continue
            if (
                action in (ACTION_SHRINK, ACTION_EXPAND)
                and view.now - self._last_resize_ts < self.dwell_s
            ):
                continue  # hysteresis dwell: no resize flapping
            key = (action, tuple(victims))
            if view.now - self._last_decided.get(key, float("-inf")) \
                    < self.decision_cooldown_s:
                continue
            if best is None or predicted > best[0]:
                best = (predicted, action, victims, reason)
        if best is None:
            return None
        predicted, action, victims, reason = best
        with self._lock:
            d = Decision(
                decision_id=self._next_id, action=action,
                victims=list(victims),
                predicted_delta_s=round(predicted, 6), reason=reason,
                ts=view.now, mode=self.mode,
            )
            self._next_id += 1
            self._last_decided[(action, tuple(victims))] = view.now
        return d

    # -- act ----------------------------------------------------------------

    def _actuate(self, decision: Decision, view: ControllerView) -> str:
        if self.mode == MODE_ADVISE:
            return OUTCOME_ADVISED
        try:
            if decision.action in (ACTION_SWAP, ACTION_EXCLUDE,
                                   ACTION_CHECKPOINT):
                if self.remediation is None:
                    return OUTCOME_SKIPPED
                from tpu_resiliency.telemetry import remediation as rem

                engine_action = {
                    ACTION_SWAP: rem.ACTION_SPARE_SWAP,
                    ACTION_EXCLUDE: rem.ACTION_EXCLUDE,
                    ACTION_CHECKPOINT: rem.ACTION_CHECKPOINT,
                }[decision.action]
                _, outcome = self.remediation.execute_action(
                    engine_action, decision.victims,
                    scores=view.stragglers or None,
                    reason=decision.reason,
                )
                if outcome == OUTCOME_OK and decision.action in (
                    ACTION_SWAP, ACTION_EXCLUDE,
                ):
                    # Optimistically clear the handled victims: a stale
                    # straggler view must not cascade swap→exclude for the
                    # same ranks before the policy re-scores the new round
                    # (the next degraded_set event re-establishes the truth).
                    with self._lock:
                        for r in decision.victims:
                            self._stragglers.pop(r, None)
                return outcome
            if decision.action == ACTION_SHRINK:
                if self.shrink_fn is None:
                    return OUTCOME_SKIPPED
                self.shrink_fn(decision.victims, decision.reason)
                with self._lock:
                    self._last_resize_ts = view.now
                    # The reclaimed ranks' notices are consumed by the shrink.
                    for n in list(self._notices.values()):
                        if n.rank in decision.victims or not decision.victims:
                            self._notices.pop(n.key, None)
                return OUTCOME_OK
            if decision.action == ACTION_EXPAND:
                if self.expand_fn is None:
                    return OUTCOME_SKIPPED
                self.expand_fn(decision.reason)
                with self._lock:
                    self._last_resize_ts = view.now
                return OUTCOME_OK
        except Exception as e:
            log.warning(f"autoscale actuation {decision.action} failed: {e!r}")
            return OUTCOME_FAILED
        return OUTCOME_SKIPPED

    # -- the loop -----------------------------------------------------------

    def tick(self) -> Optional[Decision]:
        """One decide→act→audit pass plus outcome settlement. The scenario
        and the launcher thread both drive exactly this."""
        self._settle_outcomes()
        view = self.view()
        decision = self.decide(view)
        if decision is None:
            return None
        decision.outcome = self._actuate(decision, view)
        if decision.action == ACTION_CHECKPOINT and decision.outcome == OUTCOME_OK:
            with self._lock:
                self._steps_since_ckpt = 0
        with self._lock:
            decision._train_at = self._train_s  # type: ignore[attr-defined]
            decision._wall_at = (self._wall1 or view.now)  # type: ignore[attr-defined]
            decision._wall0 = (self._wall0 or view.now)  # type: ignore[attr-defined]
            self.decisions.append(decision)
        record_event(
            "autoscale", "autoscale_decision",
            decision_id=decision.decision_id, action=decision.action,
            victims=decision.victims, mode=self.mode,
            outcome=decision.outcome,
            predicted_delta_s=decision.predicted_delta_s,
            reason=decision.reason, world_size=view.world_size,
            spares=view.spares,
        )
        log.info(
            f"autoscale [{self.mode}] #{decision.decision_id} "
            f"{decision.action}{decision.victims or ''}: predicted "
            f"{decision.predicted_delta_s:+.3f}s — {decision.reason} "
            f"({decision.outcome})"
        )
        return decision

    def _settle_outcomes(self, force: bool = False) -> None:
        """Score every decision whose measurement window closed: realized =
        training seconds gained versus the decision-time trend, paired with
        the prediction in one ``autoscale_outcome`` event and folded into the
        cost model's correction."""
        with self._lock:
            now = self._wall1 if self._wall1 is not None else self._now()
            pending = [
                d for d in self.decisions
                if not d.settled
                and (force or now - d.ts >= self.outcome_window_s)
            ]
            train_now, wall_now = self._train_s, (self._wall1 or now)
        for d in pending:
            train_at = getattr(d, "_train_at", 0.0)
            wall_at = getattr(d, "_wall_at", d.ts)
            wall0 = getattr(d, "_wall0", d.ts)
            window = max(1e-9, wall_now - wall_at)
            span = max(1e-9, wall_at - wall0)
            ratio_at = min(1.0, train_at / span) if span > 1e-9 else 1.0
            realized = (train_now - train_at) - ratio_at * window
            d.realized_delta_s = round(realized, 6)
            d.settled = True
            self.model.note_outcome(
                d.action, d.predicted_delta_s, d.realized_delta_s
            )
            record_event(
                "autoscale", "autoscale_outcome",
                decision_id=d.decision_id, action=d.action,
                outcome=d.outcome,
                predicted_delta_s=d.predicted_delta_s,
                realized_delta_s=d.realized_delta_s,
                forecast_error_s=round(
                    d.realized_delta_s - d.predicted_delta_s, 6
                ),
                window_s=round(window, 6),
            )

    def finalize(self) -> None:
        """Settle every still-pending decision with the data observed so far
        — a short advise run still pairs each decision with a realized
        delta before its stream ends."""
        self._settle_outcomes(force=True)

    # -- the /autoscale document --------------------------------------------

    def _alerts_snapshot(self) -> list:
        """Compact {rule, severity} rows from the wired watchtower, for the
        ``/autoscale`` document (empty when none is wired or it misbehaves)."""
        if self.active_alerts_fn is None:
            return []
        try:
            return [
                {"rule": a.get("rule"), "severity": a.get("severity")}
                for a in self.active_alerts_fn()
                if isinstance(a, dict)
            ]
        except Exception:
            return []

    def status(self) -> dict:
        with self._lock:
            decisions = [
                {
                    "decision_id": d.decision_id, "ts": d.ts,
                    "action": d.action, "victims": d.victims,
                    "mode": d.mode, "outcome": d.outcome,
                    "predicted_delta_s": d.predicted_delta_s,
                    "realized_delta_s": d.realized_delta_s,
                    "reason": d.reason,
                }
                for d in self.decisions[-50:]
            ]
            notices = [
                {"key": n.key, "rank": n.rank, "noticed_at": n.noticed_at,
                 "deadline": n.deadline}
                for n in self._notices.values()
            ]
            settled = [d for d in self.decisions if d.settled]
            return {
                "schema": SCHEMA,
                "mode": self.mode,
                "world_size": self._world_size,
                "target_world": self.target_world,
                "stragglers": {str(r): s for r, s in self._stragglers.items()},
                "active_alerts": self._alerts_snapshot(),
                "pending_notices": notices,
                "rescinds": self._rescinds,
                "decisions_total": len(self.decisions),
                "decisions": decisions,
                "forecast": {
                    "settled": len(settled),
                    "mean_abs_error_s": round(
                        sum(
                            abs((d.realized_delta_s or 0.0)
                                - d.predicted_delta_s)
                            for d in settled
                        ) / len(settled), 6
                    ) if settled else None,
                },
                "cost_model": self.model.constants(),
            }

    # -- launcher thread + events tail --------------------------------------

    def start(self) -> None:
        """Launcher mode: tail the shared events file and tick on an
        interval, on a daemon thread. A controller bug degrades to advise-by-
        silence, never to a launcher crash."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="autoscale", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.finalize()
        except Exception:
            log.debug("autoscale finalize failed", exc_info=True)

    def poll(self) -> Optional[Decision]:
        """One tail+tick pass (what the thread loops over)."""
        for rec in self._read_new_events():
            self.observe(rec)
        return self.tick()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except Exception:
                log.exception("autoscale tick failed; loop continues")

    def _read_new_events(self) -> list[dict]:
        """Incremental tail of the shared events JSONL (same torn-tail
        discipline as the telemetry server: only complete lines advance the
        offset)."""
        if not self.events_file:
            return []
        out: list[dict] = []
        try:
            with open(self.events_file, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return []
        if not chunk:
            return []
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        self._offset += end + 1
        for line in chunk[: end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out
