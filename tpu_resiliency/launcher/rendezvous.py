"""Elastic rendezvous: a CAS state machine over the coordination KV store.

Re-design of the reference's forked dynamic rendezvous
(``fault_tolerance/_ft_rendezvous.py`` + ``rendezvous/c10d_rendezvous_backend.py``):
the same membership contract — nodes join an open round; once ``min_nodes`` have
arrived the leader waits a short last call, then closes the round, ranking the first
``max_nodes`` joiners as *active* and the surplus as *spares* (the reference's
``redundancy_list``, ``_ft_rendezvous.py:302-338``); late arrivals register as
*waiting* so agents can trigger an upscale round (``upscaling_enabled``) — but built
on the store's atomic compare-and-set instead of a vendored 3k-LoC state machine.
Node liveness rides server-clock keep-alive stamps (``touch``/``stale_keys``), the
same mechanism the in-process layer uses, rather than a bespoke keep-alive protocol.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Callable, Optional

from tpu_resiliency.exceptions import BarrierTimeout, FaultToleranceError, StoreError
from tpu_resiliency.platform import treecomm
from tpu_resiliency.platform.store import CoordStore, StoreView
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.tracing import span

log = get_logger(__name__)


def _membership_digest(active: list[str], spares: list[str]) -> str:
    """Order-sensitive digest of a round's cast: identical digest ⇒ identical
    agents in identical rank order, so reusing the placement is sound."""
    return hashlib.sha1(
        json.dumps([list(active), list(spares)]).encode()
    ).hexdigest()


@dataclasses.dataclass
class RendezvousSettings:
    min_nodes: int = 1
    max_nodes: int = 1
    join_timeout: float = 600.0
    #: after min_nodes arrive, how long the leader holds the round open so
    #: stragglers can join (as actives up to max_nodes, then as spares)
    last_call_timeout: float = 1.0
    keep_alive_interval: float = 2.0
    keep_alive_timeout: float = 20.0
    upscaling_enabled: bool = False
    poll_interval: float = 0.25
    #: restart fast path: when a replacement round has the same agent
    #: membership as the round being replaced (only worker processes changed),
    #: re-admit the group with a single CAS + one barrier round instead of the
    #: full open/join/last-call/close ladder
    fast_path: bool = True
    #: how long a fast-round member waits for its peers' confirmation barrier
    #: before abandoning the reused round back to the full ladder
    fast_path_timeout: float = 5.0


@dataclasses.dataclass
class RendezvousOutcome:
    round: int
    node_rank: Optional[int]  # None ⇒ this node is a spare
    active: list[str]
    spares: list[str]
    #: restart epoch captured when the round closed — supervisors compare against
    #: the live epoch to see restart requests, including ones raised while they
    #: were still spawning workers (reading the epoch only at supervise start
    #: would lose those)
    epoch: int = 0
    #: True when this placement came from the restart fast path (round reuse:
    #: one CAS + one barrier instead of the full open/join/close ladder)
    fast: bool = False

    @property
    def is_spare(self) -> bool:
        return self.node_rank is None

    @property
    def num_nodes(self) -> int:
        return len(self.active)


class StoreRendezvous:
    """Per-agent handle on the shared rendezvous state.

    State blob under ``state``::

        {"round": int, "status": "open"|"closed", "seq": int,
         "participants": {node_id: join_seq}, "waiting": {node_id: seq},
         "active": [node_id...], "spares": [node_id...]}

    All transitions are optimistic CAS on the whole blob; contention is tiny
    (node-count writers at restart boundaries only).
    """

    def __init__(self, store: StoreView, node_id: str, settings: RendezvousSettings):
        self.store = store
        self.node_id = node_id
        self.s = settings
        self._ka_thread: Optional[threading.Thread] = None
        self._ka_stop = threading.Event()
        #: (round, membership digest) of the last round this node was placed
        #: in — the fast path's reuse key: a replacement round may ride the
        #: single-CAS path only against exactly this membership
        self._last_membership: Optional[tuple[int, str]] = None
        #: round number we last published a scattered join registration for
        #: (tree-laddered join — one idempotent set per round, never repeated)
        self._scatter_round = -1

    # -- keep-alive --------------------------------------------------------

    def start_keepalive(self) -> None:
        if self._ka_thread is not None:
            return
        self._ka_stop.clear()

        def loop():
            while not self._ka_stop.is_set():
                try:
                    self.store.touch(f"ka/{self.node_id}")
                except Exception:
                    pass
                self._ka_stop.wait(self.s.keep_alive_interval)

        self._ka_thread = threading.Thread(target=loop, name="rdzv-keepalive", daemon=True)
        self._ka_thread.start()

    def stop_keepalive(self) -> None:
        self._ka_stop.set()
        if self._ka_thread is not None:
            self._ka_thread.join(5.0)
            self._ka_thread = None

    def dead_nodes(self) -> set[str]:
        """Nodes whose keep-alive went stale, by the server clock."""
        stale = self.store.stale_keys("ka/", self.s.keep_alive_timeout)
        return {k.split("/", 1)[1] for k in stale}

    def live_nodes(self) -> set[str]:
        """Every agent with a fresh keep-alive — the pool available for a round."""
        all_known = {k.split("/", 1)[1] for k in self.store.prefix_get("ka/")}
        return all_known - self.dead_nodes()

    # -- global signals ----------------------------------------------------

    def restart_epoch(self) -> int:
        return int(self.store.try_get("restart", 0))

    def watch_restart(self, wake_fn) -> "RestartWatcher":
        """A started watcher thread that calls ``wake_fn()`` whenever the
        restart epoch mutates — folds the store's ``wait_changed`` event into a
        caller-side wakeup (the agent's supervise loop), so a peer's restart
        request propagates in ~ms instead of at the next poll tick. Purely an
        accelerator: callers keep their polling checks for correctness."""
        return RestartWatcher(self.store, wake_fn)

    def request_restart(self, reason: str) -> None:
        log.info(f"[{self.node_id}] requesting restart round: {reason}")
        self.store.list_append("restart_reasons", (self.node_id, reason, time.time()))
        self.store.add("restart", 1)

    def request_shutdown(self, reason: str) -> None:
        self.store.set("shutdown", f"{self.node_id}: {reason}")

    def shutdown_reason(self) -> Optional[str]:
        return self.store.try_get("shutdown")

    def mark_done(self, round_no: int) -> None:
        self.store.set(f"done/{round_no}/{self.node_id}", True)

    def done_nodes(self, round_no: int) -> set[str]:
        return {k.rsplit("/", 1)[1] for k in self.store.prefix_get(f"done/{round_no}/")}

    def waiting_count(self) -> int:
        state = self.store.try_get("state")
        if not state or state.get("status") != "closed":
            return 0
        return len(state.get("waiting", {}))

    def set_health(self, healthy: bool, detail: str = "") -> None:
        self.store.set(f"health/{self.node_id}", (bool(healthy), detail))

    def healthy_live_nodes(self) -> set[str]:
        dead = self.dead_nodes()
        out = set()
        for k, v in self.store.prefix_get("health/").items():
            node = k.split("/", 1)[1]
            if node in dead:
                continue
            ok = v[0] if isinstance(v, (tuple, list)) else bool(v)
            if ok:
                out.add(node)
        return out

    # -- the round state machine ------------------------------------------

    def _cas(self, expected, desired) -> bool:
        ok, _ = self.store.compare_set("state", expected, desired)
        return ok

    def next_round(self, prev_round: int = -1) -> RendezvousOutcome:
        """Block until a round numbered > `prev_round` closes with us placed in it.

        The whole wait is one ``rendezvous.round`` span: its duration IS the
        re-rendezvous segment of restart latency (the p50/p95 that
        ``tools/metrics_dump.py`` reports), and in the trace it sits between a
        failed round's end and the next round's spawn."""
        with span(
            "rendezvous", "rendezvous.round",
            prev_round=prev_round, node_id=self.node_id,
        ):
            out = self._next_round(prev_round)
        # Remember the placed round's membership: the reuse key a future
        # replacement round's fast path is gated on. Placement-less outcomes
        # (idle-spare store-loss exits) must not seed a reuse key.
        if out.active:
            self._last_membership = (out.round, _membership_digest(out.active, out.spares))
        return out

    def _next_round(self, prev_round: int) -> RendezvousOutcome:
        self.start_keepalive()
        try:
            self.store.touch(f"ka/{self.node_id}")
            # Re-entering rendezvous retracts any previous exit mark: an
            # ``exit/`` key must mean "left and stayed gone" — the shrink
            # fast path below treats it as a departure vote, and a stale one
            # from an earlier life of this node_id would shrink a live member
            # out of the world.
            self.store.delete(f"exit/{self.node_id}")
        except StoreError:
            # The store host may be mid-teardown (its job finished while we
            # were between rounds). The keep-alive is advisory; the state read
            # below owns the store-lost decision (idle-spare exit vs fatal),
            # so a dead store here must not crash the agent one line early.
            pass
        deadline = time.monotonic() + self.s.join_timeout
        min_reached_at: Optional[float] = None
        me = self.node_id
        state_ver = 0
        while time.monotonic() < deadline:
            try:
                cur, state_ver = self.store.get_versioned("state")
            except StoreError:
                if prev_round < 0:
                    # Never placed and the control plane is gone: the job completed
                    # (or died) without us — behave like an idle spare.
                    return RendezvousOutcome(round=0, node_rank=None, active=[], spares=[])
                raise FaultToleranceError(
                    f"coordination store lost during re-rendezvous (node {me})"
                )
            # Case 1: no state yet, or the last closed round is stale → open anew.
            if cur is None or (cur["status"] == "closed" and cur["round"] <= prev_round):
                # Restart fast path first: when the stale round's membership is
                # exactly the cast we were placed with (same agents, same
                # order — only worker processes changed), one CAS republishes
                # it as the replacement round and the loop re-reads straight
                # into the acceptance barrier below. Any ineligibility (digest
                # mismatch, dead agent, waiting upscaler, store hiccup) falls
                # through to the full open/join/close ladder unchanged.
                if cur is not None and self._try_fast_reuse(cur, prev_round):
                    continue
                # A REOPENED round expects the previous round's whole cast
                # (actives, spares, waiting): whoever reopens first must not
                # close a splinter world at last-call while a still-live peer
                # is merely finishing its worker teardown — that splits the
                # fleet and thrashes restart rounds (each charging budget).
                prev_known = sorted(
                    set(cur.get("active", []))
                    | set(cur.get("spares", []))
                    | set(cur.get("waiting", {}))
                ) if cur else []
                nxt = {
                    "round": (cur["round"] + 1) if cur else 0,
                    "status": "open",
                    "seq": 1,
                    "participants": {me: 0},
                    "waiting": {},
                    "active": [],
                    "spares": [],
                    "expected": prev_known,
                }
                min_reached_at = None
                if self._cas(cur, nxt):
                    record_event(
                        "rendezvous", "rendezvous_opened", round=nxt["round"],
                        node_id=me, expected=prev_known,
                    )
                continue
            # Case 2: a closed round newer than what we had.
            if cur["status"] == "closed":
                if me in cur["active"]:
                    # A fast-reused round is only real once every active
                    # confirms through its barrier — a member that diverged to
                    # the full ladder (it saw a dead peer first) must starve
                    # the barrier and force the reopen, not leave a splinter
                    # world supervising orphaned workers.
                    if cur.get("fast_from") and not self._confirm_fast_round(cur):
                        continue  # abandoned: state has moved, re-read it
                    return RendezvousOutcome(
                        round=cur["round"],
                        node_rank=cur["active"].index(me),
                        active=list(cur["active"]),
                        spares=list(cur["spares"]),
                        epoch=cur.get("epoch", 0),
                        fast=bool(cur.get("fast_from")),
                    )
                if me in cur["spares"]:
                    return RendezvousOutcome(
                        round=cur["round"],
                        node_rank=None,
                        active=list(cur["active"]),
                        spares=list(cur["spares"]),
                        epoch=cur.get("epoch", 0),
                        fast=bool(cur.get("fast_from")),
                    )
                # Late arrival: advertise for the next (upscale) round.
                if me not in cur.get("waiting", {}):
                    nxt = dict(cur)
                    nxt["waiting"] = dict(cur.get("waiting", {}))
                    nxt["waiting"][me] = nxt["seq"]
                    nxt["seq"] += 1
                    self._cas(cur, nxt)
                    continue
                active = set(cur["active"])
                try:
                    done = self.done_nodes(cur["round"])
                    dead = self.dead_nodes()
                except StoreError:
                    if prev_round < 0:
                        return RendezvousOutcome(
                            round=cur["round"], node_rank=None,
                            active=list(cur["active"]), spares=list(cur["spares"]),
                        )
                    raise
                if active <= done:
                    # The job finished without needing us: report as an idle spare
                    # so the agent exits cleanly.
                    return RendezvousOutcome(
                        round=cur["round"], node_rank=None,
                        active=list(cur["active"]), spares=list(cur["spares"]),
                    )
                if active and active <= (dead | done):
                    # Every remaining active died and no survivor is left to call
                    # a restart round — a waiting node must reopen itself or the
                    # job is lost with standby capacity available.
                    nxt = {
                        "round": cur["round"] + 1,
                        "status": "open",
                        "seq": 1,
                        "participants": {me: 0},
                        "waiting": {},
                        "active": [],
                        "spares": [],
                    }
                    min_reached_at = None
                    if self._cas(cur, nxt):
                        log.info(f"[{me}] actives all dead; reopened round {cur['round'] + 1}")
                        record_event(
                            "rendezvous", "rendezvous_opened",
                            round=cur["round"] + 1, node_id=me,
                            reason="actives all dead",
                        )
                    continue
                # Registered and the job is healthy: we are standby redundancy for
                # this closed round — report as a spare now rather than blocking
                # until some future round (the reference's redundancy nodes join
                # a completed rendezvous without re-triggering it,
                # ``_ft_rendezvous.py:827-831``). The agent's spare loop handles
                # promotion, job completion, and dead-active detection from here.
                return RendezvousOutcome(
                    round=cur["round"],
                    node_rank=None,
                    active=list(cur["active"]),
                    spares=list(cur["spares"]),
                    epoch=cur.get("epoch", 0),
                )
            # Case 3: an open round.
            parts = cur["participants"]
            scatter = self._scatter_join_enabled()
            if me not in parts:
                if scatter:
                    # Tree-laddered join (the treecomm edge shape lifted onto
                    # the ladder): one idempotent ``set`` on a per-node key —
                    # hash-scattered across clique shards — instead of a CAS
                    # retry storm where every joiner read-modify-writes the
                    # ONE state key through one event loop. The leader folds
                    # registrations into ``participants`` in batches below;
                    # we park on the state key until a fold lands us.
                    if self._scatter_round != cur["round"]:
                        try:
                            treecomm.scatter_register(
                                self.store, f"join/{cur['round']}", me
                            )
                            self._scatter_round = cur["round"]
                        except StoreError:
                            pass
                else:
                    nxt = dict(cur)
                    nxt["participants"] = dict(parts)
                    nxt["participants"][me] = nxt["seq"]
                    nxt["seq"] += 1
                    self._cas(cur, nxt)
                    continue
            dead = self.dead_nodes()
            live_parts = {n: s for n, s in parts.items() if n == me or n not in dead}
            if scatter and live_parts and min(live_parts, key=live_parts.get) == me:
                # Aggregator duty rides leadership (lowest join seq): fold
                # every scattered registration in one batched CAS. A fold
                # mutates state, so every parked joiner wakes into its
                # membership at once — O(N/batch) CASes for the whole world.
                if self._fold_scattered_joins(cur, dead):
                    continue
            if len(live_parts) >= self.s.min_nodes:
                if min_reached_at is None:
                    min_reached_at = time.monotonic()
                order = sorted(live_parts, key=live_parts.get)
                i_am_leader = order[0] == me
                # Close immediately at full strength — exactly the reference's
                # behavior (``_ft_rendezvous.py:830-831`` completes the round the
                # moment ``max_nodes`` is reached; its last-call deadline applies
                # only between min and max). Surplus nodes that registered before
                # the close still land as spares (``order[max_nodes:]``); later
                # ones advertise for the next round. This takes the last-call hold
                # off the restart critical path for fixed-size jobs.
                full = len(live_parts) >= self.s.max_nodes
                waited = time.monotonic() - min_reached_at
                # Previous-round members that are live (fresh keep-alive), did
                # not exit, and have not re-registered yet: they are mid-
                # teardown on their way here — hold the close for them past
                # last-call, bounded by the keep-alive timeout (a peer that
                # stops renewing gets pruned as dead and stops blocking).
                expected_missing = set()
                if i_am_leader and not full and cur.get("expected"):
                    # Leader-only: the exit/ scan feeds only the leader's close
                    # decision — N-1 followers issuing it each tick would tax
                    # the control plane at exactly the restart-storm moment.
                    exited = {
                        k.rsplit("/", 1)[1]
                        for k in self.store.prefix_get("exit/")
                    }
                    expected_missing = (
                        set(cur["expected"]) - set(live_parts) - dead - exited
                    )
                last_call_over = full or (
                    waited >= self.s.last_call_timeout and not expected_missing
                ) or (
                    waited >= self.s.last_call_timeout + self.s.keep_alive_timeout
                )
                if i_am_leader and last_call_over:
                    active = order[: self.s.max_nodes]
                    spares = order[self.s.max_nodes :]
                    closed = {
                        "round": cur["round"],
                        "status": "closed",
                        "seq": cur["seq"],
                        "participants": dict(live_parts),
                        "waiting": {},
                        "active": active,
                        "spares": spares,
                        "epoch": self.restart_epoch(),
                    }
                    if self._cas(cur, closed):
                        log.info(
                            f"[{me}] closed rendezvous round {cur['round']}: "
                            f"active={active} spares={spares}"
                        )
                        # Leader-only close record: ``waited`` is the
                        # min-nodes→close hold (last-call + expected-peer
                        # grace), the tunable part of round-formation latency.
                        record_event(
                            "rendezvous", "rendezvous_closed",
                            round=cur["round"], node_id=me, waited_s=waited,
                            active=active, spares=spares, full=full,
                        )
                        if scatter:
                            # GC the round's scattered join keys. A joiner
                            # whose registration raced the close re-reads
                            # closed state and lands in ``waiting`` — the
                            # same late-arrival semantics as a lost CAS.
                            try:
                                treecomm.scatter_clear(
                                    self.store, f"join/{cur['round']}"
                                )
                            except StoreError:
                                pass
                    continue
            # Event-driven: any peer's CAS on the round state wakes us at once
            # (a follower learns of the leader's close in ~ms instead of up to
            # a poll interval later); the timeout keeps the time-based checks
            # (keep-alive staleness, last-call window) paced as before.
            try:
                self.store.wait_changed("state", state_ver, self.s.poll_interval)
            except StoreError:
                time.sleep(self.s.poll_interval)
        raise FaultToleranceError(
            f"rendezvous did not complete within {self.s.join_timeout}s "
            f"(node {me}, waiting for round > {prev_round})"
        )

    # -- tree-laddered join (scatter/fold) ----------------------------------

    def _scatter_join_enabled(self) -> bool:
        """Worlds at or above the tree floor join by scattered edge keys +
        leader folds; smaller worlds keep the flat per-node CAS (one op per
        joiner is already optimal there, and it's the shape every pre-tree
        test pins)."""
        tree_min = int(
            os.environ.get(treecomm.TREE_MIN_ENV, treecomm.DEFAULT_TREE_MIN)
        )
        return self.s.max_nodes >= tree_min

    def _fold_scattered_joins(self, cur: dict, dead: set[str]) -> bool:
        """Leader/aggregator half of the tree-laddered join: collect the
        round's scattered registrations (concurrent prefix scan — fans
        across clique shards) and CAS the whole batch into ``participants``
        with consecutive join seqs (sorted by node id within a batch —
        deterministic given membership). True ⇒ a fold CAS was attempted and
        the caller must re-read state before acting on it."""
        try:
            regs = treecomm.scatter_collect(self.store, f"join/{cur['round']}")
        except StoreError:
            return False
        parts = cur["participants"]
        new = sorted(n for n in regs if n not in parts and n not in dead)
        if not new:
            return False
        nxt = dict(cur)
        nxt["participants"] = dict(parts)
        for n in new:
            nxt["participants"][n] = nxt["seq"]
            nxt["seq"] += 1
        self._cas(cur, nxt)
        record_event(
            "rendezvous", "rendezvous_join_folded", round=cur["round"],
            node_id=self.node_id, folded=len(new),
        )
        return True

    # -- restart fast path (round reuse) -----------------------------------

    def _try_fast_reuse(self, cur: dict, prev_round: int) -> bool:
        """Attempt the single-CAS round reuse against stale closed state
        ``cur``. True ⇒ a CAS was attempted (ours or a peer won the race) and
        the caller should re-read state; False ⇒ ineligible, take the full
        ladder. Eligibility is strict — any doubt degrades to the ladder:

        - we were placed in exactly ``prev_round`` and ``cur`` IS that round;
        - the membership digest matches our remembered placement (same agents,
          same rank order — the "only locally-promoted ranks changed" case);
        - nobody is waiting for an upscale round (that needs the ladder's
          re-ranking);
        - every missing member of the cast is EXPLAINED: keep-alive-dead or
          exit-marked. A fully-present cast republishes unchanged (the PR-9
          worker-restart case). An explained departure set takes the SHRINK
          fast path: vacated active slots are backfilled from surviving
          spares in order (the warm-spare swap), any remainder shrinks the
          world — one CAS plus the confirmation barrier, instead of the full
          open/join/last-call ladder. An unexplained absence (a survivor that
          merely stopped answering) cannot occur by construction — absence IS
          the explanation here — but a departed *us* or an emptied active
          list degrades to the ladder.
        """
        if not self.s.fast_path or cur["round"] != prev_round:
            return False
        mem = self._last_membership
        if mem is None or mem[0] != prev_round:
            return False
        digest = _membership_digest(cur.get("active", []), cur.get("spares", []))
        if digest != mem[1]:
            return False
        me = self.node_id
        if me not in cur["active"] and me not in cur["spares"]:
            return False
        if cur.get("waiting"):
            return False
        cast = set(cur["active"]) | set(cur["spares"])
        try:
            # Departed = no fresh keep-alive (stale OR deleted — ``leave()``
            # removes the key outright) or an explicit exit mark. Every cast
            # member touched ``ka/`` when it was placed, so a missing key is
            # a departure, never a never-seen node.
            live = self.live_nodes()
            exited = {
                k.rsplit("/", 1)[1] for k in self.store.prefix_get("exit/")
            }
            epoch = self.restart_epoch()
        except StoreError:
            return False
        departed = (cast - live) | (exited & cast)
        if me in departed:
            return False
        survivors_a = [n for n in cur["active"] if n not in departed]
        survivors_s = [n for n in cur["spares"] if n not in departed]
        # Warm-spare backfill: surviving spares take vacated active slots in
        # spare order; what cannot be backfilled is the shrink.
        vacancies = len(cur["active"]) - len(survivors_a)
        new_active = survivors_a + survivors_s[:vacancies]
        new_spares = survivors_s[vacancies:]
        if not new_active or len(new_active) < self.s.min_nodes:
            return False
        nxt = {
            "round": prev_round + 1,
            "status": "closed",
            "seq": cur["seq"] + 1,
            "participants": {n: i for i, n in enumerate(new_active)},
            "waiting": {},
            "active": new_active,
            "spares": new_spares,
            "epoch": epoch,
            "fast_from": digest,
            # A later full reopen still owes the whole cast its mid-teardown
            # grace, exactly as a ladder-closed round would — departed
            # members excluded (they are gone, not mid-teardown).
            "expected": sorted(cast - departed),
        }
        try:
            ok = self._cas(cur, nxt)
        except StoreError:
            return False
        if ok:
            outcome = "shrink" if departed else "reused"
            log.info(
                f"[{me}] fast-path rendezvous ({outcome}): round "
                f"{prev_round} -> {prev_round + 1}, active={new_active} "
                f"spares={new_spares}"
                + (f" departed={sorted(departed)}" if departed else "")
            )
            record_event(
                "rendezvous", "rendezvous_fast_path", outcome=outcome,
                round=prev_round + 1, node_id=me, digest=digest,
                departed=sorted(departed),
            )
        # CAS failure means the state moved under us (a peer fast-closed the
        # same round, or opened the full ladder) — either way, re-read.
        return True

    def _confirm_fast_round(self, cur: dict) -> bool:
        """Active member's confirmation barrier for a fast-reused round. True
        once every active arrived; False after abandoning the round (barrier
        starved or store hiccup) — the caller re-reads state and proceeds
        down the full ladder.

        Large casts confirm through a tree barrier (``platform/treecomm.py``)
        instead of one flat server-side barrier: at 4096 agents the flat
        round funnels every arrival and release frame through one store event
        loop (O(N) on the release critical path); the tree's per-edge keys
        hash across a sharded clique and cap the critical path at
        O(fanout · log N). Small casts keep the flat barrier — identical to
        every pre-tree build, and one op per agent is already optimal there.
        A tree timeout abandons to the full ladder exactly like a flat one.
        """
        from tpu_resiliency.platform import treecomm

        me = self.node_id
        active = cur["active"]
        tree_min = int(
            os.environ.get(treecomm.TREE_MIN_ENV, treecomm.DEFAULT_TREE_MIN)
        )
        try:
            if len(active) >= tree_min:
                fanout = int(
                    os.environ.get(
                        treecomm.TREE_FANOUT_ENV, treecomm.DEFAULT_FANOUT
                    )
                )
                tc = treecomm.TreeComm(
                    self.store.scoped(f"fastbar-tree/{cur['round']}"),
                    active.index(me),
                    len(active),
                    fanout=fanout,
                )
                tc.barrier("confirm", timeout=self.s.fast_path_timeout)
                if active.index(me) == 0:
                    # GC a LONG-finished round's tree keys (two rounds back:
                    # clearing the just-confirmed round could delete a deep
                    # member's release key before it parked on it).
                    try:
                        self.store.prefix_clear(
                            f"fastbar-tree/{cur['round'] - 2}/"
                        )
                    except StoreError:
                        pass
            else:
                self.store.barrier_join(
                    f"fastbar/{cur['round']}",
                    active.index(me),
                    len(active),
                    self.s.fast_path_timeout,
                )
            return True
        except (BarrierTimeout, StoreError) as e:
            log.warning(
                f"[{me}] fast-path round {cur['round']} confirmation failed "
                f"({e!r}); abandoning to the full ladder"
            )
            self._abandon_fast_round(cur)
            return False

    def _abandon_fast_round(self, cur: dict) -> None:
        """Demote a fast-reused round that never confirmed: CAS it to an open
        round so the full ladder re-forms the world. Best-effort — if the CAS
        fails someone else already moved the state, which is just as good."""
        nxt = {
            "round": cur["round"] + 1,
            "status": "open",
            "seq": 1,
            "participants": {self.node_id: 0},
            "waiting": {},
            "active": [],
            "spares": [],
            "expected": sorted(
                set(cur.get("active", [])) | set(cur.get("spares", []))
            ),
        }
        try:
            if self._cas(cur, nxt):
                record_event(
                    "rendezvous", "rendezvous_fast_path", outcome="abandoned",
                    round=cur["round"], node_id=self.node_id,
                )
        except StoreError:
            pass

    def mark_exited(self) -> None:
        """Record that this agent's process is leaving (success or failure)."""
        self.store.set(f"exit/{self.node_id}", True)

    def await_peers_exit(self, timeout: float = 20.0) -> None:
        """Store-host duty: hold the server up until every placed peer has either
        marked itself exited or gone keep-alive-stale — otherwise closing the store
        rips the control plane out from under agents still coordinating."""
        state = self.store.try_get("state") or {}
        peers = (
            set(state.get("active", []))
            | set(state.get("spares", []))
            | set(state.get("waiting", {}))
        )
        peers.discard(self.node_id)
        deadline = time.monotonic() + timeout
        while peers and time.monotonic() < deadline:
            exited = {k.split("/", 1)[1] for k in self.store.prefix_get("exit/")}
            remaining = peers - exited
            if not remaining:
                return
            if remaining <= self.dead_nodes():
                return
            time.sleep(0.2)

    def leave(self) -> None:
        """Best-effort departure: drop our keep-alive and waiting registration."""
        self.stop_keepalive()
        try:
            self.store.delete(f"ka/{self.node_id}")
            cur = self.store.try_get("state")
            if cur and self.node_id in cur.get("waiting", {}):
                nxt = dict(cur)
                nxt["waiting"] = {
                    n: s for n, s in cur["waiting"].items() if n != self.node_id
                }
                self._cas(cur, nxt)
        except Exception:
            pass


class RestartWatcher:
    """Daemon thread parking on the restart key's version; calls ``wake_fn``
    on every mutation. Purely an accelerator: it must never be able to delay
    or fail its owner, so the connection is built INSIDE the thread with
    minimal retries (a wedged store at round start must not stall the agent's
    supervision), every wait runs on a one-shot connection (never holding a
    client lock the owner could contend on), and ``stop`` does not block —
    the daemon thread parks out its current wait (≤ its timeout) and exits."""

    #: long enough to amortize the one-shot reconnect, and past the store
    #: client's blocking threshold so the wait never rides (and locks) a
    #: persistent socket.
    _WAIT_S = 6.0

    def __init__(self, rdzv_store, wake_fn):
        client = rdzv_store.client
        self._host, self._port = client.host, client.port
        self._prefix = rdzv_store.prefix
        self._auth_key = client.auth_key
        self._wake = wake_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="restart-watcher", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        from tpu_resiliency.platform.shardstore import connect_store

        store = None
        try:
            store = connect_store(
                self._host, self._port, prefix=self._prefix,
                auth_key=self._auth_key, connect_retries=2,
            )
            _, ver = store.get_versioned("restart")
            while not self._stop.is_set():
                changed, _, ver = store.wait_changed("restart", ver, self._WAIT_S)
                if changed and not self._stop.is_set():
                    self._wake()
        except Exception:
            # On any store hiccup the owner's polling still observes the
            # epoch; don't let a watcher crash take the agent.
            pass
        finally:
            if store is not None:
                try:
                    store.close()
                except Exception:
                    pass

    def stop(self) -> None:
        """Non-blocking: flag the thread down; it exits after its current
        parked wait (daemon — it cannot outlive the process). No join, not
        even a bounded one: stop() runs in the round-teardown path of every
        restart, and the thread is parked in a multi-second store wait — a
        100 ms join timeout here was a flat 100 ms tax on EVERY respawn
        (visible as the rendezvous segment of BENCH_restart's decomposition).
        A wake racing the flag is harmless: wake_fn only sets an Event whose
        consumer re-reads store state for truth."""
        self._stop.set()
