"""Incident engine: one causally-ordered artifact per fault, with SLO timings.

Before this module, reconstructing "what happened during that fault" meant
grepping per-rank JSONL by hand. The engine automates the postmortem: on every
fault, restart round, checkpoint fallback, or remediation it opens an
**incident**, and on recovery it writes one ``incidents/incident-<ts>.json``
artifact containing

- the **causal chain**: the window's events classified into
  detect → decide → act → recover milestones, ordered by timestamp with
  span-begin-before-member tie-breaking (the PR-1 trace ids stitched across
  the launcher/worker boundary scope the window to THIS run);
- the relevant processes' **flight-recorder dumps**
  (``utils/flight_recorder.py``) — present even for a SIGKILLed rank, whose
  normal event sink died with it;
- computed **SLO timings**: time-to-detect (first fault evidence → incident
  opened), time-to-decide (opened → first decision), time-to-recover (first
  fault evidence → recovered), and steps lost (last pre-fault iteration →
  first post-recovery iteration), exported as ``tpu_incident_*`` metrics via
  ``incident_opened`` / ``incident_closed`` events.

Two operating modes share one implementation:

- **explicit** (the launcher agent): ``open()`` on worker failure / restart
  round, ``close()`` on round success — the agent knows its own phase machine.
- **auto** (``auto_open=True``, attached as an events sink inside a worker):
  degraded-set transitions, remediation decisions, and checkpoint
  fallbacks/quarantines open incidents; recovery transitions close them. This
  is how telemetry-driven remediation (``telemetry/remediation.py``) gets its
  audit artifact without the launcher in the loop.

``tools/incident_report.py`` renders any artifact as a human postmortem
timeline; schema in ``docs/incidents.md``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import Counter, deque
from typing import Any, Optional

from tpu_resiliency.utils import events as events_mod
from tpu_resiliency.utils import flight_recorder
from tpu_resiliency.utils.events import read_events, record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

SCHEMA = "tpu-incident-1"

#: how far back the pre-buffer is scanned for fault evidence at open time
FAULT_LOOKBACK_S = 300.0
#: pre-open context included in the artifact's event window
PRE_WINDOW_S = 30.0
#: bounded capture: the artifact's event window and per-process flight dumps
MAX_WINDOW_EVENTS = 2000
MAX_FLIGHT_RECORDS = 600

# -- phase classification -----------------------------------------------------

#: event kinds that are *evidence the fault itself happened* (fault_ts anchors)
FAULT_KINDS = frozenset({
    "worker_failed", "fn_exception", "hang_detected", "health_terminated",
    "rank_terminated", "ckpt_quarantined", "ckpt_integrity_failure",
})

_DETECT = frozenset(FAULT_KINDS | {
    "straggler_report", "degraded_set", "flight_flush",
})
_DECIDE = frozenset({
    "restart_requested", "remediation_decision", "control_request",
    "ckpt_fallback", "budget_exhausted", "restart_budget",
})
_ACT = frozenset({
    "remediation_action", "kill_ladder", "worker_promoted",
    "rendezvous_round", "stood_down",
})
_RECOVER = frozenset({
    "round_succeeded", "completed", "training_finished",
})


def classify_phase(rec: dict) -> Optional[str]:
    """detect | decide | act | recover for chain-worthy kinds, else None."""
    kind = rec.get("kind")
    if kind == "straggler_report":
        flagged = rec.get("stragglers_by_perf") or rec.get("stragglers_by_section")
        return "detect" if flagged else None
    if kind == "degraded_set":
        if rec.get("newly"):
            return "detect"
        if rec.get("recovered"):
            return "recover"
        return None
    if kind == "remediation_action":
        return "recover" if rec.get("action") == "reinstate" else "act"
    if kind in _DETECT:
        return "detect"
    if kind in _DECIDE:
        return "decide"
    if kind in _ACT:
        return "act"
    if kind in _RECOVER:
        return "recover"
    return None


def _order_key(rec: dict) -> tuple:
    # Span begins sort before same-ts members, ends after: the causal
    # guarantee trace ids give us inside one wall-clock domain.
    kind = rec.get("kind")
    order = 0 if kind == "span_begin" else (2 if kind == "span_end" else 1)
    ts = rec.get("ts")
    return (ts if isinstance(ts, (int, float)) else 0.0, order)


@dataclasses.dataclass
class _OpenIncident:
    incident_id: str
    trigger: str
    detail: str
    opened_ts: float
    fault_ts: float
    ranks: list
    decide_ts: Optional[float] = None
    act_ts: Optional[float] = None
    last_iteration_before: Optional[int] = None
    first_iteration_after: Optional[int] = None
    #: hang census captured at open time (``ElasticAgent.hang_census``): who
    #: was stuck where, which barriers were open, who never arrived
    census: Optional[dict] = None


class IncidentEngine:
    """Collects the fault window and writes the postmortem artifact.

    ``attach()`` registers the engine as an events sink: every local event
    lands in a bounded pre-buffer (fault-evidence lookback + fallback window
    when no shared events file exists). The shared JSONL named by
    ``$TPU_RESILIENCY_EVENTS_FILE`` — which carries *every* process's records —
    is read at close time and preferred for the artifact window.
    """

    def __init__(
        self,
        incidents_dir: str,
        *,
        node_id: str = "",
        events_file: Optional[str] = None,
        flight_dir: Optional[str] = None,
        auto_open: bool = False,
    ):
        self.incidents_dir = incidents_dir
        self.node_id = node_id
        self.events_file = events_file if events_file is not None else (
            os.environ.get(events_mod.EVENTS_FILE_ENV) or None
        )
        #: flight dumps live beside the incident artifacts by default — one
        #: directory to ship to the operator
        self.flight_dir = flight_dir or incidents_dir
        self.auto_open = auto_open
        os.makedirs(incidents_dir, exist_ok=True)
        self._prebuffer: deque[dict] = deque(maxlen=MAX_WINDOW_EVENTS)
        self._open: Optional[_OpenIncident] = None
        self._attached = False
        self._seq = 0
        #: artifact paths written this engine's lifetime (tests/operators)
        self.artifacts: list[str] = []

    # -- sink ---------------------------------------------------------------

    def attach(self) -> None:
        if not self._attached:
            events_mod.add_sink(self._sink)
            self._attached = True

    def detach(self) -> None:
        if self._attached:
            events_mod.remove_sink(self._sink)
            self._attached = False

    def _sink(self, event) -> None:
        # Flattened to the JSONL record shape so close-time merging treats
        # captured and file-read records identically.
        rec = {
            "ts": event.ts, "source": event.source, "kind": event.kind,
            "pid": event.pid, "rank": event.rank,
        }
        if event.trace_id is not None:
            rec["trace_id"] = event.trace_id
        if event.span_id is not None:
            rec["span_id"] = event.span_id
        for k, v in event.payload.items():
            rec[f"p_{k}" if k in events_mod.RESERVED_KEYS else k] = v
        self.observe(rec)

    def observe(self, rec: dict) -> None:
        """Feed one flattened record (sink entry; also callable from tests)."""
        if rec.get("kind") in ("incident_opened", "incident_closed"):
            return  # our own narration must not re-trigger us
        self._prebuffer.append(rec)
        inc = self._open
        if inc is not None:
            self._track_milestones(inc, rec)
            if self.auto_open and self._is_auto_close(rec):
                self.close(outcome="recovered", _closing_rec=rec)
            return
        if self.auto_open:
            trigger = self._auto_trigger(rec)
            if trigger is not None:
                self.open(
                    trigger, detail=str(rec.get("kind")),
                    ranks=self._ranks_of(rec),
                )

    @staticmethod
    def _auto_trigger(rec: dict) -> Optional[str]:
        kind = rec.get("kind")
        if kind == "degraded_set" and rec.get("newly"):
            return "degraded"
        if kind == "remediation_decision":
            return "remediation"
        if kind in ("ckpt_fallback", "ckpt_quarantined"):
            return str(kind)
        if kind in FAULT_KINDS:
            return str(kind)
        return None

    @staticmethod
    def _is_auto_close(rec: dict) -> bool:
        kind = rec.get("kind")
        if kind == "degraded_set" and rec.get("recovered") and not rec.get("newly"):
            return True
        if kind == "remediation_action" and rec.get("action") == "reinstate":
            return True
        return kind in ("round_succeeded", "completed", "training_finished")

    @staticmethod
    def _ranks_of(rec: dict) -> list:
        for key in ("newly", "global_rank", "ranks", "rank"):
            v = rec.get(key)
            if isinstance(v, list):
                return sorted(v)
            if isinstance(v, int):
                return [v]
        return []

    def _track_milestones(self, inc: _OpenIncident, rec: dict) -> None:
        phase = classify_phase(rec)
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            return
        if phase == "decide" and inc.decide_ts is None:
            inc.decide_ts = ts
        elif phase == "act" and inc.act_ts is None:
            inc.act_ts = ts
        if rec.get("kind") == "iteration_start" and isinstance(
            rec.get("iteration"), int
        ):
            inc.first_iteration_after = (
                rec["iteration"] if inc.first_iteration_after is None
                else min(inc.first_iteration_after, rec["iteration"])
            )

    # -- lifecycle ----------------------------------------------------------

    @property
    def is_open(self) -> bool:
        return self._open is not None

    def open(
        self,
        trigger: str,
        detail: str = "",
        ranks: Optional[list] = None,
        fault_ts: Optional[float] = None,
        census: Optional[dict] = None,
    ) -> str:
        """Open an incident (idempotent: a second fault folds into the open
        one). Returns the incident id. ``census``: an optional hang-census
        snapshot (per-rank locations, open barriers, suspects) embedded
        verbatim in the artifact."""
        if self._open is not None:
            if ranks:
                self._open.ranks = sorted(set(self._open.ranks) | set(ranks))
            if census is not None and self._open.census is None:
                self._open.census = census
            return self._open.incident_id
        now = time.time()
        if fault_ts is None:
            fault_ts = self._scan_fault_evidence(now)
        self._seq += 1
        incident_id = f"incident-{int(now * 1000)}-{self._seq}"
        self._open = _OpenIncident(
            incident_id=incident_id,
            trigger=trigger,
            detail=detail,
            opened_ts=now,
            fault_ts=min(fault_ts, now),
            ranks=sorted(ranks or []),
            census=census,
        )
        # Iterations seen before the fault — the steps-lost baseline.
        last_iter = None
        for rec in self._prebuffer:
            if rec.get("kind") == "iteration_start" and isinstance(
                rec.get("iteration"), int
            ):
                last_iter = rec["iteration"] if last_iter is None else max(
                    last_iter, rec["iteration"]
                )
        self._open.last_iteration_before = last_iter
        record_event(
            "incident", "incident_opened",
            incident_id=incident_id, trigger=trigger, detail=detail,
            node_id=self.node_id, ranks=self._open.ranks,
            time_to_detect_s=round(now - self._open.fault_ts, 6),
        )
        log.warning(
            f"incident {incident_id} opened: {trigger}"
            + (f" ({detail})" if detail else "")
        )
        return incident_id

    def _scan_fault_evidence(self, now: float) -> float:
        """Earliest fault-evidence timestamp in the lookback window (the
        time-to-detect anchor); the open time when no evidence was captured."""
        earliest = now
        for rec in self._prebuffer:
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)) or ts < now - FAULT_LOOKBACK_S:
                continue
            if classify_phase(rec) == "detect" and ts < earliest:
                earliest = ts
        return earliest

    def close(
        self,
        outcome: str = "recovered",
        resumed_iteration: Optional[int] = None,
        _closing_rec: Optional[dict] = None,
    ) -> Optional[str]:
        """Close the open incident and write its artifact. Returns the
        artifact path (None when no incident was open)."""
        inc = self._open
        if inc is None:
            return None
        self._open = None
        now = time.time()
        if resumed_iteration is not None:
            inc.first_iteration_after = resumed_iteration
        window = self._window(inc, now)
        if _closing_rec is not None and _closing_rec not in window:
            window.append(_closing_rec)
        window.sort(key=_order_key)
        chain = self._chain(window, inc)
        slo = self._slo(inc, now, chain)
        flights = self._flights()
        artifact = {
            "schema": SCHEMA,
            "id": inc.incident_id,
            "trigger": inc.trigger,
            "detail": inc.detail,
            "node_id": self.node_id,
            "trace_id": self._dominant_trace(window),
            "outcome": outcome,
            "ranks": inc.ranks,
            "opened_ts": inc.opened_ts,
            "closed_ts": now,
            "fault_ts": inc.fault_ts,
            "slo": slo,
            "chain": chain,
            "census": inc.census,
            "events": window[-MAX_WINDOW_EVENTS:],
            "flight": flights,
        }
        path = os.path.join(self.incidents_dir, f"{inc.incident_id}.json")
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(artifact, f, indent=2, default=repr)
                f.write("\n")
            os.replace(tmp, path)
        except OSError as e:
            log.error(f"cannot write incident artifact {path!r}: {e}")
            path = None
        record_event(
            "incident", "incident_closed",
            incident_id=inc.incident_id, trigger=inc.trigger, outcome=outcome,
            node_id=self.node_id, artifact=path, **slo,
        )
        log.warning(
            f"incident {inc.incident_id} closed ({outcome}): "
            f"detect={slo['time_to_detect_s']}s decide={slo['time_to_decide_s']}s "
            f"recover={slo['time_to_recover_s']}s steps_lost={slo['steps_lost']}"
        )
        if path is not None:
            self.artifacts.append(path)
        return path

    # -- artifact assembly ---------------------------------------------------

    def _window(self, inc: _OpenIncident, now: float) -> list[dict]:
        """The incident's event window: the shared JSONL when available
        (every process's records), the local pre-buffer otherwise — sliced to
        [fault - PRE_WINDOW_S, close] and to this run's trace."""
        lo = inc.fault_ts - PRE_WINDOW_S
        recs: list[dict] = []
        if self.events_file:
            # Stream-filtered at read time: the shared file can span many
            # runs/days and must never be materialized whole at close.
            recs = read_events(self.events_file, since=lo, until=now)
        if not recs:
            recs = [
                r for r in self._prebuffer
                if isinstance(r.get("ts"), (int, float)) and lo <= r["ts"] <= now
            ]
        # Dominant trace over the window only — a longer earlier run sharing
        # the stream must not out-vote this incident's own events.
        trace = self._dominant_trace(recs)
        out = []
        for r in recs:
            if trace and r.get("trace_id") not in (None, trace):
                continue  # another run sharing the stream
            if r.get("kind") in ("incident_opened", "incident_closed"):
                continue
            out.append(r)
        return out

    @staticmethod
    def _dominant_trace(recs: list[dict]) -> Optional[str]:
        counts = Counter(
            r["trace_id"] for r in recs if isinstance(r.get("trace_id"), str)
        )
        return counts.most_common(1)[0][0] if counts else None

    @staticmethod
    def _chain(window: list[dict], inc: _OpenIncident) -> list[dict]:
        chain = []
        for r in window:
            phase = classify_phase(r)
            if phase is None:
                continue
            chain.append({
                "phase": phase,
                "ts": r.get("ts"),
                "kind": r.get("kind"),
                "source": r.get("source"),
                "rank": r.get("rank"),
                "pid": r.get("pid"),
                "summary": _summarize(r),
            })
        return chain

    def _slo(self, inc: _OpenIncident, closed_ts: float, chain: list[dict]) -> dict:
        decide_ts = inc.decide_ts
        act_ts = inc.act_ts
        recover_ts: Optional[float] = None
        for m in chain:
            ts = m.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            if decide_ts is None and m["phase"] == "decide" and ts >= inc.fault_ts:
                decide_ts = ts
            if act_ts is None and m["phase"] == "act" and ts >= inc.fault_ts:
                act_ts = ts
            if m["phase"] == "recover":
                recover_ts = ts if recover_ts is None else max(recover_ts, ts)
        if recover_ts is None:
            recover_ts = closed_ts
        steps_lost = None
        if (
            inc.last_iteration_before is not None
            and inc.first_iteration_after is not None
        ):
            steps_lost = max(0, inc.last_iteration_before - inc.first_iteration_after)
        return {
            "time_to_detect_s": round(max(0.0, inc.opened_ts - inc.fault_ts), 6),
            "time_to_decide_s": (
                round(max(0.0, decide_ts - inc.opened_ts), 6)
                if decide_ts is not None else None
            ),
            "time_to_act_s": (
                round(max(0.0, act_ts - inc.opened_ts), 6)
                if act_ts is not None else None
            ),
            "time_to_recover_s": round(max(0.0, recover_ts - inc.fault_ts), 6),
            "steps_lost": steps_lost,
        }

    def _flights(self) -> dict[str, list[dict]]:
        try:
            dumps = flight_recorder.collect(self.flight_dir)
        except Exception:
            return {}
        return {
            ident: records[-MAX_FLIGHT_RECORDS:]
            for ident, records in dumps.items()
        }


def _summarize(rec: dict) -> str:
    """One short human line per chain milestone (mirrors events_summary)."""
    kind = rec.get("kind")
    if kind == "worker_failed":
        return (
            f"rank {rec.get('global_rank')} failed: "
            f"{rec.get('detail', rec.get('exitcode'))}"
        )
    if kind == "degraded_set":
        return (
            f"degraded={rec.get('degraded')} +{rec.get('newly')} "
            f"-{rec.get('recovered')}"
        )
    if kind == "straggler_report":
        return f"stragglers by perf: {rec.get('stragglers_by_perf')}"
    if kind == "remediation_decision":
        return f"plan={rec.get('plan')} for ranks {rec.get('newly')}"
    if kind == "remediation_action":
        return (
            f"{rec.get('action')} -> {rec.get('outcome')}"
            f" (ranks {rec.get('ranks')})"
        )
    if kind == "restart_requested":
        return f"restart requested: {rec.get('reason')}"
    if kind == "rendezvous_round":
        return f"round {rec.get('round')} world={rec.get('world_size')}"
    if kind == "round_succeeded":
        return f"round {rec.get('round')} succeeded"
    if kind == "kill_ladder":
        return f"step {rec.get('step')} -> rank {rec.get('global_rank')}"
    if kind == "ckpt_fallback":
        return (
            f"fallback {rec.get('from_iteration')} -> {rec.get('to_iteration')}"
        )
    if kind == "flight_flush":
        return f"flight dump: {rec.get('reason')}"
    payload = {
        k: v for k, v in rec.items()
        if k not in events_mod.RESERVED_KEYS and k != "kind"
    }
    return " ".join(f"{k}={v}" for k, v in list(payload.items())[:6])


def read_incident(path: str) -> dict:
    """Parse and schema-check one incident artifact (raises ValueError)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
        raise ValueError(f"{path}: not a {SCHEMA} artifact")
    for key in ("id", "trigger", "outcome", "slo", "chain", "events"):
        if key not in doc:
            raise ValueError(f"{path}: missing {key!r}")
    return doc
