"""``tpu-ft-launcher`` CLI: the fault-tolerant elastic launcher.

Analogue of the reference's ``ft_launcher`` console script
(``fault_tolerance/launcher.py:2065 main``, CLI surface ``:739 LaunchConfig``): spawns
``--nproc-per-node`` workers per host under a per-host elastic agent with per-rank
hang monitors, restarts on failure up to ``--max-restarts``, supports elastic
``--nnodes MIN:MAX`` with spares and optional upscaling, ``--restart-policy
{any-failed,min-healthy}``, YAML fault-tolerance config with ``--ft-param-*``
overrides (``config.py:144``), and per-round/per-rank log capture.

Store hosting: the agent whose ``--rdzv-endpoint`` port is free on the local machine
binds the coordination KVServer itself (rank-0-hosts pattern); everyone else connects
as a client. A multi-host job therefore needs no separate store daemon — start the
first agent on the endpoint host.

Example::

    tpu-ft-launcher --nproc-per-node 4 --nnodes 2:3 \\
        --rdzv-endpoint host0:29511 --max-restarts 5 train.py --lr 3e-4
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from tpu_resiliency.launcher.agent import AgentConfig, ElasticAgent, WorkersFailed
from tpu_resiliency.platform.store import (
    AUTH_KEY_ENV,
    CoordStore,
    KVServer,
    store_answers,
)
from tpu_resiliency.utils.events import EVENTS_FILE_ENV, METRICS_FILE_ENV
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.tracing import ensure_trace_id, span
from tpu_resiliency.watchdog.config import FaultToleranceConfig

log = get_logger(__name__)

STORE_PREFIX = "launcher/"


def parse_nnodes(spec: str) -> tuple[int, int]:
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return int(lo), int(hi)
    n = int(spec)
    return n, n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-ft-launcher",
        description="Fault-tolerant elastic launcher for TPU training workloads.",
        allow_abbrev=False,
    )
    p.add_argument("--nproc-per-node", type=int, default=1)
    # None defaults let the conflict check distinguish "omitted" from "typed the
    # default value" — main() fills in '1' / '127.0.0.1:29511'.
    p.add_argument(
        "--nnodes",
        default=None,
        help="node count, fixed ('2') or elastic range ('MIN:MAX'); surplus joiners "
        "become spares (the reference's redundancy list); default 1",
    )
    p.add_argument(
        "--rdzv-endpoint", default=None,
        help="host:port of the store (default 127.0.0.1:29511)",
    )
    p.add_argument(
        "--rdzv-id",
        default="default",
        help="job identity namespacing the coordination state: two jobs sharing "
        "one store server never see each other's rendezvous (reference --rdzv-id)",
    )
    p.add_argument(
        "--store-shards", type=int, default=1,
        help="host the coordination store as a clique of N server processes "
        "(shard 0 on the endpoint port) with the keyspace hash-partitioned "
        "client-side (crc32(key) %% N); workers and monitors inherit the "
        "clique via $TPU_RESILIENCY_STORE_SHARDS, and barriers/watch-parks "
        "stay shard-local because a name hashes to one shard. 1 (default) "
        "keeps today's single in-process server",
    )
    p.add_argument(
        "--store-replicate", action="store_true",
        help="HA clique: every key is written to its home shard AND the "
        "successor shard ((h+1) %% N), so a SIGKILL'd shard's keyspace — "
        "barriers included — stays servable from the successor while the "
        "clique is degraded; clients fail over automatically once the "
        "shard's circuit breaker opens. Descendants inherit via "
        "$TPU_RESILIENCY_STORE_REPLICATE. No effect with --store-shards 1 "
        "(successor == primary: the degenerate clique replicates nothing)",
    )
    p.add_argument(
        "--store-auto-reshard", action="store_true",
        help="automatic shard respawn for a job-hosted store clique: the "
        "launcher watches each shard's process + circuit-breaker telemetry "
        "and, when one stays dead past a grace window, spawns a replacement "
        "KVServer and drives reshard_clique onto the healed map (audited as "
        "store_auto_reshard events); operator-initiated resharding is "
        "unchanged. No effect unless this launcher hosts the clique "
        "(--store-shards > 1)",
    )
    p.add_argument(
        "--standalone",
        action="store_true",
        help="single-node convenience: host the store on an ephemeral local port "
        "and pin --nnodes 1 (reference --standalone)",
    )
    p.add_argument("--node-id", default="", help="stable node identity (default: generated)")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument(
        "--restart-policy", choices=("any-failed", "min-healthy"), default="any-failed"
    )
    p.add_argument("--monitor-interval", type=float, default=0.5)
    p.add_argument(
        "--rdzv-last-call",
        type=float,
        default=1.0,
        help="seconds the leader holds a rendezvous round open after min nodes arrive",
    )
    p.add_argument(
        "--rdzv-keep-alive-interval", type=float, default=2.0,
        help="agent keep-alive stamp period",
    )
    p.add_argument(
        "--rdzv-keep-alive-timeout", type=float, default=20.0,
        help="agents with keep-alives staler than this are treated as dead",
    )
    p.add_argument("--upscaling-enabled", action="store_true")
    p.add_argument(
        "--warm-spares",
        type=int,
        default=0,
        help="parked pre-imported interpreters kept warm per node; restart "
        "rounds promote one instead of paying interpreter+import startup "
        "(beats the reference's cold start_processes respawn path)",
    )
    p.add_argument(
        "--warm-spare-preload",
        default="jax",
        help="comma-separated modules each warm spare imports while parked",
    )
    p.add_argument(
        "--warm-spare-warmup",
        default="imports",
        help="park phase for warm spares: 'imports' (preloads only, default), "
        "'runtime' (platform-safe runtime warmup: plugin discovery, tracing "
        "machinery, CPU/loopback backend pre-init — device grabbing stays "
        "strictly post-promotion), or a custom 'module:function' spec; "
        "deeper-warmed spares are promoted first",
    )
    p.add_argument(
        "--compile-cache-dir",
        default=None,
        help="persistent XLA compilation cache shared across restart rounds "
        "(exports $JAX_COMPILATION_CACHE_DIR + "
        "$TPU_RESILIENCY_COMPILE_CACHE_DIR to workers): a respawned worker's "
        "first step loads the previous round's executables instead of "
        "re-tracing/re-compiling; corrupt entries are swept to a cold "
        "compile, never a crash",
    )
    p.add_argument(
        "--no-rdzv-fast-path",
        action="store_true",
        help="disable restart fast-path rendezvous (round reuse); replacement "
        "rounds always take the full open/join/close ladder",
    )
    p.add_argument(
        "--ckpt-coding",
        default=None,
        metavar="mirror|erasure[:parity]",
        help="checkpoint replication byte-economy (exports "
        "$TPU_RESILIENCY_CKPT_CODING; workers building their replication "
        "strategy via checkpoint.coding.replication_from_env pick it up): "
        "'mirror' full-mirrors every shard across the clique (default), "
        "'erasure' stores one Reed-Solomon block per peer instead — "
        "~(1+(m-1)/k)x the payload on the wire per save vs (n-1)x",
    )
    p.add_argument(
        "--ckpt-delta-interval",
        type=int,
        default=None,
        metavar="N",
        help="delta-checkpoint cycle (exports $TPU_RESILIENCY_CKPT_DELTA): "
        "between full keyframes, up to N-1 replication rounds ship only the "
        "chunks whose manifest CRCs changed since the previous save; 0/1 "
        "disables (mirror strategy only)",
    )
    p.add_argument(
        "--cold-dir",
        default=None,
        metavar="DIR",
        help="durable cold tier root (exports $TPU_RESILIENCY_COLD_DIR; "
        "workers' LocalCheckpointManager picks it up via "
        "checkpoint.coldtier.cold_from_env): finalized keyframe containers "
        "are spilled there asynchronously — off the save critical path — "
        "and a FRESH job with an empty workdir can bootstrap from it on any "
        "world size. A dead/full backend degrades to local-only "
        "(coldtier_degraded events), never a failed save",
    )
    p.add_argument(
        "--cold-keep",
        type=int,
        default=None,
        metavar="N",
        help="cold-tier retention: keep the newest N archived iterations "
        "(exports $TPU_RESILIENCY_COLD_KEEP); pruning is keyframe-aware — "
        "an iteration a retained delta chain names as its base is never "
        "orphaned. Default: keep everything",
    )
    p.add_argument("--term-grace", type=float, default=15.0)
    p.add_argument("--log-dir", default=None, help="capture per-round/per-rank worker logs")
    p.add_argument(
        "--events-file",
        default=None,
        help="JSONL structured-event stream shared by the agent and every worker "
        "(exports $TPU_RESILIENCY_EVENTS_FILE; default: inherit the env var)",
    )
    p.add_argument(
        "--metrics-file",
        default=None,
        help="bridge events into per-process metrics JSON snapshots at this "
        "path, '<pid>' inserted before the extension (exports "
        "$TPU_RESILIENCY_METRICS_FILE); post-hoc aggregation needs only "
        "--events-file + tpu-metrics-dump",
    )
    p.add_argument(
        "--incidents-dir",
        default=None,
        help="enable the incident plane: incident-<ts>.json postmortem "
        "artifacts land here, and every process keeps a crash-surviving "
        "flight-recorder ring in the same directory (exports "
        "$TPU_RESILIENCY_FLIGHT_DIR); render artifacts with "
        "tpu-incident-report",
    )
    p.add_argument(
        "--telemetry-port",
        type=int,
        default=None,
        help="serve live job telemetry from the agent: /metrics (merged "
        "job-level Prometheus view from rank-pushed snapshots), /goodput "
        "(time-attribution ledger), /healthz (agent health decision). "
        "0 binds an ephemeral port; the bound port is written to "
        "<run-dir>/telemetry.port (omit the flag to disable)",
    )
    p.add_argument(
        "--fleet-dir",
        default=None,
        help="fleet-federation discovery directory shared by every job the "
        "fleet aggregator (tpu-fleetd) watches: the agent registers its "
        "telemetry endpoint there as a heartbeat-refreshed lease file "
        "(removed on clean exit, expired by fleetd on staleness) and stamps "
        "this job's --rdzv-id onto every event ($TPU_RESILIENCY_JOB) so "
        "fleet-merged streams slice back per job; implies --telemetry-port 0 "
        "when telemetry is not otherwise enabled",
    )
    p.add_argument(
        "--autoscale",
        choices=("off", "advise", "act"),
        default="off",
        help="goodput-optimal autoscale controller (launcher/autoscale.py): "
        "consumes the goodput ledger, straggler scores, warm-spare depth, "
        "and preemption notices (incl. rescinds) and picks the goodput-"
        "maximizing action from an explicit cost model. 'advise' (the safe "
        "mode to start with) audits every decision as autoscale_decision "
        "events + the /autoscale endpoint without acting; 'act' routes "
        "decisions through the remediation actuators and restart rounds",
    )
    p.add_argument(
        "--alerts",
        choices=("off", "on"),
        default="on",
        help="SLO watchtower (telemetry/watchtower.py): burn-rate and "
        "anomaly alert rules evaluated over in-process time-series rings "
        "fed from the shared events stream, served at GET /alerts and "
        "folded into /snapshot. Needs telemetry enabled to matter. Rule "
        "overrides via $TPU_RESILIENCY_ALERT_RULES (JSON file)",
    )
    p.add_argument("--run-dir", default="", help="scratch dir for sockets/error files")
    p.add_argument("--ft-cfg-path", default=None, help="YAML with a fault_tolerance section")
    p.add_argument("--no-ft-monitors", action="store_true", help="disable per-rank hang monitors")
    p.add_argument(
        "--no-python",
        action="store_true",
        help="run the script as a raw executable instead of through the interpreter",
    )
    p.add_argument(
        "--module",
        "-m",
        action="store_true",
        help="treat the positional as a python module (python -m NAME), "
        "reference --module",
    )
    p.add_argument("script", help="training script or module (plus its args)")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


#: launcher flags that take no value — keep in sync with build_parser(); needed to
#: find where the user's script starts without invoking argparse
_STORE_TRUE_FLAGS = {
    "--store-auto-reshard",
    "--store-replicate",
    "--upscaling-enabled",
    "--no-ft-monitors",
    "--no-python",
    "--no-rdzv-fast-path",
    "--module",
    "-m",
    "--standalone",
    "-h",
    "--help",
}


def split_at_script(argv: list[str]) -> tuple[list[str], list[str]]:
    """Split argv into (launcher args, script + script args): the script is the
    first token that is neither an option nor an option's value."""
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("-"):
            i += 1 if (a in _STORE_TRUE_FLAGS or "=" in a) else 2
        else:
            return argv[:i], argv[i:]
    return argv, []


def extract_ft_params(argv: list[str]) -> tuple[list[str], argparse.Namespace]:
    """Pull dynamic ``--ft-param-<field>[=| ]<value>`` options out of the *launcher's*
    portion of argv (reference's ``--ft-param-*`` namespace, ``config.py:144``).
    Tokens at or after the script name are left untouched — a ``--ft-param-*`` flag
    there belongs to the user's script, not to us."""
    head, tail = split_at_script(argv)
    rest: list[str] = []
    ns = argparse.Namespace()
    i = 0
    while i < len(head):
        arg = head[i]
        if arg.startswith("--ft-param-"):
            body = arg[len("--ft-param-") :]
            if "=" in body:
                name, value = body.split("=", 1)
            else:
                name = body
                i += 1
                if i >= len(head):
                    raise SystemExit(f"--ft-param-{name} requires a value")
                value = head[i]
            setattr(ns, f"ft_param_{name.replace('-', '_')}", value)
        else:
            rest.append(arg)
        i += 1
    return rest + tail, ns


def endpoint_is_local(host: str) -> bool:
    """Is the rendezvous endpoint this machine? Only then may we host the store —
    a free port elsewhere must NOT seed a second, split-brain store."""
    import socket as socketmod

    if host in ("", "localhost", "127.0.0.1", "0.0.0.0", "::1"):
        return True
    hostname = socketmod.gethostname()
    if host in (hostname, socketmod.getfqdn()):
        return True
    try:
        ep_ips = {ai[4][0] for ai in socketmod.getaddrinfo(host, None)}
    except OSError:
        return False
    local_ips = {"127.0.0.1", "::1"}
    try:
        local_ips |= {ai[4][0] for ai in socketmod.getaddrinfo(hostname, None)}
    except OSError:
        pass
    return bool(ep_ips & local_ips)


def host_or_connect_store(
    endpoint: str, rdzv_id: str = "default", store_shards: int = 1,
    store_replicate: bool = False,
):
    """Bind the KVServer on the endpoint port when the endpoint IS this machine and
    the port is free; otherwise connect as a client.

    First-local-agent-hosts: deterministic on one machine; in a multi-host job only
    agents on the endpoint host ever try to bind, so remote agents cannot form an
    isolated second store.

    ``store_shards > 1`` hosts a **clique** instead of one in-process server:
    N ``KVServer`` subprocesses (shard 0 on the endpoint port, the rest
    ephemeral), the spec exported via ``$TPU_RESILIENCY_STORE_SHARDS`` for
    every descendant and published on shard 0 under the reserved
    ``store-clique/endpoints`` key so late joiners handed only the classic
    endpoint reconnect as sharded clients instead of splitting the keyspace.
    Returns ``(store, server_or_clique_or_None, client_host, port)``; the
    store is a :class:`CoordStore` or a sharded
    :class:`~tpu_resiliency.platform.shardstore.CliqueStore` — identical
    ``StoreView`` surface either way."""
    from tpu_resiliency.exceptions import StoreError
    from tpu_resiliency.platform.shardstore import (
        CLIQUE_KEY,
        REPLICATE_ENV,
        SHARDS_ENV,
        SpawnedClique,
        connect_store,
        probe_clique_spec,
    )

    host, _, port_s = endpoint.partition(":")
    port = int(port_s or "29511")
    auth_key = os.environ.get(AUTH_KEY_ENV) or None
    server = None
    client_host = host or "127.0.0.1"
    clique_spec = os.environ.get(SHARDS_ENV, "").strip()
    if not clique_spec and endpoint_is_local(host):
        # A live store already answering on the port (another job on this
        # shared endpoint, or an externally hosted server) means connect NOW —
        # entering the bind path would stall in its EADDRINUSE retry window
        # before falling back to client mode. Probe loopback first (job-hosted
        # stores bind it), then the endpoint's own address (an external server
        # may bind only the machine's non-loopback interface).
        probe_hosts = ["127.0.0.1"]
        if host and host not in ("127.0.0.1", "localhost", "0.0.0.0"):
            probe_hosts.append(host)
        live_host = next(
            (
                h
                for h in probe_hosts
                if port != 0 and store_answers(h, port, auth_key=auth_key)
            ),
            None,
        )
        if live_host is not None:
            log.info(f"live coordination store on {live_host}:{port}; joining as client")
            client_host = live_host
            clique_spec = probe_clique_spec(live_host, port, auth_key=auth_key)
        else:
            if store_shards > 1:
                try:
                    bind_host = "0.0.0.0" if auth_key else "127.0.0.1"
                    adv_host = (
                        host if host not in ("", "localhost", "0.0.0.0")
                        else "127.0.0.1"
                    )
                    server = SpawnedClique(
                        store_shards, host=bind_host, first_port=port,
                        advertise_host=adv_host if auth_key else "127.0.0.1",
                    )
                    port = server.port
                    client_host = "127.0.0.1"
                    clique_spec = server.spec
                    log.info(
                        f"hosting coordination store clique "
                        f"({store_shards} shards): {clique_spec}"
                    )
                except StoreError as e:
                    log.warning(
                        f"store clique spawn failed ({e}); falling back to a "
                        f"single in-process server"
                    )
                    server = None
            if server is None:
                try:
                    bind_host = "0.0.0.0" if auth_key else "127.0.0.1"
                    server = KVServer(host=bind_host, port=port, auth_key=auth_key)
                    port = server.port  # resolves port 0 → the ephemeral port actually bound
                    log.info(f"hosting coordination store on :{port}")
                    client_host = "127.0.0.1"
                except OSError:
                    client_host = "127.0.0.1"
    elif not clique_spec and port != 0:
        # Remote endpoint: one probe tells us whether it fronts a clique.
        clique_spec = probe_clique_spec(client_host, port, auth_key=auth_key)
    if clique_spec:
        # Every process we spawn (agents are in-process, workers/monitors
        # inherit the environment) must route through the same shard map.
        os.environ[SHARDS_ENV] = clique_spec
    if store_replicate and clique_spec:
        # Successor replication is a CLIENT-side discipline: descendants must
        # all double-write or the replica keyspace develops holes, so the
        # flag rides the environment the same way the shard spec does.
        os.environ[REPLICATE_ENV] = "1"
    # rdzv_id namespaces every launcher key: two jobs sharing one store server
    # never see each other's rendezvous/agent state (reference --rdzv-id).
    prefix = STORE_PREFIX + (f"{rdzv_id}/" if rdzv_id != "default" else "")
    store = connect_store(
        client_host, port, prefix=prefix, auth_key=auth_key, shards=clique_spec
    )
    if isinstance(server, SpawnedClique):
        # Publish the spec for late joiners (raw key on shard 0 — the clique
        # client routes CLIQUE_KEY wherever it hashes, so write it through a
        # direct shard-0 connection).
        shard0 = CoordStore(client_host, port, auth_key=auth_key)
        try:
            shard0.set(CLIQUE_KEY, clique_spec)
        finally:
            shard0.close()
    return store, server, client_host, port


def main(argv: Optional[list[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    argv, ft_ns = extract_ft_params(argv)
    args = build_parser().parse_args(argv)

    base_ft = (
        FaultToleranceConfig.from_yaml_file(args.ft_cfg_path)
        if args.ft_cfg_path
        else FaultToleranceConfig()
    )
    ft_cfg = FaultToleranceConfig.from_args(ft_ns, base=base_ft)

    # Flag validation first: reject before ANY side effects (env mutation,
    # store hosting).
    if args.module and args.no_python:
        log.error("--module and --no-python are mutually exclusive")
        return 2
    try:
        nnodes_spec = parse_nnodes(args.nnodes) if args.nnodes is not None else (1, 1)
    except ValueError:
        log.error(f"invalid --nnodes spec {args.nnodes!r}: want N or MIN:MAX")
        return 2
    if args.standalone:
        # Silently discarding an explicit endpoint would strand the other nodes
        # at a rendezvous this job never joins. Explicitness (not the literal
        # value) decides: typing the default endpoint still conflicts, while any
        # --nnodes spec meaning exactly one node ('1', '1:1') is consistent.
        if args.rdzv_endpoint is not None:
            log.error("--standalone conflicts with explicit --rdzv-endpoint")
            return 2
        if nnodes_spec != (1, 1):
            log.error("--standalone requires a single node (--nnodes 1)")
            return 2
    if args.rdzv_endpoint is None:
        args.rdzv_endpoint = "127.0.0.1:29511"
    if args.nnodes is None:
        args.nnodes = "1"

    if args.events_file:
        # One exported variable wires the whole tree: the agent records through it
        # and every spawned worker/monitor inherits it (events.py env sink).
        os.environ[EVENTS_FILE_ENV] = os.path.abspath(args.events_file)
    if args.fleet_dir:
        from tpu_resiliency.utils.events import JOB_ENV

        # Fleet scope: stamp the job identity onto every event this process
        # tree records, so streams several jobs share (or fleetd later
        # merges) slice back to one job with --job.
        os.environ[JOB_ENV] = args.rdzv_id
    if args.metrics_file:
        os.environ[METRICS_FILE_ENV] = os.path.abspath(args.metrics_file)
    if args.ckpt_coding:
        from tpu_resiliency.checkpoint.coding import CODING_ENV

        os.environ[CODING_ENV] = args.ckpt_coding
    if args.ckpt_delta_interval is not None:
        from tpu_resiliency.checkpoint.coding.delta import DELTA_ENV

        os.environ[DELTA_ENV] = str(args.ckpt_delta_interval)
    if args.cold_dir:
        from tpu_resiliency.checkpoint.coldtier import COLD_DIR_ENV, COLD_KEEP_ENV

        # One exported variable wires the whole tree, like the coding knobs:
        # every worker's LocalCheckpointManager builds its ColdTier from it
        # (checkpoint.coldtier.cold_from_env) — spills ride save-finalize,
        # restores grow the coverage ladder's cold rung.
        os.environ[COLD_DIR_ENV] = os.path.abspath(args.cold_dir)
        os.makedirs(os.path.abspath(args.cold_dir), exist_ok=True)
        if args.cold_keep is not None:
            os.environ[COLD_KEEP_ENV] = str(args.cold_keep)
    elif args.cold_keep is not None:
        log.warning("--cold-keep has no effect without --cold-dir")
    if args.compile_cache_dir:
        from tpu_resiliency.platform import compile_cache

        cache_dir = os.path.abspath(args.compile_cache_dir)
        # Both exports on purpose: TPU_RESILIENCY_* drives this package's
        # integrity sweep + compile_cache event in workers that import it;
        # JAX_COMPILATION_CACHE_DIR makes plain-JAX workers (no tpu_resiliency
        # import) cache too. Sweep HERE, before any worker starts, so a cache
        # corrupted between jobs is purged exactly once up front.
        os.environ[compile_cache.CACHE_DIR_ENV] = cache_dir
        os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        os.makedirs(cache_dir, exist_ok=True)
        swept = compile_cache.sweep(cache_dir)
        if swept.get("purged"):
            log.warning(
                f"compile cache sweep purged {swept['purged']} corrupt "
                f"entries from {cache_dir} (cold compiles will follow)"
            )
    # Trace identity rides the same single-export pattern: mint here (the root
    # of the process tree) so every agent/worker/monitor event shares one
    # trace_id and spans stitch cross-process (tools/trace_export.py).
    ensure_trace_id()

    if args.standalone:
        # Single-node convenience (reference --standalone): private ephemeral
        # store, one node — no rendezvous configuration needed.
        args.rdzv_endpoint = "127.0.0.1:0"
        args.nnodes = "1"
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    store, server, store_host, store_port = host_or_connect_store(
        args.rdzv_endpoint, rdzv_id=args.rdzv_id,
        store_shards=max(1, args.store_shards),
        store_replicate=bool(args.store_replicate),
    )
    # Cross-job registry OUTSIDE any rdzv-id namespace: which jobs are on this
    # endpoint. Powers the hosted-store teardown warning (a job-hosted server
    # dies with its job; other --rdzv-id jobs need to know why they lost it).
    import time as time_mod
    import uuid

    from tpu_resiliency.platform.shardstore import connect_store as _connect_store

    jobs_reg = _connect_store(
        store_host, store_port, prefix="launcher-jobs/",
        auth_key=os.environ.get(AUTH_KEY_ENV) or None,
    )
    job_token = f"{args.rdzv_id}/{uuid.uuid4().hex[:8]}"
    try:
        jobs_reg.set(job_token, time_mod.time())
    except Exception:
        pass
    # Workers reach the store through the agent-visible address: if we host it,
    # that's this machine; remote workers of other agents use their agent's view.
    endpoint_host = args.rdzv_endpoint.partition(":")[0] or "127.0.0.1"
    worker_store_host = "127.0.0.1" if server is not None else endpoint_host

    worker_argv = [args.script] + list(args.script_args)
    if args.module:
        worker_argv = ["-m"] + worker_argv
    cfg = AgentConfig(
        argv=worker_argv,
        nproc_per_node=args.nproc_per_node,
        min_nodes=min_nodes,
        max_nodes=max_nodes,
        node_id=args.node_id,
        max_restarts=args.max_restarts,
        restart_policy=args.restart_policy,
        monitor_interval=args.monitor_interval,
        last_call_timeout=args.rdzv_last_call,
        keep_alive_interval=args.rdzv_keep_alive_interval,
        keep_alive_timeout=args.rdzv_keep_alive_timeout,
        upscaling_enabled=args.upscaling_enabled,
        term_grace=args.term_grace,
        run_dir=args.run_dir,
        log_dir=args.log_dir,
        use_python=not args.no_python,
        enable_ft_monitors=not args.no_ft_monitors,
        store_host=worker_store_host,
        store_port=store_port,
        warm_spares=args.warm_spares,
        warm_spare_preload=args.warm_spare_preload,
        warm_spare_warmup=args.warm_spare_warmup,
        rdzv_fast_path=not args.no_rdzv_fast_path,
        incidents_dir=(
            os.path.abspath(args.incidents_dir) if args.incidents_dir else ""
        ),
        telemetry_port=args.telemetry_port,
        fleet_dir=os.path.abspath(args.fleet_dir) if args.fleet_dir else "",
        job_id=args.rdzv_id,
        autoscale=args.autoscale,
        alerts=args.alerts,
        # rdzv-id namespacing keeps two jobs on one store endpoint from
        # merging each other's metrics snapshots into their /metrics views.
        metrics_push_prefix=f"jobmetrics/{args.rdzv_id}/",
    )
    agent = ElasticAgent(cfg, ft_cfg, store)
    auto_reshard = None
    if args.store_auto_reshard:
        from tpu_resiliency.platform.shardstore import (
            AutoReshardSupervisor,
            CliqueStore,
            SpawnedClique,
        )

        if isinstance(server, SpawnedClique) and isinstance(store, CliqueStore):
            auto_reshard = AutoReshardSupervisor(server, store.client)
            auto_reshard.start()
            log.info(
                f"store auto-reshard supervisor watching "
                f"{len(server.endpoints)} shards"
            )
        else:
            log.warning(
                "--store-auto-reshard needs a job-hosted clique "
                "(--store-shards > 1); ignoring"
            )
    try:
        # The root span of the whole run: every round/rendezvous/worker span
        # parents (transitively) under it.
        with span("launcher", "launcher.job", node_id=cfg.node_id):
            exitcodes = agent.run()
        log.info(f"workload finished: exit codes {exitcodes}")
        return 0
    except WorkersFailed as e:
        log.error(f"workload failed: {e}")
        return 1
    finally:
        if auto_reshard is not None:
            auto_reshard.stop()
        if server is not None:
            # We host the control plane: closing it while peers still coordinate
            # would rip the store out from under them — wait for their exit marks.
            try:
                agent.rdzv.await_peers_exit()
            except Exception:
                pass
            # An in-process-hosted store dies with this job (same lifetime as
            # torchrun's agent-hosted c10d store). Other --rdzv-id jobs on this
            # endpoint cannot hold us open — warn so their failures aren't
            # mysterious; host the store externally
            # (python -m tpu_resiliency.platform.store) for multi-job endpoints.
            try:
                foreign = {
                    k.split("/")[0]
                    for k in jobs_reg.prefix_get("")
                    if not k.startswith(f"{args.rdzv_id}/")
                }
                if foreign:
                    log.warning(
                        f"closing the job-hosted store with other rdzv-id jobs "
                        f"still registered ({sorted(foreign)[:5]}); host the "
                        f"store externally to outlive this job"
                    )
            except Exception:
                pass
        try:
            jobs_reg.delete(job_token)
        except Exception:
            pass
        jobs_reg.close()
        store.close()
        if server is not None:
            server.close()


if __name__ == "__main__":
    sys.exit(main())
