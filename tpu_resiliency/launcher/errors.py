"""Worker error files: structured crash reports crossing the process boundary.

Analogue of the reference's torchelastic error-file machinery
(``_torch_elastic_compat/multiprocessing/errors/__init__.py:379`` ``@record``): the
launcher hands each worker a private JSON error-file path via
``$TPU_RESILIENCY_ERROR_FILE``; a ``@record``-wrapped main writes its traceback there
before dying, and the agent attaches the parsed payload to its failure report — so a
multi-node crash is diagnosed from the agent log alone, without grepping N worker logs.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import sys
import time
import traceback
from typing import Any, Callable, Optional

ERROR_FILE_ENV = "TPU_RESILIENCY_ERROR_FILE"


@dataclasses.dataclass
class WorkerError:
    message: str
    exception_type: str = ""
    traceback: str = ""
    pid: int = 0
    timestamp: float = 0.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_file(cls, path: str) -> Optional["WorkerError"]:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


def write_error_file(exc: BaseException, path: Optional[str] = None) -> None:
    path = path or os.environ.get(ERROR_FILE_ENV)
    if not path:
        return
    err = WorkerError(
        message=str(exc),
        exception_type=type(exc).__name__,
        traceback="".join(traceback.format_exception(type(exc), exc, exc.__traceback__)),
        pid=os.getpid(),
        timestamp=time.time(),
    )
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(err.to_json())
    except OSError:
        pass


def record(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Decorate a worker ``main`` so uncaught exceptions land in the error file
    (and still propagate). SystemExit with code 0 is not an error."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except SystemExit as e:
            if e.code not in (0, None):
                write_error_file(e)
            raise
        except BaseException as e:
            write_error_file(e)
            raise

    return wrapper


def main_guard(fn: Callable[[], Any]) -> None:
    """Run ``fn`` as a worker entry point: record + non-zero exit on failure."""
    try:
        record(fn)()
    except SystemExit:
        raise
    except BaseException:
        traceback.print_exc()
        sys.exit(1)
