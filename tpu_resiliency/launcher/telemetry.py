"""Live telemetry endpoint on the launcher agent.

Everything the observability plane records was, until this module, offline:
metrics lived in per-rank files and every CLI replayed a finished JSONL. The
:class:`TelemetryServer` turns the launcher into the job's scrape target — a
stdlib ``http.server`` thread on an ephemeral port with a port-file handshake
(no port flag coordination; a sidecar reads ``telemetry.port`` out of the run
dir), serving three endpoints:

- ``GET /metrics`` — the **merged job-level** Prometheus view: the launcher's
  own registry folded together with every rank-published snapshot from the
  coordination store (``utils/metrics.py:MetricsPublisher`` push path +
  ``MetricsRegistry.merge``). One scrape answers for N ranks; no scraper ever
  opens a rank's files.
- ``GET /goodput`` — the goodput ledger's attribution document as JSON
  (``utils/goodput.py``): wall clock classified into train / ckpt_stall /
  restart / incident / unattributed, goodput ratio, per-rank rows. The ledger
  tails the shared events JSONL incrementally, so a scrape costs only the
  bytes appended since the last one.
- ``GET /healthz`` — the agent's current health decision as JSON; HTTP 200
  when healthy, 503 when not (load-balancer / watchdog friendly).
- ``GET /hangz`` — the live blocked-collective census as JSON
  (``schema: tpu-hangz-1``): per-rank last-known location + stuck duration
  (from each rank's monitor), every open barrier round with its arrived /
  missing / absent ranks and waiter ages (the store's ``barrier_census``
  op), and ranked hang suspects — "who is stuck where, and who never
  arrived", while the job is still wedged.
- ``GET /autoscale`` — the autoscale controller's status document
  (``schema: tpu-autoscale-1``, ``launcher/autoscale.py``): mode, pending
  preemption notices, the recent decision audit with predicted AND realized
  goodput deltas, forecast accuracy, and the live cost-model constants.
- ``GET /metrics.json`` — the same merged job-level view as ``/metrics``, as
  a mergeable JSON snapshot (``MetricsRegistry.snapshot`` format): the
  federation input — fleetd folds these with ``MetricsRegistry.merge``
  instead of parsing exposition text.
- ``GET /incidents`` — recent ``tpu-incident-1`` artifact summaries from the
  incidents dir (``schema: tpu-incidents-1``; heavyweight fields — event
  window, flight dumps — trimmed to counts).
- ``GET /snapshot`` — the consolidated per-job document
  (``schema: tpu-job-snapshot-1``): metrics snapshot + goodput + health +
  hangz + incidents in ONE round trip, so a fleet scrape costs one GET per
  job (``tools/fleetd.py``).
- ``GET /storez`` — the coordination store's live self-telemetry document
  (``schema: tpu-storez-1``, wrapping the ``store_stats`` wire op's
  ``tpu-store-stats-1`` body): per-op latency with queue-wait/handle split,
  bytes in/out, connection counts, dedup hit rate, barrier park depth, hot
  key prefixes. Folded into ``/snapshot`` so fleetd gets it for free; a
  crashing collector degrades the document, never the endpoint.
- ``GET /alerts`` — the SLO watchtower's state document
  (``schema: tpu-alerts-1``, ``telemetry/watchtower.py``): the loaded rule
  table with per-rule state (ok / pending / firing / error), the
  severity-ranked active alerts, recent fire/resolve history, and the ring
  census. The watchtower rides the same incremental events tail as the
  ledgers, so every refresh advances its rings too; a crashing rule degrades
  to an error row on its rule entry, never a non-200. Folded into
  ``/snapshot`` so fleetd gets the fleet-wide alert feed for free.

``/healthz`` results are TTL-cached (``health_ttl``, default 1 s) behind a
lock, so a scrape storm from fleet pollers costs one ``health_fn``
evaluation per TTL instead of stacking concurrent runs.

**Fleet registration**: with ``fleet_dir`` set (launcher ``--fleet-dir``),
the server announces the job to the fleet control plane by writing an atomic
``tpu-fleet-lease-1`` lease file (job id, url, pid, started_at) into the
shared directory and heartbeat-refreshing it every ``lease_interval``
seconds; a clean ``stop()`` removes the lease, a crash lets it go stale and
fleetd expires it (``fleet/registry.py``) — the same announce/teardown
discipline as the ``telemetry.port`` handshake, shared-directory-wide.

Each ``/metrics`` or ``/goodput`` request also refreshes the ledger and
publishes attribution deltas back through the event stream
(``goodput_update`` → ``tpu_time_attributed_seconds_total{phase}`` /
``tpu_goodput_ratio``), so the Prometheus view and the post-hoc
``tpu-metrics-dump`` aggregation of the same JSONL stay in parity.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from tpu_resiliency.utils import events as events_mod
from tpu_resiliency.utils.goodput import GoodputLedger
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.metrics import MetricsRegistry, MetricsSink, observe_record

log = get_logger(__name__)

#: default name of the port-file handshake inside the launcher's run dir
PORT_FILE_NAME = "telemetry.port"


class TelemetryServer:
    """Threaded HTTP endpoint serving /metrics, /goodput, /healthz.

    ``fetch_snapshots`` returns the rank-published snapshot documents (the
    agent wires a store prefix scan); ``health_fn`` returns the health
    document (``{"healthy": bool, ...}``). Both are optional — without them
    the server still serves the launcher-local registry and the ledger.
    """

    def __init__(
        self,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        port_file: Optional[str] = None,
        events_file: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        fetch_snapshots: Optional[Callable[[], list]] = None,
        health_fn: Optional[Callable[[], dict]] = None,
        census_fn: Optional[Callable[[], dict]] = None,
        autoscale_fn: Optional[Callable[[], dict]] = None,
        store_stats_fn: Optional[Callable[[], dict]] = None,
        health_ttl: float = 1.0,
        fleet_dir: Optional[str] = None,
        job: str = "default",
        node_id: str = "",
        incidents_dir: Optional[str] = None,
        lease_interval: float = 5.0,
        snapshot_ttl: float = 1.0,
        watchtower=None,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.ledger = GoodputLedger()
        # The byte-flow ledger rides the same incremental tail as the goodput
        # ledger, so the live tpu_byteflow_* view and a post-hoc
        # `tpu-metrics-dump --bytes` of the same stream agree.
        from tpu_resiliency.utils.byteflow import ByteFlowLedger

        self.byteflow = ByteFlowLedger()
        self._host = host
        self._want_port = port
        self.port_file = port_file
        self.events_file = events_file
        self.fetch_snapshots = fetch_snapshots
        self.health_fn = health_fn
        self.census_fn = census_fn
        self.autoscale_fn = autoscale_fn
        self.store_stats_fn = store_stats_fn
        #: SLO watchtower (``telemetry/watchtower.py``): fed from the same
        #: events tail the ledgers ride; None keeps /alerts degraded-but-200.
        self.watchtower = watchtower
        #: fleet discovery (``fleet/registry.py``): directory the job's lease
        #: lives in; None keeps the server single-job (no registration).
        self.fleet_dir = fleet_dir
        self.job = job or "default"
        self.node_id = node_id
        self.incidents_dir = incidents_dir
        self.lease_interval = lease_interval
        self._lease = None
        self._lease_stop = threading.Event()
        self._lease_thread: Optional[threading.Thread] = None
        #: /snapshot body cache lifetime: the consolidated document is the
        #: fleet-scrape hot path, and several fleetds / dashboards polling one
        #: job must cost one ledger-refresh + registry-merge + serialize per
        #: TTL, not one per scraper (the /healthz discipline, one level up).
        #: 0 disables caching (computation still serializes under the lock).
        self.snapshot_ttl = snapshot_ttl
        self._snapshot_lock = threading.Lock()
        self._snapshot_cache: Optional[tuple[float, bytes]] = None
        #: /healthz result cache lifetime: a scrape storm (fleet pollers all
        #: hitting one launcher) must not stack concurrent health_fn runs.
        #: 0 disables caching (computation still serializes under the lock).
        self.health_ttl = health_ttl
        self._health_lock = threading.Lock()
        self._health_cache: Optional[tuple[float, dict]] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        #: byte offset of the last complete line consumed from events_file
        self._offset = 0
        self._refresh_lock = threading.Lock()
        self._sink: Optional[MetricsSink] = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> int:
        """Bind, write the port file, start serving. Returns the bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive: a fleet scraper's per-beat GET rides one persistent
            # connection (and one server-side handler thread) instead of
            # paying TCP setup + thread spawn per scrape. Every response
            # already carries Content-Length, which 1.1 requires.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # no stderr chatter
                log.debug(f"telemetry: {fmt % args}")

            def do_GET(self):
                try:
                    server._handle(self)
                except BrokenPipeError:
                    pass
                except Exception:
                    log.debug("telemetry request failed", exc_info=True)
                    try:
                        self.send_error(500)
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((self._host, self._want_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="telemetry-http", daemon=True
        )
        self._thread.start()
        # The launcher's own events feed the local half of the merged view.
        self._sink = MetricsSink(self.registry)
        events_mod.add_sink(self._sink)
        port = self._httpd.server_address[1]
        if self.port_file:
            d = os.path.dirname(self.port_file)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{self.port_file}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(f"{port}\n")
            os.replace(tmp, self.port_file)
        if self.fleet_dir:
            self._register_lease(port)
        if self.watchtower is not None:
            # The pump: alerts fire and resolve on schedule even when nobody
            # scrapes (refresh tails the events file into the watchtower).
            self.watchtower.start(poll_fn=self.refresh)
        log.info(f"telemetry endpoint on http://{self._host}:{port} "
                 f"(/metrics /goodput /healthz /hangz /autoscale /snapshot "
                 f"/storez /alerts)")
        return port

    def stop(self) -> None:
        if self.watchtower is not None:
            self.watchtower.stop()
        if self._lease_thread is not None:
            self._lease_stop.set()
            self._lease_thread.join(timeout=5.0)
            self._lease_thread = None
        if self._lease is not None:
            # Clean stop: the job disappears from the fleet view immediately
            # instead of lingering until heartbeat staleness.
            from tpu_resiliency.fleet.registry import remove_lease

            remove_lease(self._lease.path)
            self._lease = None
        if self._sink is not None:
            events_mod.remove_sink(self._sink)
            self._sink = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.port_file:
            try:
                os.unlink(self.port_file)
            except OSError:
                pass

    # -- fleet registration -------------------------------------------------

    def _register_lease(self, port: int) -> None:
        """Announce this job to the fleet dir and start the heartbeat. A
        registration failure degrades to single-job serving — discovery is
        observability, never control flow."""
        from tpu_resiliency.fleet.registry import JobLease, write_lease

        self._lease = JobLease(
            job=self.job,
            url=f"http://{self._host}:{port}",
            pid=os.getpid(),
            node_id=self.node_id,
            started_at=time.time(),
        )
        try:
            write_lease(self.fleet_dir, self._lease)
        except OSError as e:
            log.warning(f"cannot register fleet lease in {self.fleet_dir!r}: {e}")
            self._lease = None
            return
        self._lease_stop.clear()
        self._lease_thread = threading.Thread(
            target=self._lease_loop, name="fleet-lease", daemon=True
        )
        self._lease_thread.start()

    def _lease_loop(self) -> None:
        while not self._lease_stop.wait(self.lease_interval):
            lease = self._lease
            if lease is None:
                return
            try:
                # Each refresh is a full atomic rewrite stamping a fresh
                # heartbeat_ts — fleetd treats a stale stamp as a dead job.
                from tpu_resiliency.fleet.registry import write_lease

                write_lease(self.fleet_dir, lease)
            except OSError:
                log.debug("fleet lease refresh failed", exc_info=True)

    # -- request handling ---------------------------------------------------

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?", 1)[0]
        if path == "/metrics":
            self.refresh()
            body = self.merged_registry().to_prometheus().encode()
            self._respond(req, 200, body, "text/plain; version=0.0.4")
        elif path == "/metrics.json":
            self.refresh()
            doc = self.merged_registry().snapshot()
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/incidents":
            self._respond(
                req, 200, _json_body(self._incidents_doc()), "application/json"
            )
        elif path == "/snapshot":
            self._respond(req, 200, self._snapshot_body(), "application/json")
        elif path == "/goodput":
            summary = self.refresh()
            self._respond(req, 200, _json_body(summary), "application/json")
        elif path == "/healthz":
            doc = self._health_doc()
            status = 200 if doc.get("healthy") else 503
            self._respond(req, status, _json_body(doc), "application/json")
        elif path == "/autoscale":
            if self.autoscale_fn is None:
                doc = {"schema": "tpu-autoscale-1", "mode": "off",
                       "error": "no autoscale controller wired"}
            else:
                try:
                    doc = dict(self.autoscale_fn())
                except Exception as e:
                    # A broken controller degrades the document, never the
                    # endpoint — same contract as /hangz.
                    doc = {"schema": "tpu-autoscale-1", "error": repr(e)}
            doc.setdefault("schema", "tpu-autoscale-1")
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/hangz":
            if self.census_fn is None:
                doc = {"schema": "tpu-hangz-1", "error": "no census source wired"}
            else:
                try:
                    doc = dict(self.census_fn())
                except Exception as e:
                    # A wedged store/monitor must degrade the census, not the
                    # endpoint — /hangz exists precisely for wedged moments.
                    doc = {"schema": "tpu-hangz-1", "error": repr(e)}
            doc.setdefault("schema", "tpu-hangz-1")
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/storez":
            self._respond(
                req, 200, _json_body(self._storez_doc()), "application/json"
            )
        elif path == "/alerts":
            self._respond(
                req, 200, _json_body(self._alerts_doc()), "application/json"
            )
        else:
            self._respond(
                req, 404,
                _json_body({"error": f"unknown path {path!r}",
                            "endpoints": ["/metrics", "/metrics.json",
                                          "/goodput", "/healthz", "/hangz",
                                          "/autoscale", "/incidents",
                                          "/snapshot", "/storez", "/alerts"]}),
                "application/json",
            )

    def _storez_doc(self) -> dict:
        """The /storez body (schema ``tpu-storez-1``): the coordination
        store's ``store_stats`` document wrapped with the job identity. A
        crashing collector — or a store that predates the op — degrades the
        document to an ``error`` field, never the endpoint (the /hangz
        contract: the forensics plane must answer during the incidents it
        exists for)."""
        doc: dict = {"schema": "tpu-storez-1", "job": self.job}
        if self.store_stats_fn is None:
            doc["error"] = "no store stats source wired"
            return doc
        try:
            doc.update(dict(self.store_stats_fn()))
        except Exception as e:
            doc["error"] = repr(e)
        doc["schema"] = "tpu-storez-1"
        return doc

    def _alerts_doc(self) -> dict:
        """The /alerts body (schema ``tpu-alerts-1``). The watchtower already
        contains crashing rules to error rows; this guard covers a wedged
        engine itself — the document degrades, never the endpoint. A refresh
        first, so a scrape sees alerts derived from every complete line the
        events file holds right now (same freshness contract as /goodput)."""
        if self.watchtower is None:
            return {"schema": "tpu-alerts-1", "job": self.job,
                    "error": "no watchtower wired"}
        try:
            self.refresh()
            doc = dict(self.watchtower.status())
        except Exception as e:
            doc = {"schema": "tpu-alerts-1", "error": repr(e)}
        doc.setdefault("schema", "tpu-alerts-1")
        doc.setdefault("job", self.job)
        return doc

    def _health_doc(self) -> dict:
        """The /healthz body, TTL-cached. Computation happens INSIDE the lock
        on purpose: two concurrent scrapes during a slow health_fn serialize,
        and the second returns the first's fresh result instead of running
        health_fn again — a scrape storm costs one evaluation per TTL."""
        with self._health_lock:
            now = time.monotonic()
            if (
                self._health_cache is not None
                and now - self._health_cache[0] < self.health_ttl
            ):
                return self._health_cache[1]
            doc = {"healthy": True}
            if self.health_fn is not None:
                try:
                    doc = dict(self.health_fn())
                except Exception as e:
                    doc = {"healthy": False, "error": repr(e)}
            self._health_cache = (time.monotonic(), doc)
            return doc

    #: incident feed length cap: the fleet wants the recent tail, not a
    #: job-lifetime archive (artifacts on disk remain the full record)
    INCIDENTS_LIMIT = 50

    def _incidents_doc(self) -> dict:
        """Recent incident-artifact summaries, newest first. Heavy forensic
        fields (event window, flight dumps, chain, census) are trimmed to
        counts — the fleet feed answers "what happened, when, how bad";
        ``tpu-incident-report`` against the artifact answers "why"."""
        doc: dict = {"schema": "tpu-incidents-1", "job": self.job, "incidents": []}
        if not self.incidents_dir:
            return doc
        try:
            names = [
                n for n in os.listdir(self.incidents_dir)
                if n.startswith("incident-") and n.endswith(".json")
            ]
        except OSError as e:
            doc["error"] = repr(e)
            return doc
        for name in sorted(names, reverse=True)[: self.INCIDENTS_LIMIT]:
            try:
                with open(os.path.join(self.incidents_dir, name)) as f:
                    art = json.load(f)
            except (OSError, json.JSONDecodeError, UnicodeDecodeError):
                continue  # torn/foreign file: skip, never degrade the feed
            if not isinstance(art, dict) or art.get("schema") != "tpu-incident-1":
                continue
            doc["incidents"].append({
                "id": art.get("id"),
                "trigger": art.get("trigger"),
                "detail": art.get("detail"),
                "outcome": art.get("outcome"),
                "ranks": art.get("ranks"),
                "node_id": art.get("node_id"),
                "opened_ts": art.get("opened_ts"),
                "closed_ts": art.get("closed_ts"),
                "fault_ts": art.get("fault_ts"),
                "slo": art.get("slo"),
                "events": len(art.get("events") or []),
                "chain": len(art.get("chain") or []),
                "flight_dumps": len(art.get("flight") or {}),
                "artifact": name,
            })
        doc["incidents"].sort(
            key=lambda i: -(i.get("opened_ts") if isinstance(
                i.get("opened_ts"), (int, float)) else 0.0)
        )
        return doc

    def snapshot_doc(self) -> dict:
        """The consolidated per-job document — one GET answers a fleet
        scrape (``schema: tpu-job-snapshot-1``). Every section degrades
        independently: a wedged census or crashed health_fn yields an error
        field in its section, never a failed snapshot."""
        goodput = self.refresh()
        doc: dict = {
            "schema": "tpu-job-snapshot-1",
            "job": self.job,
            "node_id": self.node_id,
            "pid": os.getpid(),
            "ts": time.time(),
            "metrics": self.merged_registry().snapshot(),
            "goodput": goodput,
            "health": self._health_doc(),
            "incidents": self._incidents_doc()["incidents"],
        }
        if self.census_fn is not None:
            try:
                doc["hangz"] = dict(self.census_fn())
            except Exception as e:
                doc["hangz"] = {"error": repr(e)}
            doc["hangz"].setdefault("schema", "tpu-hangz-1")
        if self.autoscale_fn is not None:
            try:
                doc["autoscale"] = dict(self.autoscale_fn())
            except Exception as e:
                doc["autoscale"] = {"error": repr(e)}
            doc["autoscale"].setdefault("schema", "tpu-autoscale-1")
        if self.store_stats_fn is not None:
            doc["storez"] = self._storez_doc()
        if self.watchtower is not None:
            try:
                doc["alerts"] = dict(self.watchtower.status())
            except Exception as e:
                doc["alerts"] = {"error": repr(e)}
            doc["alerts"].setdefault("schema", "tpu-alerts-1")
        return doc

    def _snapshot_body(self) -> bytes:
        """The /snapshot response bytes, TTL-cached. Compute-inside-the-lock
        like ``_health_doc``: concurrent fleet scrapers during a slow build
        serialize, and the laggards reuse the fresh bytes — rendered once,
        not once per scraper."""
        with self._snapshot_lock:
            now = time.monotonic()
            if (
                self._snapshot_cache is not None
                and now - self._snapshot_cache[0] < self.snapshot_ttl
            ):
                return self._snapshot_cache[1]
            body = _json_body(self.snapshot_doc())
            self._snapshot_cache = (time.monotonic(), body)
            return body

    @staticmethod
    def _respond(
        req: BaseHTTPRequestHandler, status: int, body: bytes, ctype: str
    ) -> None:
        req.send_response(status)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    # -- data assembly ------------------------------------------------------

    def refresh(self) -> dict:
        """Feed the ledger the events appended since the last refresh, then
        publish attribution deltas through the event stream (which lands in
        the local registry via the attached sink AND in the shared JSONL for
        post-hoc parity). Returns the current summary."""
        with self._refresh_lock:
            for rec in self._read_new_events():
                self.ledger.observe(rec)
                self.byteflow.observe(rec)
                if self.watchtower is not None:
                    # Same tail, same order — the watchtower's stream clock
                    # advances exactly as an offline replay of this file would.
                    self.watchtower.observe(rec)
            self.byteflow.publish()
            return self.ledger.publish()

    def _read_new_events(self) -> list[dict]:
        if not self.events_file:
            return []
        out: list[dict] = []
        try:
            with open(self.events_file, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return []
        if not chunk:
            return []
        # Only complete lines advance the offset: a torn trailing line is
        # re-read whole on the next refresh.
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        self._offset += end + 1
        for line in chunk[: end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    def merged_registry(self) -> MetricsRegistry:
        """Fold the launcher-local registry and every rank-published snapshot
        into one fresh job-level registry (the /metrics body)."""
        merged = MetricsRegistry()
        merged.merge(self.registry.snapshot())
        if self.fetch_snapshots is not None:
            try:
                snapshots = self.fetch_snapshots() or []
            except Exception:
                log.debug("snapshot fetch failed", exc_info=True)
                snapshots = []
            for snap in snapshots:
                try:
                    merged.merge(snap)
                except (ValueError, TypeError):
                    log.debug("skipping unmergeable snapshot", exc_info=True)
        return merged

    def observe(self, rec: dict) -> None:
        """Feed one flat record straight into local registry + ledgers (tests
        and embedders without an events file)."""
        observe_record(rec, self.registry)
        self.ledger.observe(rec)
        self.byteflow.observe(rec)
        if self.watchtower is not None:
            self.watchtower.observe(rec)


def _json_body(doc: dict) -> bytes:
    return (json.dumps(doc, indent=2) + "\n").encode()
