"""Warm spare workers: pre-paid interpreter+import cost for restart rounds.

``BENCH_restart.json`` decomposes the in-job respawn tax: of ~4-6 s, nearly all
is process spawn + interpreter startup, with a measured multi-second
bare-interpreter floor that *serializes* across concurrent spawns. The
reference pays the same tax on every restart round (its ``start_processes``
spawn path, ``_torch_elastic_compat/multiprocessing/api.py``) — this module
removes it:

- A :class:`WarmSparePool` keeps N **parked interpreters** that have already
  imported the expensive modules (``jax`` by default) but have NOT initialized
  any platform/backend state — parking happens strictly before rank assignment,
  rendezvous, or device use, so a promoted spare is indistinguishable from a
  fresh interpreter to the workload.
- On a restart round, ``WorkerGroup.start`` *promotes* a warm spare instead of
  paying the spawn: the per-round spec (argv, env, log paths) is written down
  an inherited pipe, and the shim in this module applies it and runs the user
  script as ``__main__``.

The pipe is also the lifetime tether: a parked shim blocks in ``readline`` (no
polling, zero CPU while parked) and EOF — the launcher exiting or crashing at
ANY point, including while the spare is still importing — unparks it straight
into a clean exit. No leaked interpreters, no ppid watching.

No fork anywhere: each spare is a fresh ``exec``'d interpreter (a forked JAX
runtime is unusable), merely one that did its imports early.

Promotion parity contract: the shim REPLACES ``os.environ`` with the round env
(matching ``Popen(env=...)`` semantics of the cold path), points ``sys.argv``
and ``sys.path[0]`` at the script exactly as ``python script.py`` would (for
``-m`` workers ``sys.path[0]`` stays the working directory, as
``python -m`` does), and splices round-env ``PYTHONPATH`` entries that were
not present at park time into ``sys.path``. One caveat remains by design: an
env var that a *preloaded* module reads at import time must already be present
in the launcher's environment (true for ``JAX_PLATFORMS`` workflows here:
workers re-select platforms at runtime via
``platform.device.apply_platform_env``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
from typing import Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: exported into a promoted spare's env so workloads/tests can observe promotion
PROMOTED_ENV = "TPU_FT_WARM_SPARE"


# ------------------------------------------------------------------ the shim --


def _apply_spec_and_run(spec: dict) -> None:
    # Replace — not merge — the environment: a var the launcher dropped since
    # the spare was parked must not survive into the worker (cold workers get
    # Popen(env=...) replacement semantics; promoted workers must match).
    os.environ.clear()
    os.environ.update(spec.get("env", {}))
    for stream_name, fd in (("stdout", 1), ("stderr", 2)):
        path = spec.get(stream_name)
        if path:
            f = open(path, "ab")
            os.dup2(f.fileno(), fd)

    argv = spec["argv"]
    module_mode = bool(argv) and argv[0] == "-m"
    if not module_mode:
        # `python script.py`: sys.path[0] is the script's directory, REPLACING
        # the -m working-directory entry this interpreter booted with. Done
        # BEFORE the PYTHONPATH splice so a round entry equal to the launcher
        # cwd isn't wrongly deduped against that about-to-vanish slot.
        sys.path[0] = os.path.dirname(os.path.abspath(argv[0]))
    # Round-env PYTHONPATH entries the parked interpreter never saw: splice
    # them in where the cold interpreter would have put them (right after the
    # argv[0] slot, ahead of site-packages).
    for p in reversed(
        [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
    ):
        if p not in sys.path:
            sys.path.insert(1, p)

    if module_mode:
        import runpy

        # `python -m mod`: sys.path[0] is the working directory — which is
        # exactly what this shim (itself launched via -m) already has there.
        sys.argv = [argv[1]] + argv[2:]
        runpy.run_module(argv[1], run_name="__main__", alter_sys=True)
    else:
        import types

        # Execute the script in a module REGISTERED as __main__ (runpy.run_path
        # runs in a throwaway namespace): pickling of script-level classes and
        # multiprocessing-spawn children resolve __main__ to the user's script,
        # exactly as under `python script.py`.
        script = argv[0]
        sys.argv = list(argv)
        mod = types.ModuleType("__main__")
        mod.__file__ = script
        mod.__dict__["__builtins__"] = __builtins__
        sys.modules["__main__"] = mod
        with open(script, "rb") as f:
            code = compile(f.read(), script, "exec")
        exec(code, mod.__dict__)


def _serve_parked(go_fd: int, ready_file: str, preload: str) -> None:
    """Import the expensive modules, announce readiness, then block on the
    launcher's pipe until a round spec arrives (or EOF: launcher gone)."""
    for mod in filter(None, preload.split(",")):
        __import__(mod)
    tmp = ready_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(os.getpid()))
    os.replace(tmp, ready_file)

    with os.fdopen(go_fd, "r") as go:
        line = go.readline()  # blocks; zero CPU while parked
    if not line.strip():
        sys.exit(0)  # EOF/blank: the launcher is gone or released us
    _apply_spec_and_run(json.loads(line))


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="parked warm-spare worker shim")
    ap.add_argument("--go-fd", type=int, required=True)
    ap.add_argument("--ready-file", required=True)
    ap.add_argument("--preload", default="jax")
    args = ap.parse_args(argv)
    _serve_parked(args.go_fd, args.ready_file, args.preload)
    return 0


# ------------------------------------------------------------------ the pool --


class ParkedSpare:
    """One parked interpreter. ``warm`` once its preloads finished; ``unpark``
    hands it the round spec and it becomes a regular worker process."""

    def __init__(self, proc: subprocess.Popen, go_wfd: int, ready_file: str):
        self.proc = proc
        self._go_wfd: Optional[int] = go_wfd
        self.ready_file = ready_file

    @property
    def warm(self) -> bool:
        return self.proc.poll() is None and os.path.exists(self.ready_file)

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def unpark(
        self,
        argv: list[str],
        env: dict[str, str],
        stdout: Optional[str] = None,
        stderr: Optional[str] = None,
    ) -> subprocess.Popen:
        env = dict(env)
        env[PROMOTED_ENV] = "1"
        spec = {"argv": list(argv), "env": env, "stdout": stdout, "stderr": stderr}
        payload = memoryview((json.dumps(spec) + "\n").encode())
        while payload:
            n = os.write(self._go_wfd, payload)
            payload = payload[n:]
        os.close(self._go_wfd)
        self._go_wfd = None
        self._cleanup_files()
        return self.proc

    def _cleanup_files(self) -> None:
        try:
            os.unlink(self.ready_file)
        except OSError:
            pass

    def kill(self, grace: float = 2.0) -> None:
        """Release (EOF → clean exit) with a SIGKILL backstop, and reap."""
        if self._go_wfd is not None:
            try:
                os.close(self._go_wfd)
            except OSError:
                pass
            self._go_wfd = None
        try:
            self.proc.wait(timeout=grace if self.proc.poll() is None else 0.1)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    self.proc.kill()
                except (ProcessLookupError, PermissionError):
                    pass
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                log.error(f"parked spare pid {self.proc.pid} unreapable")
        self._cleanup_files()


def spawn_spare(run_dir: str, spare_id: int, preload: str = "jax") -> ParkedSpare:
    """Spawn one parked shim; the returned spare's pipe write-end is the only
    handle the launcher needs (spec on promote, close on release)."""
    os.makedirs(run_dir, exist_ok=True)
    ready = os.path.join(run_dir, f"ready_{spare_id}")
    try:
        os.unlink(ready)
    except OSError:
        pass
    rfd, wfd = os.pipe()
    try:
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "tpu_resiliency.launcher.park",
                "--go-fd",
                str(rfd),
                "--ready-file",
                ready,
                "--preload",
                preload,
            ],
            env=dict(os.environ),
            start_new_session=True,
            pass_fds=(rfd,),
        )
    except BaseException:
        os.close(wfd)
        raise
    finally:
        os.close(rfd)
    return ParkedSpare(proc, wfd, ready)


class WarmSparePool:
    """Keeps ``size`` parked interpreters ready; replenishes on acquire.

    Spawning a spare is a non-blocking ``Popen`` (~ms for the parent); the
    spare pays its import bill in the background while the current round runs,
    so by the time a restart needs it the interpreter floor is already paid.
    """

    def __init__(self, size: int, run_dir: str, preload: str = "jax"):
        self.size = size
        self.run_dir = os.path.join(run_dir, "spares")
        self.preload = preload
        self._spares: list[ParkedSpare] = []
        self._next_id = 0
        self._startup_deaths = 0  # consecutive died-before-warm spares
        for _ in range(size):
            self._spawn()

    def _spawn(self) -> None:
        sid = self._next_id
        self._next_id += 1
        self._spares.append(spawn_spare(self.run_dir, sid, self.preload))

    def acquire(self) -> Optional[ParkedSpare]:
        """A warm spare (removed from the pool), or None — callers fall back to
        a cold spawn, so a dead/cold pool degrades to exactly the poolless
        behavior. The pool is topped back up to ``size`` on every call,
        whatever was reaped or promoted."""
        live: list[ParkedSpare] = []
        for s in self._spares:
            if s.alive:
                live.append(s)
                continue
            # Died before ever becoming warm = its preload/startup failed
            # (traceback went to the launcher's stderr). A systematic startup
            # failure (e.g. a typo'd --warm-spare-preload) must not respawn
            # doomed interpreters on every round forever.
            died_cold = not os.path.exists(s.ready_file) and s.proc.poll() != 0
            self._startup_deaths = self._startup_deaths + 1 if died_cold else 0
            s.kill()  # reap the zombie + remove its ready file
        self._spares = live
        if self.size > 0 and self._startup_deaths >= 2 * self.size:
            log.error(
                f"warm-spare pool disabled: {self._startup_deaths} spares died "
                f"during startup (bad --warm-spare-preload={self.preload!r}? "
                "see the launcher's stderr for their tracebacks); restart "
                "rounds will cold-spawn"
            )
            self.size = 0
        found: Optional[ParkedSpare] = None
        for i, spare in enumerate(self._spares):
            if spare.warm:
                found = spare
                del self._spares[i]
                break
        while len(self._spares) < self.size:
            self._spawn()
        return found

    @property
    def warm_count(self) -> int:
        return sum(1 for s in self._spares if s.warm)

    def close(self) -> None:
        for s in self._spares:
            s.kill()
        self._spares = []


if __name__ == "__main__":
    sys.exit(main())
