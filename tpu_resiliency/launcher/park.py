"""Warm spare workers: pre-paid interpreter+import cost for restart rounds.

``BENCH_restart.json`` decomposes the in-job respawn tax: of ~4-6 s, nearly all
is process spawn + interpreter startup, with a measured multi-second
bare-interpreter floor that *serializes* across concurrent spawns. The
reference pays the same tax on every restart round (its ``start_processes``
spawn path, ``_torch_elastic_compat/multiprocessing/api.py``) — this module
removes it:

- A :class:`WarmSparePool` keeps N **parked interpreters** that have already
  imported the expensive modules (``jax`` by default) but have NOT initialized
  any device-owning backend — parking happens strictly before rank assignment,
  rendezvous, or device use, so a promoted spare is indistinguishable from a
  fresh interpreter to the workload.
- An optional **runtime warmup phase** (``--warm-spare-warmup runtime``) goes
  one park level deeper: after the imports, the shim runs a platform-safe
  warmup (``platform/device.py:warm_runtime``) — backend *plugin discovery*
  without initialization, the backend-free tracing machinery, and CPU/loopback
  backend pre-init only where it cannot conflict with the dying worker's
  device lease (``$JAX_PLATFORMS=cpu`` workloads). Device-grabbing stays
  strictly post-promotion. The achieved **park depth** (1 = imports,
  2 = runtime-warm) is reported in the ready file so promotion can prefer the
  deepest-warmed spare.
- On a restart round, ``WorkerGroup.start`` *promotes* a warm spare instead of
  paying the spawn: the per-round spec (argv, env, log paths) is written down
  an inherited pipe, and the shim in this module applies it and runs the user
  script as ``__main__``.

The pipe is also the lifetime tether: a parked shim blocks in ``readline`` (no
polling, zero CPU while parked) and EOF — the launcher exiting or crashing at
ANY point, including while the spare is still importing — unparks it straight
into a clean exit. No leaked interpreters, no ppid watching.

No fork anywhere: each spare is a fresh ``exec``'d interpreter (a forked JAX
runtime is unusable), merely one that did its imports early.

Promotion parity contract: the shim REPLACES ``os.environ`` with the round env
(matching ``Popen(env=...)`` semantics of the cold path), points ``sys.argv``
and ``sys.path[0]`` at the script exactly as ``python script.py`` would (for
``-m`` workers ``sys.path[0]`` stays the working directory, as
``python -m`` does), and splices round-env ``PYTHONPATH`` entries that were
not present at park time into ``sys.path``. The warmup phase is bound by the
same contract: it must not mutate ``os.environ`` or ``sys.path``, and a
warmup that raises kills the spare *before* its ready file exists, so the
pool counts it as a startup death (doomed warmups disable the pool instead of
respawning forever). One caveat remains by design: an env var that a
*preloaded* module reads at import time must already be present in the
launcher's environment (true for ``JAX_PLATFORMS`` workflows here: workers
re-select platforms at runtime via ``platform.device.apply_platform_env``).

Pool discipline (the restart hot path): ``acquire()`` only *selects* — it
reaps the dead, prefers the deepest-warmed spare, and never spawns. Top-up is
``replenish()``, which ``WorkerGroup.start`` runs on a background thread
*after* the round's workers are up, so promotion latency never includes a
replacement ``Popen``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
from typing import Optional

from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: exported into a promoted spare's env so workloads/tests can observe promotion
PROMOTED_ENV = "TPU_FT_WARM_SPARE"
#: the promoted spare's park depth (1 = imports, 2 = runtime-warm)
PROMOTED_DEPTH_ENV = "TPU_FT_WARM_SPARE_DEPTH"

#: ``--warm-spare-warmup`` value meaning "imports only, no warmup phase"
WARMUP_IMPORTS = "imports"
#: alias for the built-in platform-safe runtime warmup
WARMUP_RUNTIME = "runtime"
_WARMUP_RUNTIME_SPEC = "tpu_resiliency.platform.device:warm_runtime"


# ------------------------------------------------------------------ the shim --


def _run_warmup(spec: str) -> None:
    """Resolve and run the warmup callable (``module:function``; ``runtime``
    aliases the built-in platform-safe warmup). Any failure propagates: the
    shim dies before writing its ready file, which the pool counts as a
    startup death rather than promoting a half-warm interpreter."""
    if spec == WARMUP_RUNTIME:
        spec = _WARMUP_RUNTIME_SPEC
    mod_name, _, fn_name = spec.partition(":")
    import importlib

    fn = getattr(importlib.import_module(mod_name), fn_name or "warm_runtime")
    fn()


def _apply_spec_and_run(spec: dict) -> None:
    # Replace — not merge — the environment: a var the launcher dropped since
    # the spare was parked must not survive into the worker (cold workers get
    # Popen(env=...) replacement semantics; promoted workers must match).
    os.environ.clear()
    os.environ.update(spec.get("env", {}))
    for stream_name, fd in (("stdout", 1), ("stderr", 2)):
        path = spec.get(stream_name)
        if path:
            f = open(path, "ab")
            os.dup2(f.fileno(), fd)

    argv = spec["argv"]
    module_mode = bool(argv) and argv[0] == "-m"
    if not module_mode:
        # `python script.py`: sys.path[0] is the script's directory, REPLACING
        # the -m working-directory entry this interpreter booted with. Done
        # BEFORE the PYTHONPATH splice so a round entry equal to the launcher
        # cwd isn't wrongly deduped against that about-to-vanish slot.
        sys.path[0] = os.path.dirname(os.path.abspath(argv[0]))
    # Round-env PYTHONPATH entries the parked interpreter never saw: splice
    # them in where the cold interpreter would have put them (right after the
    # argv[0] slot, ahead of site-packages).
    for p in reversed(
        [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
    ):
        if p not in sys.path:
            sys.path.insert(1, p)

    if module_mode:
        import runpy

        # `python -m mod`: sys.path[0] is the working directory — which is
        # exactly what this shim (itself launched via -m) already has there.
        sys.argv = [argv[1]] + argv[2:]
        runpy.run_module(argv[1], run_name="__main__", alter_sys=True)
    else:
        import types

        # Execute the script in a module REGISTERED as __main__ (runpy.run_path
        # runs in a throwaway namespace): pickling of script-level classes and
        # multiprocessing-spawn children resolve __main__ to the user's script,
        # exactly as under `python script.py`.
        script = argv[0]
        sys.argv = list(argv)
        mod = types.ModuleType("__main__")
        mod.__file__ = script
        mod.__dict__["__builtins__"] = __builtins__
        sys.modules["__main__"] = mod
        with open(script, "rb") as f:
            code = compile(f.read(), script, "exec")
        exec(code, mod.__dict__)


def _serve_parked(go_fd: int, ready_file: str, preload: str, warmup: str) -> None:
    """Import the expensive modules, run the optional warmup phase, announce
    readiness (with the achieved park depth), then block on the launcher's
    pipe until a round spec arrives (or EOF: launcher gone)."""
    for mod in filter(None, preload.split(",")):
        __import__(mod)
    depth = 1
    if warmup and warmup != WARMUP_IMPORTS:
        _run_warmup(warmup)
        depth = 2
    tmp = ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"pid": os.getpid(), "depth": depth}, f)
    os.replace(tmp, ready_file)

    with os.fdopen(go_fd, "r") as go:
        line = go.readline()  # blocks; zero CPU while parked
    if not line.strip():
        sys.exit(0)  # EOF/blank: the launcher is gone or released us
    _apply_spec_and_run(json.loads(line))


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="parked warm-spare worker shim")
    ap.add_argument("--go-fd", type=int, required=True)
    ap.add_argument("--ready-file", required=True)
    ap.add_argument("--preload", default="jax")
    ap.add_argument("--warmup", default=WARMUP_IMPORTS)
    args = ap.parse_args(argv)
    _serve_parked(args.go_fd, args.ready_file, args.preload, args.warmup)
    return 0


# ------------------------------------------------------------------ the pool --


class ParkedSpare:
    """One parked interpreter. ``warm`` once its preloads finished; ``unpark``
    hands it the round spec and it becomes a regular worker process."""

    def __init__(self, proc: subprocess.Popen, go_wfd: int, ready_file: str):
        self.proc = proc
        self._go_wfd: Optional[int] = go_wfd
        self.ready_file = ready_file

    @property
    def warm(self) -> bool:
        return self.proc.poll() is None and os.path.exists(self.ready_file)

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    @property
    def park_depth(self) -> int:
        """The ready file's reported depth: 0 not warm, 1 imports, 2 runtime.
        A legacy plain-pid ready file reads as depth 1."""
        if not self.warm:
            return 0
        try:
            with open(self.ready_file) as f:
                body = f.read().strip()
            if body.startswith("{"):
                return int(json.loads(body).get("depth", 1))
            return 1
        except (OSError, ValueError):
            return 1

    def unpark(
        self,
        argv: list[str],
        env: dict[str, str],
        stdout: Optional[str] = None,
        stderr: Optional[str] = None,
    ) -> subprocess.Popen:
        env = dict(env)
        env[PROMOTED_ENV] = "1"
        env[PROMOTED_DEPTH_ENV] = str(self.park_depth)
        spec = {"argv": list(argv), "env": env, "stdout": stdout, "stderr": stderr}
        payload = memoryview((json.dumps(spec) + "\n").encode())
        while payload:
            n = os.write(self._go_wfd, payload)
            payload = payload[n:]
        os.close(self._go_wfd)
        self._go_wfd = None
        self._cleanup_files()
        return self.proc

    def _cleanup_files(self) -> None:
        try:
            os.unlink(self.ready_file)
        except OSError:
            pass

    def kill(self, grace: float = 2.0) -> None:
        """Release (EOF → clean exit) with a SIGKILL backstop, and reap."""
        if self._go_wfd is not None:
            try:
                os.close(self._go_wfd)
            except OSError:
                pass
            self._go_wfd = None
        try:
            self.proc.wait(timeout=grace if self.proc.poll() is None else 0.1)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    self.proc.kill()
                except (ProcessLookupError, PermissionError):
                    pass
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                log.error(f"parked spare pid {self.proc.pid} unreapable")
        self._cleanup_files()


def spawn_spare(
    run_dir: str, spare_id: int, preload: str = "jax",
    warmup: str = WARMUP_IMPORTS,
) -> ParkedSpare:
    """Spawn one parked shim; the returned spare's pipe write-end is the only
    handle the launcher needs (spec on promote, close on release)."""
    os.makedirs(run_dir, exist_ok=True)
    ready = os.path.join(run_dir, f"ready_{spare_id}")
    try:
        os.unlink(ready)
    except OSError:
        pass
    rfd, wfd = os.pipe()
    try:
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "tpu_resiliency.launcher.park",
                "--go-fd",
                str(rfd),
                "--ready-file",
                ready,
                "--preload",
                preload,
                "--warmup",
                warmup,
            ],
            env=dict(os.environ),
            start_new_session=True,
            pass_fds=(rfd,),
        )
    except BaseException:
        os.close(wfd)
        raise
    finally:
        os.close(rfd)
    return ParkedSpare(proc, wfd, ready)


class WarmSparePool:
    """Keeps ``size`` parked interpreters ready.

    Spawning a spare is a non-blocking ``Popen`` (~ms for the parent); the
    spare pays its import bill in the background while the current round runs,
    so by the time a restart needs it the interpreter floor is already paid.

    ``acquire()`` is promotion-hot-path-safe: it only reaps and selects
    (deepest park depth first) — it NEVER spawns. Call :meth:`replenish`
    off the critical path (``WorkerGroup.start`` does, on a background
    thread after the round's workers are up) to top the pool back up.
    """

    def __init__(
        self, size: int, run_dir: str, preload: str = "jax",
        warmup: str = WARMUP_IMPORTS,
    ):
        self.size = size
        self.run_dir = os.path.join(run_dir, "spares")
        self.preload = preload
        self.warmup = warmup
        self._spares: list[ParkedSpare] = []
        self._next_id = 0
        self._lock = threading.Lock()
        self._startup_deaths = 0  # consecutive died-before-warm spares
        self.replenish()

    def _spawn_locked(self) -> None:
        sid = self._next_id
        self._next_id += 1
        self._spares.append(
            spawn_spare(self.run_dir, sid, self.preload, self.warmup)
        )

    def _reap_locked(self) -> None:
        """Drop dead spares; track consecutive startup deaths so a doomed
        preload/warmup (e.g. a typo'd module) disables the pool with a
        diagnostic instead of respawning dying interpreters on every round
        forever. The tracebacks went to the launcher's stderr."""
        live: list[ParkedSpare] = []
        for s in self._spares:
            if s.alive:
                live.append(s)
                continue
            died_cold = not os.path.exists(s.ready_file) and s.proc.poll() != 0
            self._startup_deaths = self._startup_deaths + 1 if died_cold else 0
            s.kill()  # reap the zombie + remove its ready file
        self._spares = live
        if self.size > 0 and self._startup_deaths >= 2 * self.size:
            log.error(
                f"warm-spare pool disabled: {self._startup_deaths} spares died "
                f"during startup (bad --warm-spare-preload={self.preload!r} or "
                f"--warm-spare-warmup={self.warmup!r}? see the launcher's "
                "stderr for their tracebacks); restart rounds will cold-spawn"
            )
            self.size = 0

    def _record_state_locked(self) -> None:
        # The pool gauge (tpu_warm_spares_warm) rides the event stream like
        # every other metric: one record per state change, not a poller.
        record_event(
            "launcher", "warm_spare_pool",
            size=self.size, parked=len(self._spares),
            warm=sum(1 for s in self._spares if s.warm),
        )

    def acquire(self) -> Optional[ParkedSpare]:
        """The deepest-warmed spare (removed from the pool), or None — callers
        fall back to a cold spawn, so a dead/cold pool degrades to exactly the
        poolless behavior. Selection only: replacements are spawned by
        :meth:`replenish`, never here — promotion must not block on a
        ``Popen``."""
        with self._lock:
            self._reap_locked()
            best_i, best_depth = -1, 0
            for i, spare in enumerate(self._spares):
                depth = spare.park_depth
                if depth > best_depth:
                    best_i, best_depth = i, depth
            found = self._spares.pop(best_i) if best_i >= 0 else None
            self._record_state_locked()
            return found

    def replenish(self) -> int:
        """Reap the dead and spawn spares until the pool is back at ``size``;
        returns how many were spawned. Safe to call from a background thread
        (WorkerGroup.start does, after the round's workers are up)."""
        with self._lock:
            self._reap_locked()
            spawned = 0
            while len(self._spares) < self.size:
                self._spawn_locked()
                spawned += 1
            if spawned:
                self._record_state_locked()
            return spawned

    @property
    def warm_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._spares if s.warm)

    def stats(self) -> dict:
        """Pool state for /healthz: size, parked, warm, deepest park depth."""
        with self._lock:
            depths = [s.park_depth for s in self._spares]
            return {
                "size": self.size,
                "parked": len(self._spares),
                "warm": sum(1 for d in depths if d > 0),
                "deepest": max(depths, default=0),
            }

    def close(self) -> None:
        with self._lock:
            for s in self._spares:
                s.kill()
            self._spares = []


if __name__ == "__main__":
    sys.exit(main())
