"""Worker process group: spawn, poll, redirect, stop.

The lean re-design of the reference's vendored torchelastic multiprocessing layer
(``_torch_elastic_compat/multiprocessing/api.py`` ``start_processes``/``PContext``,
std redirection/tee, ~2000 LoC): one ``subprocess.Popen`` per rank with per-rank
log files and error files, a non-blocking group poll, and graceful→forceful stop.
No fork-server indirection — TPU workers are always fresh interpreters (a forked JAX
runtime is unusable anyway), so plain exec is both simpler and correct. The
spawn+import tax that exec'ing fresh interpreters costs on *restart* rounds is
removed by ``park.WarmSparePool`` (pre-imported parked interpreters, promoted
by ``start`` when available) rather than by forking.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import signal
import subprocess
import sys
import threading
import time
from typing import IO, Optional

from tpu_resiliency.launcher.errors import ERROR_FILE_ENV, WorkerError
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


def signal_tree(pid: int, sig: int) -> None:
    """Signal a session-leader's whole process group, falling back to the
    single pid if the group is already gone. Shared by worker stop and
    warm-spare teardown (both spawn session leaders)."""
    try:
        os.killpg(pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass


class GroupState(enum.Enum):
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"


@dataclasses.dataclass
class Worker:
    local_rank: int
    global_rank: int
    proc: subprocess.Popen
    error_file: str
    log_dir: Optional[str] = None
    _stdout: Optional[IO] = None
    _stderr: Optional[IO] = None

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def exitcode(self) -> Optional[int]:
        return self.proc.poll()

    def error(self) -> Optional[WorkerError]:
        return WorkerError.from_file(self.error_file)


@dataclasses.dataclass
class WorkerFailure:
    local_rank: int
    global_rank: int
    exitcode: int
    error: Optional[WorkerError]

    def describe(self) -> str:
        base = f"rank {self.global_rank} (local {self.local_rank}) exit {self.exitcode}"
        if self.error is not None:
            base += f": {self.error.exception_type}: {self.error.message}"
        return base


class WorkerGroup:
    """One round's local workers. Start → poll → (stop | reap)."""

    def __init__(
        self,
        argv: list[str],
        nproc: int,
        base_env: dict[str, str],
        run_dir: str,
        log_dir: Optional[str] = None,
        use_python: bool = True,
        spare_pool=None,
    ):
        self.argv = argv
        self.nproc = nproc
        self.base_env = base_env
        self.run_dir = run_dir
        self.log_dir = log_dir
        self.use_python = use_python
        #: optional launcher-owned ``park.WarmSparePool``: ranks are served by
        #: promoting parked pre-imported interpreters when one is warm,
        #: removing the measured multi-second spawn+import tax from restart
        #: rounds; cold spawn remains the fallback per rank.
        self.spare_pool = spare_pool if use_python else None
        self.workers: list[Worker] = []
        #: optional callable local_rank -> extra env (e.g. the per-rank monitor socket)
        self.per_rank_env = None
        #: set by a per-worker reaper thread the instant ANY worker exits, so
        #: the supervise loop wakes immediately instead of discovering the exit
        #: at its next poll tick — this takes the detection segment of
        #: BENCH_restart's respawn decomposition from O(monitor_interval) to ~ms.
        self._change = threading.Event()

    def start(self, round_no: int, first_global_rank: int, world_size: int) -> None:
        if self.workers:
            raise RuntimeError("worker group already started")
        os.makedirs(self.run_dir, exist_ok=True)
        cmd = ([sys.executable] if self.use_python else []) + self.argv
        for local in range(self.nproc):
            grank = first_global_rank + local
            env = dict(os.environ)
            env.update(self.base_env)
            if self.per_rank_env is not None:
                env.update(self.per_rank_env(local))
            error_file = os.path.join(self.run_dir, f"err_r{round_no}_rank{grank}.json")
            if os.path.exists(error_file):
                os.unlink(error_file)
            env.update(
                {
                    "RANK": str(grank),
                    "LOCAL_RANK": str(local),
                    "WORLD_SIZE": str(world_size),
                    "LOCAL_WORLD_SIZE": str(self.nproc),
                    "TPU_FT_RESTART_COUNT": str(round_no),
                    ERROR_FILE_ENV: error_file,
                }
            )
            stdout = stderr = None
            stdout_path = stderr_path = None
            wlog_dir = None
            if self.log_dir:
                wlog_dir = os.path.join(self.log_dir, f"round_{round_no}", f"rank_{grank}")
                os.makedirs(wlog_dir, exist_ok=True)
                stdout_path = os.path.join(wlog_dir, "stdout.log")
                stderr_path = os.path.join(wlog_dir, "stderr.log")
            spare = self.spare_pool.acquire() if self.spare_pool is not None else None
            proc = None
            if spare is not None:
                # Promote a parked pre-imported interpreter: it applies env and
                # redirection itself (dup2 on the given paths) and runs the
                # script as __main__ — no spawn, no import bill. The pool
                # handed us its deepest-warmed spare; replacements are spawned
                # AFTER the round is up (see the replenish thread below), so
                # nothing here ever blocks on a Popen.
                try:
                    depth = spare.park_depth
                    proc = spare.unpark(
                        self.argv, env, stdout=stdout_path, stderr=stderr_path
                    )
                    log.info(
                        f"rank {grank}: promoted warm spare pid {proc.pid} "
                        f"(park depth {depth})"
                    )
                    # worker_pid, not pid: 'pid' is the Event's own identity
                    # field (the recording process — this launcher).
                    record_event(
                        "launcher", "worker_promoted", round=round_no,
                        global_rank=grank, worker_pid=proc.pid,
                        outcome="promoted", park_depth=depth,
                    )
                except OSError:
                    # The spare died between acquire() and the pipe write
                    # (EPIPE); fall through to a cold spawn.
                    spare.kill()
                    log.warning(f"rank {grank}: warm spare died at promotion; cold spawn")
                    record_event(
                        "launcher", "worker_promoted", round=round_no,
                        global_rank=grank, outcome="dead_at_promotion",
                    )
            elif self.spare_pool is not None and self.spare_pool.size > 0:
                # A pool exists but had nothing warm to give: the cold spawn
                # below is a fallback worth counting (it IS the latency the
                # pool exists to remove).
                record_event(
                    "launcher", "worker_promoted", round=round_no,
                    global_rank=grank, outcome="cold_fallback",
                )
            if proc is None:
                if stdout_path is not None:
                    stdout = open(stdout_path, "ab")
                    stderr = open(stderr_path, "ab")
                # Each worker leads its own session/process group so stop() can
                # signal the whole tree — a worker's own subprocesses
                # (dataloaders, shell wrappers) must not outlive it into the
                # next restart round.
                proc = subprocess.Popen(
                    cmd,
                    env=env,
                    stdout=stdout,
                    stderr=stderr,
                    start_new_session=True,
                )
            self.workers.append(
                Worker(
                    local_rank=local,
                    global_rank=grank,
                    proc=proc,
                    error_file=error_file,
                    log_dir=wlog_dir,
                    _stdout=stdout,
                    _stderr=stderr,
                )
            )
        for w in self.workers:
            threading.Thread(
                target=self._reap_and_signal, args=(w.proc,), daemon=True
            ).start()
        if self.spare_pool is not None:
            # Top the pool back up OFF the promotion critical path: the round's
            # workers are already running; replacement Popen cost lands on a
            # background thread, not on restart latency.
            threading.Thread(
                target=self._replenish_pool, daemon=True,
                name="spare-replenish",
            ).start()
        log.info(
            f"started {self.nproc} workers (global ranks "
            f"{first_global_rank}..{first_global_rank + self.nproc - 1} of {world_size})"
        )

    def _replenish_pool(self) -> None:
        try:
            self.spare_pool.replenish()
        except Exception:
            log.exception("warm-spare pool replenish failed")

    def _reap_and_signal(self, proc: subprocess.Popen) -> None:
        try:
            proc.wait()
        except Exception:
            pass
        self._change.set()

    def wait_change(self, timeout: float) -> bool:
        """Block up to ``timeout`` for any worker exit since the last call;
        True if one happened. The event is only a wakeup accelerator — state
        truth is always re-read via :meth:`poll` — so the clear-after-wake
        race (a second exit landing between wake and clear) is harmless: the
        caller's poll sees every exit code regardless."""
        if self._change.wait(timeout):
            self._change.clear()
            return True
        return False

    def notify_change(self) -> None:
        """External wake for :meth:`wait_change` — e.g. the agent's restart-key
        watcher folding store events into the same supervise wakeup."""
        self._change.set()

    def poll(self) -> GroupState:
        codes = [w.exitcode for w in self.workers]
        if any(c not in (0, None) for c in codes):
            return GroupState.FAILED
        if all(c == 0 for c in codes):
            return GroupState.SUCCEEDED
        return GroupState.RUNNING

    def failures(self) -> list[WorkerFailure]:
        return [
            WorkerFailure(
                local_rank=w.local_rank,
                global_rank=w.global_rank,
                exitcode=w.exitcode,
                error=w.error(),
            )
            for w in self.workers
            if w.exitcode not in (0, None)
        ]

    def exitcodes(self) -> dict[int, Optional[int]]:
        return {w.global_rank: w.exitcode for w in self.workers}

    @staticmethod
    def _signal_tree(pid: int, sig: int) -> None:
        signal_tree(pid, sig)

    def stop(self, grace: float = 15.0, sig: int = int(signal.SIGTERM)) -> None:
        """Graceful stop: `sig` (after SIGCONT, in case a worker is stopped), then
        SIGKILL leftovers after `grace` (reference ``_shutdown_rank`` escalation,
        ``rank_monitor_server.py:176``)."""
        for w in self.workers:
            if w.exitcode is None:
                self._signal_tree(w.pid, signal.SIGCONT)
                self._signal_tree(w.pid, sig)
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if all(w.exitcode is not None for w in self.workers):
                break
            # The reaper threads set _change the instant any worker exits, so
            # this wait returns in ~ms once the last one dies — teardown is on
            # the restart critical path and must not poll it away in 100 ms
            # ticks. Clear first (the exit that triggered this stop already
            # set it); an exit racing the clear is caught by the timeout
            # re-check. State truth stays with the poll above.
            self._change.clear()
            self._change.wait(0.02)
        for w in self.workers:
            if w.exitcode is None:
                log.warning(f"worker rank {w.global_rank} ignored signal; SIGKILL")
                # The top rung of the kill ladder — pairs with the monitor's
                # per-signal ``kill_ladder`` records so the stream shows which
                # step actually ended a wedged rank.
                record_event(
                    "launcher", "kill_ladder", step="SIGKILL",
                    global_rank=w.global_rank, worker_pid=w.pid,
                    grace_s=grace,
                )
                self._signal_tree(w.pid, signal.SIGKILL)
            else:
                # Reap stragglers the dead leader left behind in its group.
                self._signal_tree(w.pid, signal.SIGKILL)
        for w in self.workers:
            try:
                w.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                log.error(f"worker pid {w.pid} unreapable")
        self._close_logs()

    def reap(self) -> None:
        for w in self.workers:
            if w.exitcode is None:
                w.proc.wait()
        self._close_logs()

    def _close_logs(self) -> None:
        for w in self.workers:
            for f in (w._stdout, w._stderr):
                if f is not None:
                    try:
                        f.close()
                    except OSError:
                        pass
            w._stdout = w._stderr = None
