"""Fault-tolerant elastic launcher: per-host agent, CAS rendezvous, worker groups.

TPU-native analogue of the reference's ``ft_launcher`` + elastic-agent stack
(``fault_tolerance/launcher.py``, ``_torch_elastic_compat/agent``).
"""

from tpu_resiliency.launcher.agent import AgentConfig, ElasticAgent, WorkersFailed
from tpu_resiliency.launcher.errors import (
    ERROR_FILE_ENV,
    WorkerError,
    main_guard,
    record,
    write_error_file,
)
from tpu_resiliency.launcher.proc import GroupState, WorkerFailure, WorkerGroup
from tpu_resiliency.launcher.rendezvous import (
    RendezvousOutcome,
    RendezvousSettings,
    StoreRendezvous,
)

__all__ = [
    "AgentConfig",
    "ElasticAgent",
    "WorkersFailed",
    "ERROR_FILE_ENV",
    "WorkerError",
    "main_guard",
    "record",
    "write_error_file",
    "GroupState",
    "WorkerFailure",
    "WorkerGroup",
    "RendezvousOutcome",
    "RendezvousSettings",
    "StoreRendezvous",
]
