"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context sequence/context parallelism is first-class in this framework: the
sequence axis of activations is sharded over the mesh's ``sp`` axis, and attention
runs as a ring — each device holds its local Q block resident and rotates K/V
blocks around the ``sp`` ring with ``lax.ppermute`` (one ICI hop per step), folding
each incoming block into a numerically-stable online softmax (flash-attention-style
``(m, l, o)`` accumulators). Peak memory per device is O(T_local) and the
communication pattern is nearest-neighbor — exactly what ICI topologies are built
for. No reference counterpart exists (the reference implements no parallelism,
SURVEY.md §2.7 checklist); the pattern follows the public blockwise/ring-attention
literature (PAPERS.md).

Usage (the transformer wires this through ``forward(..., attn_fn=...)``)::

    attn_fn = make_ring_attn_fn(mesh)       # axes: dp, sp, tp
    logits = forward(params, tokens, cfg, attn_fn=attn_fn)

The kernel is causal with GLOBAL positions: shard ``i`` of the ring owns positions
``[i*T_local, (i+1)*T_local)``; masks are computed against the source shard of
each rotating K/V block, so results are bit-for-bit the same attention function as
the dense ``models.transformer._attention`` (verified in tests to fp tolerance).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpu_resiliency.parallel.mesh import DP, SP, TP

NEG_INF = -1e30


def _ring_block(q, k, v, *, axis_name: str, causal: bool):
    """Local kernel under shard_map. q/k/v: [B, T_local, H, dh] (this shard)."""
    sp = lax.psum(1, axis_name)  # static axis size
    idx = lax.axis_index(axis_name)
    b, tl, h, dh = q.shape
    scale = 1.0 / np.sqrt(dh)

    qf = q.astype(jnp.float32)
    q_pos = idx * tl + jnp.arange(tl)  # global positions of the resident Q block

    m = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tl), jnp.float32)
    o = jnp.zeros((b, tl, h, dh), jnp.float32)

    perm = [(j, (j + 1) % sp) for j in range(sp)]
    for r in range(sp):
        # Block r arrived from shard (idx - r): its K positions are global.
        src = (idx - r) % sp
        k_pos = src * tl + jnp.arange(tl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32)) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        block_m = scores.max(axis=-1)  # [B, H, Tq]
        new_m = jnp.maximum(m, block_m)
        # Fully-masked rows keep new_m == NEG_INF; exp(NEG_INF - NEG_INF) would be
        # 1, so probabilities are explicitly zeroed where the score was masked.
        p = jnp.exp(scores - new_m[..., None])
        p = jnp.where(scores <= NEG_INF, 0.0, p)
        correction = jnp.exp(m - new_m)  # [B, H, Tq]
        l = l * correction + p.sum(axis=-1)
        o = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
        )
        m = new_m
        if r + 1 < sp:
            k, v = lax.ppermute((k, v), axis_name, perm)

    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)


@functools.lru_cache(maxsize=8)
def _cached_sharded_kernel(mesh, axis_name: str, causal: bool, batch_axis: str,
                           head_axis: str):
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, axis_name, head_axis, None)
    return jax.shard_map(
        functools.partial(_ring_block, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )


def make_ring_attn_fn(mesh, *, causal: bool = True, axis_name: str = SP,
                      batch_axis: str = DP, head_axis: str = TP):
    """Build an ``attn_fn`` for ``models.transformer.forward``: q/k/v enter as
    [B, T, H, dh] logically; physically sharded (batch over ``dp``, sequence over
    ``sp``, heads over ``tp``). KV must be pre-repeated to full heads
    (``transformer.adapt_attn_fn`` wraps custom fns with exactly that repeat),
    so head counts divide over ``tp``."""
    kernel = _cached_sharded_kernel(mesh, axis_name, causal, batch_axis, head_axis)

    def attn_fn(q, k, v):
        return kernel(q, k, v)

    return attn_fn
