"""Mesh axis conventions and sharding-spec helpers for the framework's models.

Axis names used throughout:

- ``dp``: data parallel (batch axis; gradients all-reduced over ICI),
- ``tp``: tensor parallel (attention heads / MLP hidden sharded; activations
  all-gathered / reduce-scattered by XLA where needed),
- ``sp``: sequence/context parallel (long-context: sequence axis sharded, attention
  runs as a ring over ``sp`` — see ``parallel/ring_attention.py``),
- ``pp``: pipeline parallel (the stacked ``[L]`` layer axis sharded into stages;
  microbatches flow stage-to-stage as a ``ppermute`` ring — see
  ``parallel/pipeline.py``),
- ``ep``: expert parallel (MoE expert axis sharded; the dispatch einsums make XLA
  route tokens with an all-to-all — see ``models/moe.py``).

The reference implements no parallelism (SURVEY.md §2.7 checklist) — these exist because
a TPU-native resiliency framework must be *exercised* against real sharded workloads,
and its rank topology components (Tree layers, replication cliques) key off mesh axes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

DP, TP, SP, PP, EP = "dp", "tp", "sp", "pp", "ep"


def build_mesh(
    n_devices: Optional[int] = None,
    *,
    dp: int = 1,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence] = None,
):
    """Build a ``Mesh`` with the framework's canonical axes (dp, tp, sp, pp, ep).

    If ``n_devices`` is given without explicit axis sizes, all devices go to ``dp``.
    Axis order puts ``pp`` outermost (stage hops are the rarest, largest-grained
    transfers) and ``tp`` innermost (its collectives are per-matmul, so it gets the
    fastest ICI loops).
    """
    import jax

    from tpu_resiliency.platform.device import make_mesh

    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    total = dp * tp * sp * pp * ep
    if total == 1 and n_devices:
        dp, total = len(devs), len(devs)
    if total != len(devs):
        raise ValueError(f"dp*tp*sp*pp*ep = {total} != {len(devs)} devices")
    return make_mesh({PP: pp, DP: dp, EP: ep, SP: sp, TP: tp}, devices=devs)


def default_split(n_devices: int) -> dict[str, int]:
    """A sensible (dp, tp, sp) split for n devices (pp/ep left to dedicated configs —
    see :func:`moe_pipeline_split`).

    All three axes are real: 8 devices → (dp=2, tp=2, sp=2) — the training step
    runs tensor-parallel matmuls, a data-parallel gradient reduction, AND ring
    attention over the sequence axis (``parallel/ring_attention.py``)."""
    if n_devices % 8 == 0:
        return {"dp": n_devices // 4, "tp": 2, "sp": 2, "pp": 1, "ep": 1}
    tp = 2 if n_devices % 2 == 0 else 1
    return {"dp": n_devices // tp, "tp": tp, "sp": 1, "pp": 1, "ep": 1}


def moe_pipeline_split(n_devices: int) -> dict[str, int]:
    """A (dp, pp, ep) split exercising the pipeline + expert axes: 8 devices →
    (dp=2, pp=2, ep=2). The MoE training step then runs a data-parallel gradient
    reduction, a two-stage microbatch pipeline, AND expert-parallel dispatch."""
    if n_devices % 4 == 0:
        return {"dp": n_devices // 4, "tp": 1, "sp": 1, "pp": 2, "ep": 2}
    if n_devices % 2 == 0:
        return {"dp": n_devices // 2, "tp": 1, "sp": 1, "pp": 1, "ep": 2}
    return {"dp": n_devices, "tp": 1, "sp": 1, "pp": 1, "ep": 1}


def param_specs(cfg) -> dict:
    """PartitionSpecs for the transformer parameter pytree (see models/transformer.py).

    Layout follows the megatron-style convention: column-parallel then row-parallel —
    wq/wk/wv and w_gate/w_up shard their output dim over ``tp``; wo and w_down shard
    their input dim over ``tp``; embeddings shard vocab over ``tp``; norms replicate.
    """
    from jax.sharding import PartitionSpec as P

    return {
        "embed": P(TP, None),  # [V, D]
        "layers": {
            "attn_norm": P(None, None),  # [L, D]
            "wq": P(None, None, TP),  # [L, D, H*dh]
            "wk": P(None, None, TP),  # [L, D, Hkv*dh]
            "wv": P(None, None, TP),  # [L, D, Hkv*dh]
            "wo": P(None, TP, None),  # [L, H*dh, D]
            "mlp_norm": P(None, None),  # [L, D]
            "w_gate": P(None, None, TP),  # [L, D, F]
            "w_up": P(None, None, TP),  # [L, D, F]
            "w_down": P(None, TP, None),  # [L, F, D]
        },
        "final_norm": P(None),  # [D]
        "lm_head": P(None, TP),  # [D, V]
    }


def moe_param_specs(cfg) -> dict:
    """PartitionSpecs for the MoE parameter pytree (see models/moe.py).

    The dense per-layer MLP is replaced by a replicated router and experts stacked
    on an ``[E]`` axis sharded over ``ep``; within each expert the SwiGLU weights
    keep the megatron column/row split over ``tp``. The stacked ``[L]`` layer axis
    shards over ``pp`` when the pipeline runs (``layer_axis="pp"``).
    """
    from jax.sharding import PartitionSpec as P

    specs = param_specs(cfg)
    layers = dict(specs["layers"])
    for k in ("w_gate", "w_up", "w_down"):
        del layers[k]
    layers["w_router"] = P(None, None, None)  # [L, D, E]
    layers["we_gate"] = P(None, EP, None, TP)  # [L, E, D, F]
    layers["we_up"] = P(None, EP, None, TP)  # [L, E, D, F]
    layers["we_down"] = P(None, EP, TP, None)  # [L, E, F, D]
    specs["layers"] = layers
    return specs


def pipeline_layer_specs(layer_specs: dict) -> dict:
    """Prepend ``pp`` to the leading stacked-``[L]`` dim of every per-layer spec, so
    each pipeline stage holds only its own layers."""
    from jax.sharding import PartitionSpec as P

    return {k: P(PP, *spec[1:]) for k, spec in layer_specs.items()}


def batch_spec():
    from jax.sharding import PartitionSpec as P

    return P(DP, SP)  # tokens [B, T]


def tree_shardings(mesh, specs):
    """Map a spec pytree to NamedShardings on ``mesh``."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )


def axis_sizes(mesh) -> dict[str, int]:
    """``{axis name: size}`` of a Mesh — the form the elastic reshard layout
    (``checkpoint/reshard.py``) consumes."""
    return {str(n): int(s) for n, s in zip(mesh.axis_names, mesh.devices.shape)}


def checkpoint_layout(mesh, tree, spec_tree, ranks: Optional[Sequence[int]] = None):
    """A :class:`~tpu_resiliency.checkpoint.reshard.TreeLayout` for saving
    ``tree`` (this rank's LOCAL pytree) sharded per ``spec_tree`` on ``mesh``.

    This is the save-side half of elastic resharding: pass the result to
    ``LocalCheckpointManager.save(..., layout=...)`` and any later world —
    shrunk, grown, or re-split — can resume via ``load_resharded``. ``ranks``
    defaults to one rank per mesh device position (``range(n)``); pass the
    job's actual global rank order when it differs."""
    from tpu_resiliency.checkpoint.reshard import TreeLayout

    sizes = axis_sizes(mesh)
    if ranks is None:
        import numpy as _np

        ranks = range(int(_np.prod(mesh.devices.shape, dtype=_np.int64)))
    # Mesh axis order is authoritative (row-major rank grid follows it).
    axes = [(n, sizes[n]) for n in map(str, mesh.axis_names)]
    return TreeLayout.for_local_tree(tree, spec_tree, axes, list(ranks))
