"""Parallelism package. ``pipeline`` is intentionally NOT imported here: it pulls in
jax at module import, while ``mesh`` keeps jax imports inside function bodies so
jax-free host-side processes (launcher, telemetry hosts) can use the mesh math.
Import it directly: ``from tpu_resiliency.parallel import pipeline``."""

from tpu_resiliency.parallel import mesh

__all__ = ["mesh"]
