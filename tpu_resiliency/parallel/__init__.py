from tpu_resiliency.parallel import mesh

__all__ = ["mesh"]
