"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp`` mesh axis.

The stacked ``[L]`` layer axis of the models' parameter pytrees shards into
``pp`` contiguous stages (each device holds ``L/pp`` layers and scans over them —
the same single-trace layer body as the unpipelined models). Microbatches flow
through the stage ring as a ``lax.ppermute`` of the activation carry: at tick ``t``
stage 0 ingests microbatch ``t``, every stage applies its layers, and the result
rotates one hop so stage ``s`` processes microbatch ``t - s``. After
``n_micro + pp - 1`` ticks every microbatch has crossed every stage; the last
stage's results are ``psum``-replicated back over ``pp``. Bubble-tick compute is
masked out of the output (and therefore out of the gradients — ``ppermute`` and the
masks are linear, so ``jax.grad`` derives the reverse schedule automatically; no
hand-written backward pass).

TPU-first choices:
- the schedule is a ``lax.scan`` over ticks — one compiled program, no per-tick
  dispatch, static shapes throughout;
- ``shard_map`` is manual over ``pp`` ONLY (``axis_names={'pp'}``): everything
  inside the stage body stays auto-sharded, so tensor-parallel (``tp``) matmuls
  and expert-parallel (``ep``) dispatch compose with pipelining without any
  pipeline-specific code in the models;
- stage hops are nearest-neighbor ``ppermute`` — the cheapest ICI pattern.

The reference implements no parallelism (SURVEY.md §2.7 checklist); this exists so
the resiliency framework is exercised against the full (dp, tp, sp, pp, ep) mesh
its rank-topology components (Tree layers, replication cliques) are built for.

Composition limits: ring attention (``sp > 1``) is itself a ``shard_map`` and does
not nest inside the pipeline body; pipelined configs run dense attention
(``sp == 1`` — enforced), while long-context jobs shard ``sp`` without ``pp``.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_resiliency.parallel.mesh import PP, SP


def make_stacked_pipeline(mesh, layer_fn: Callable, n_micro: int, axis_name: str = PP):
    """Build ``apply(layers, carries, consts) -> carries_out``.

    - ``layers``: pytree whose leaves stack the layer axis ``[L, ...]``; ``L`` must
      divide evenly into ``mesh.shape[axis_name]`` stages.
    - ``carries``: pytree whose leaves have leading ``[n_micro]`` — one activation
      carry per microbatch (e.g. ``(x,)`` or ``(x, aux)``).
    - ``consts``: pytree of per-call constants replicated to every stage (e.g. RoPE
      tables).
    - ``layer_fn(carry, lp, consts) -> carry`` applies ONE layer.
    """
    n_stages = mesh.shape[axis_name]
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def stage_fn(layers_local, carry, consts):
        def body(c, lp):
            return layer_fn(c, lp, consts), None

        c, _ = lax.scan(body, carry, layers_local)
        return c

    def apply(layers, carries, consts):
        # On CPU meshes only, everything crossing the auto/manual boundary
        # travels in f32: the replicated-over-pp inputs transpose to a psum in
        # the backward pass, and XLA's CPU AllReducePromotion pass miscompiles
        # the bf16 all-reduce / reduce-scatter that boundary would otherwise
        # emit ("Invalid binary instruction opcode copy"). On TPU the bug does
        # not apply and the cast would double boundary transfer and memory for
        # bf16 activations, so the carries keep their own dtypes there. Gated
        # on the platform of the mesh that executes this shard_map, not the
        # process default backend — they differ in mixed-backend debugging.
        f32_boundary = mesh.devices.flat[0].platform == "cpu"
        dtypes = jax.tree.map(lambda a: a.dtype, carries)

        def _to_boundary(a):
            return a.astype(jnp.float32) if f32_boundary else a

        def body(layers_local, carries32, consts):
            carries_local = jax.tree.map(
                lambda a, dt: a.astype(dt), carries32, dtypes
            )
            s = lax.axis_index(axis_name)
            state = jax.tree.map(lambda a: a[0], carries_local)
            out = jax.tree.map(jnp.zeros_like, carries_local)

            def tick(carry, t):
                state, out = carry
                y = stage_fn(layers_local, state, consts)
                # The last stage emits microbatch t-(n_stages-1)'s final
                # activation. Every stage writes its buffer, but only the last
                # stage's buffer is read back (all_gather + static index below).
                widx = t - (n_stages - 1)
                ok = widx >= 0

                def write(o, yl):
                    upd = lax.dynamic_update_slice_in_dim(
                        o,
                        yl[None].astype(o.dtype),
                        jnp.clip(widx, 0, n_micro - 1),
                        axis=0,
                    )
                    return jnp.where(ok, upd, o)

                out = jax.tree.map(write, out, y)
                nxt = lax.ppermute(y, axis_name, fwd) if n_stages > 1 else y
                inj = jax.tree.map(
                    lambda c: lax.dynamic_index_in_dim(
                        c, jnp.clip(t + 1, 0, n_micro - 1), axis=0, keepdims=False
                    ),
                    carries_local,
                )
                state = jax.tree.map(lambda a, b: jnp.where(s == 0, a, b), inj, nxt)
                return (state, out), None

            (_, out), _ = lax.scan(
                tick, (state, out), jnp.arange(n_micro + n_stages - 1)
            )
            # Replicate the last stage's buffer to every stage (boundary dtype
            # per _to_boundary above).
            return jax.tree.map(
                lambda o: lax.all_gather(_to_boundary(o), axis_name, axis=0)[
                    n_stages - 1
                ],
                out,
            )

        sharded = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), P(), P()),
            out_specs=P(),
            axis_names={axis_name},
            check_vma=False,
        )
        out_b = sharded(layers, jax.tree.map(_to_boundary, carries), consts)
        return jax.tree.map(lambda o, dt: o.astype(dt), out_b, dtypes)

    return apply


def _check_pipeline_mesh(mesh, cfg, n_micro):
    n_stages = mesh.shape[PP]
    if cfg.n_layers % n_stages != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={n_stages}")
    if mesh.shape.get(SP, 1) != 1:
        raise ValueError(
            "pipelined configs run dense attention: ring attention (sp > 1) is a "
            "shard_map and does not nest inside the pp stage body"
        )
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1")


def make_pipelined_loss_fn(cfg, mesh, n_micro: int, family: str = "dense"):
    """Cross-entropy loss with the layer stack pipelined over ``pp``.

    ``family``: ``"dense"`` (``models.transformer``) or ``"moe"``
    (``models.moe`` — router aux rides the microbatch carry and is averaged).
    """
    from tpu_resiliency.models import moe as moe_mod
    from tpu_resiliency.models import transformer as tfm

    _check_pipeline_mesh(mesh, cfg, n_micro)

    if family == "dense":

        def layer_fn(carry, lp, consts):
            (x,) = carry
            cos, sin = consts
            return (tfm._layer(cfg, x, lp, cos, sin, tfm._attention),)

    elif family == "moe":

        def layer_fn(carry, lp, consts):
            x, aux = carry
            cos, sin = consts
            x, layer_aux = moe_mod._moe_layer(cfg, x, lp, cos, sin, tfm._attention)
            return (x, aux + layer_aux)

    else:
        raise ValueError(f"unknown family: {family!r}")

    pipeline = make_stacked_pipeline(mesh, layer_fn, n_micro)

    def loss_fn(params, tokens):
        B, T = tokens.shape
        if B % n_micro != 0:
            raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
        mb = B // n_micro
        x = params["embed"].astype(cfg.dtype)[tokens]
        cos, sin = tfm.rope_tables(cfg, T)
        mbs = x.reshape(n_micro, mb, T, -1)
        if family == "moe":
            carries = (mbs, jnp.zeros((n_micro,), jnp.float32))
        else:
            carries = (mbs,)
        out = pipeline(params["layers"], carries, (cos, sin))
        x = out[0].reshape(B, T, -1)
        x = tfm.rms_norm(x, params["final_norm"])
        logits = (x @ params["lm_head"].astype(x.dtype)).astype(jnp.float32)

        logits = logits[:, :-1]
        targets = tokens[:, 1:]
        loss = tfm.token_nll(logits, targets).mean()
        if family == "moe":
            loss = loss + cfg.router_aux_weight * out[1].mean() / cfg.n_layers
        return loss

    return loss_fn


def make_pipelined_train_step(cfg, mesh, n_micro: int, family: str = "dense", optimizer=None):
    """(train_step, init_opt_state) with the layer stack pipelined over ``pp`` —
    same contract as the models' ``make_train_step``."""
    from tpu_resiliency.models.transformer import make_train_step_from_loss

    return make_train_step_from_loss(
        make_pipelined_loss_fn(cfg, mesh, n_micro, family), optimizer
    )
