"""Pallas TPU kernel for the telemetry reduction stage: fused masked median + totals.

The hot part of a scoring round is reducing raw timing windows ``[R, S, W]`` to
per-(rank, signal) medians and time-weights — the work the reference does with Python
loops over per-kernel deques + ``torch`` stats on host (``straggler/straggler.py:172-197``,
``reporting.py``'s pack/unpack). Here it is one Pallas kernel, tiled over ranks, that:

1. masks invalid ring-buffer slots (slot index ≥ count) to +inf,
2. computes each element's *stable rank* within its window via W compare/accumulate
   passes on the VPU (no sort, no gather — selection by rank counting, which maps onto
   TPU vector units far better than a bitonic network),
3. selects the median as the mean of the ``(n-1)//2``-th and ``n//2``-th order
   statistics by masked summation,
4. computes the masked total (the weight) in the same pass over VMEM-resident data.

The downstream scoring math (cross-rank min, weighted perf score, robust-z, EWMA) is
plain ``jnp`` in ``telemetry/scoring.py`` — it is O(R·S) and XLA fuses it into a couple
of reductions.

Measured on v5e-1 (4096×64×32) by **on-device program duration** (the only trustworthy
methodology here — BASELINE.md "measurement-integrity note"): this kernel's scoring
round runs in **4.31 ms vs 8.43 ms** for XLA's sort-based ``masked_median`` lowering —
a 2.0× win, identical F1. It is therefore the **default window reduction on TPU** for
the mesh scoring path (``MeshTelemetry(use_pallas=None)`` auto-selects by backend and
shape via :func:`pallas_supported`); non-TPU backends use the XLA lowering. Earlier
rounds' conclusions ("loses 100×", then "parity") were wall-clock measurement
artifacts. Rank-counting is O(W²), so auto-selection caps it at the measured
window crossover and switches to the O(32·W) radix-select kernel beyond it
(``auto_mode``); ``scripts/bench_pallas_sweep.py`` measures all three variants.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _median_weights_kernel(data_ref, counts_ref, med_ref, weight_ref):
    data = data_ref[:]  # [RT, S, W] f32
    counts = counts_ref[:]  # [RT, S] i32
    rt, s, w = data.shape

    pos = jax.lax.broadcasted_iota(jnp.int32, (rt, s, w), dimension=2)
    valid = pos < counts[:, :, None]
    x = jnp.where(valid, data, jnp.inf)

    # Stable rank of each element within its window:
    #   rank_i = #{j : x_j < x_i} + #{j < i : x_j == x_i}
    # computed with W VPU compare passes in a fori_loop (bounded live temps — a static
    # unroll blows the VMEM stack). The j-th element is extracted with a positional
    # mask + reduction rather than dynamic_slice, which this Pallas lowering lacks.
    rank = jnp.zeros((rt, s, w), jnp.int32)

    def body(j, rank):
        sel = pos == j
        xj = jnp.sum(jnp.where(sel, x, 0.0), axis=2, keepdims=True)  # [RT, S, 1]
        xj = jnp.where(j < counts[:, :, None], xj, jnp.inf)  # invalid slot ⇒ +inf
        less = (xj < x).astype(jnp.int32)
        eq_before = ((xj == x) & (j < pos)).astype(jnp.int32)
        return rank + less + eq_before

    rank = jax.lax.fori_loop(0, w, body, rank)
    _write_median_and_weight(data, counts, valid, rank, med_ref, weight_ref)


def _write_median_and_weight(data, counts, valid, rank, med_ref, weight_ref):
    """Shared selection tail: median = mean of the (n-1)//2-th and n//2-th order
    statistics picked by rank equality; weight = masked total."""
    n = jnp.maximum(counts, 1)
    lo_idx = ((n - 1) // 2)[:, :, None]
    hi_idx = (n // 2)[:, :, None]
    x_finite = jnp.where(valid, data, 0.0)
    lo = jnp.sum(jnp.where(rank == lo_idx, x_finite, 0.0), axis=2)
    hi = jnp.sum(jnp.where(rank == hi_idx, x_finite, 0.0), axis=2)
    med = 0.5 * (lo + hi)
    med_ref[:] = jnp.where(counts > 0, med, jnp.inf)
    weight_ref[:] = jnp.sum(x_finite, axis=2)


def _median_weights_pairwise_kernel(data_ref, counts_ref, med_ref, weight_ref):
    """All-pairs variant: one [RT, S, W, W] comparison block instead of W
    sequential VPU passes — more VMEM (quadratic temporaries, so it runs at a
    smaller rank tile) but no serial loop. Which formulation wins is measured, not
    assumed: bench.py times both as separate variants on the real chip."""
    data = data_ref[:]  # [RT, S, W] f32
    counts = counts_ref[:]  # [RT, S] i32
    rt, s, w = data.shape

    pos = jax.lax.broadcasted_iota(jnp.int32, (rt, s, w), dimension=2)
    valid = pos < counts[:, :, None]
    x = jnp.where(valid, data, jnp.inf)

    xi = x[:, :, :, None]  # the element whose rank we compute
    xj = x[:, :, None, :]  # everything it is compared against
    pi = pos[:, :, :, None]
    pj = pos[:, :, None, :]
    rank = jnp.sum(
        (xj < xi).astype(jnp.int32) + ((xj == xi) & (pj < pi)).astype(jnp.int32),
        axis=3,
    )
    _write_median_and_weight(data, counts, valid, rank, med_ref, weight_ref)


def _radix_select(x, key, cand0, k):
    """Exact k-th smallest (0-indexed among ``cand0`` elements) per trailing-W
    group via MSB-first radix selection on the 32 sort-key bits: 32 masked
    count-and-narrow passes, O(32·W) — the O(W·log) formulation that keeps the
    Pallas path winning where rank-counting's O(W²) would hand large windows
    back to the XLA sort. All remaining candidates after 32 bits share the
    selected value bit-for-bit, so extraction is a masked min.

    Mosaic constraint (hit on real v5e, invisible in interpret mode): ``i1``
    vectors cannot be reshaped (``tpu.reshape vector<...xi1>`` is rejected), so
    the candidate mask and the branch predicate are carried as int32 0/1 and
    only compared elementwise — never broadcast with ``[..., None]`` as bools."""
    def body(i, carry):
        cand, k = carry  # cand: int32 0/1 mask [.., W]; k: int32 [..]
        bit = 31 - i
        # Bits of the UNSIGNED order key u = key ^ 0x80000000: bit 31 is the
        # inverted sign of the signed key (XOR with 1 exactly when bit == 31);
        # bits 30..0 coincide with key's.
        raw = jax.lax.shift_right_logical(key, bit) & 1
        bitval = raw ^ (bit == 31).astype(jnp.int32)
        c0 = jnp.sum(cand * (1 - bitval), axis=-1)
        go_zero = (k < c0).astype(jnp.int32)
        want = 1 - go_zero[..., None]  # desired bit value in the kept branch
        cand = cand * (bitval == want).astype(jnp.int32)
        k = k - (1 - go_zero) * c0
        return cand, k

    cand, _ = jax.lax.fori_loop(0, 32, body, (cand0.astype(jnp.int32), k))
    return jnp.min(jnp.where(cand == 1, x, jnp.inf), axis=-1)


def _median_weights_radix_kernel(data_ref, counts_ref, med_ref, weight_ref):
    """O(W·log)-class variant: radix-select both median order statistics
    instead of rank-counting. 64 VPU passes total regardless of W, so it is the
    auto-selected mode past the loop kernel's measured window cap. Assumes no
    NaNs (timing windows; invalid slots are masked before keying)."""
    data = data_ref[:]  # [RT, S, W] f32
    counts = counts_ref[:]  # [RT, S] i32
    rt, s, w = data.shape

    pos = jax.lax.broadcasted_iota(jnp.int32, (rt, s, w), dimension=2)
    valid = pos < counts[:, :, None]
    x = jnp.where(valid, data, jnp.inf)

    # Monotone float→int32 key: signed comparison of the key matches float
    # order (non-negatives keep their bits; negatives bit-complement then flip
    # the sign bit).
    b = jax.lax.bitcast_convert_type(x, jnp.int32)
    key = jnp.where(b >= 0, b, jnp.bitwise_xor(jnp.bitwise_not(b), jnp.int32(-(2**31))))

    n = jnp.maximum(counts, 1)
    lo = _radix_select(x, key, valid, (n - 1) // 2)
    hi = _radix_select(x, key, valid, n // 2)
    med = 0.5 * (lo + hi)
    med_ref[:] = jnp.where(counts > 0, med, jnp.inf)
    weight_ref[:] = jnp.sum(jnp.where(valid, data, 0.0), axis=2)


#: Largest window the O(W²) kernels (loop / pairwise) are auto-selected for;
#: beyond it auto-selection switches to the radix kernel (O(32·W), no cap)
#: instead of falling back to the XLA sort. MEASURED on v5e
#: (``BENCH_pallas_sweep.json``, device-true, W∈{32..256} × R∈{256..4096}):
#: the loop kernel beats both the XLA sort and the radix kernel at every
#: tested R for W≤128 (up to 2.0×; the only counter-reads are ≤0.8%
#: small-R ties at W=64, within noise, against a 25% loop win at R=4096),
#: and loses hard at W=256 (XLA 2.2–3.7× faster) — so the measured cap
#: is 128. Operators re-derive it per device via
#: ``scripts/bench_pallas_sweep.py`` → ``$TPU_RESILIENCY_PALLAS_MAX_WINDOW``.
DEFAULT_MAX_WINDOW = 128
MAX_WINDOW_ENV = "TPU_RESILIENCY_PALLAS_MAX_WINDOW"

#: Opt-in for AUTO-selecting the radix kernel past the loop cap (explicit
#: ``mode="radix"`` always works). Default off, now on measurement rather
#: than absence of it (``BENCH_pallas_sweep.json``): radix's pass cost is
#: flat in W but loses to the loop kernel at every W≤128 (where the loop is
#: auto-selected anyway) and to the XLA sort at W=128 (19.7 vs 18.0 ms at
#: R=4096); at W=256 — the one regime it could win (projected ~20 vs
#: 22.8 ms) — it currently fails to Mosaic-compile on v5e. Flip only once a
#: sweep shows it compiling AND beating the sort past the loop cap.
RADIX_ENV = "TPU_RESILIENCY_PALLAS_RADIX"
DEFAULT_RADIX_AUTO = False

#: Modes whose work grows quadratically with the window (subject to the cap).
_QUADRATIC_MODES = ("loop", "pairwise")

#: Pairwise has its own, smaller bound: the sweep measured it compiling only
#: at W=32 on v5e (S-folded; Mosaic rejects its 4-D blocks at W=64 even
#: folded) and losing to the loop kernel 4-5x where it runs — the shared
#: loop cap must not re-open a gate the measurement closed.
PAIRWISE_MAX_WINDOW = 32


def max_auto_window() -> int:
    import os

    try:
        return int(os.environ.get(MAX_WINDOW_ENV, DEFAULT_MAX_WINDOW))
    except ValueError:
        return DEFAULT_MAX_WINDOW


def radix_auto_enabled() -> bool:
    import os

    v = os.environ.get(RADIX_ENV)
    if v is None:
        return DEFAULT_RADIX_AUTO
    return v.strip().lower() in ("1", "on", "true", "yes")


def auto_mode(window: int) -> str:
    """Mode choice for an auto-selected Pallas path: the measured-winning
    quadratic ``loop`` kernel up to the window cap, the scaling-safe ``radix``
    kernel beyond it."""
    return "loop" if window <= max_auto_window() else "radix"


def default_rank_tile(mode: str) -> int:
    # pairwise materializes [RT, S, W, W] temporaries — quadratic VMEM, so it
    # runs at a much smaller rank tile.
    return 8 if mode == "pairwise" else 32


#: Largest [RT, S, W] element count a default block may hold, per mode —
#: each set to the largest block PROVEN to Mosaic-compile on v5e by the live
#: sweep. The radix kernel carries more concurrent W-sized temporaries than
#: the loop kernel (x, int32 key, candidate mask, plus the selection
#: carries): its compile fails at 32·64·256-element blocks (≈2 MB/array, ~6
#: live arrays brushes VMEM) while every 32·64·128 block is proven. The loop
#: kernel compiled and ran at 32·64·256 (the W=256 sweep column), so its
#: budget is 2× radix's. Default tiles halve until the block fits the
#: budget. Halving preserves the gate-checked divisibility only when 32 | R;
#: for other admitted rank counts :func:`_snap_tile` snaps to the largest
#: divisor of R within budget (and both the gate and the kernel reject the
#: degenerate near-prime-R grids that snap produces, as well as single
#: rank-rows that already exceed the budget).
MODE_BLOCK_ELEMS = {
    "loop": 32 * 64 * 256,
    "radix": 32 * 64 * 128,
}

#: Snapped tiles more than this factor below the budget tile mean a
#: near-prime rank count shattered the grid into many tiny blocks — a
#: pathological launch far slower than the XLA sort, rejected loudly like
#: pairwise's near-prime S fold. Relative (not an absolute tile floor): a
#: snapped tile of 7 on a budget of 8 is a fine 2-block grid at R=14, while
#: a snapped tile of 1 on a budget of 16 is a 31-block shatter at R=31.
SNAP_SHATTER_FACTOR = 4


def mode_rank_tile(mode: str, s: int, w: int, base: int = 32) -> int:
    tile = base
    budget = MODE_BLOCK_ELEMS[mode]
    while tile > 1 and tile * s * w > budget:
        tile //= 2
    return tile


def _pairwise_fold_divisor(s: int) -> int:
    """Largest signal-group size ≤32 that divides ``s`` — the S-fold unit the
    pairwise kernel uses to stay under Mosaic's 4-D block limit. Shared by the
    kernel's fold path and the shape gate so both always agree on which
    near-prime signal counts are rejected (< 8 degenerates the grid)."""
    return next(d for d in range(32, 0, -1) if s % d == 0)


def _snap_tile(mode: str, r: int, s: int, w: int, base: int = 32) -> int | None:
    """Default tile for ``[r, s, w]`` in a budgeted mode: the largest divisor
    of ``r`` within the VMEM budget. ``None`` marks the shapes callers must
    reject: a single rank-row already over budget (no tile can fit), or a
    degenerate divisor far below the budget tile (shattered grid)."""
    if s * w > MODE_BLOCK_ELEMS[mode]:
        return None
    shrunk = min(mode_rank_tile(mode, s, w, base), r)
    snapped = next(d for d in range(shrunk, 0, -1) if r % d == 0)
    if snapped * SNAP_SHATTER_FACTOR < shrunk:
        return None
    return snapped


def pallas_supported(
    n_ranks: int,
    rank_tile: int | None = None,
    mode: str | None = None,
    window: int | None = None,
    signals: int | None = None,
) -> bool:
    """Shape gate for auto-selection: the kernel tiles the rank axis, so the
    per-shard rank count must be a whole number of tiles (or fit in one). Pass
    the same ``mode``/``rank_tile`` that will be given to
    :func:`fused_median_weights`; ``mode=None`` means :func:`auto_mode` (which
    needs ``window``). Pass ``signals`` too when known: the budgeted modes'
    (loop/radix) VMEM block budget can shrink their default tile, and only
    with the signal count can the gate mirror that shrink (and reject the
    near-prime rank counts whose snapped tile degenerates, or single
    rank-rows that exceed the budget outright).

    An explicitly quadratic ``mode`` is rejected past the measured window cap —
    auto-selection must not hand a W=128 user a silent O(W²) blowup. With mode
    auto, windows past the cap route to the radix kernel only once it is
    device-measured/opted-in (:func:`radix_auto_enabled`); until then they
    fall back to the XLA sort."""
    if mode is None:
        mode = auto_mode(window) if window is not None else "loop"
        if mode == "radix" and not radix_auto_enabled():
            return False
    elif window is not None and mode in _QUADRATIC_MODES:
        cap = PAIRWISE_MAX_WINDOW if mode == "pairwise" else max_auto_window()
        if window > cap:
            return False
    if signals is not None and mode == "pairwise" and signals > 32:
        # Mirror the kernel's S-fold rejection (Mosaic caps its 4-D block at
        # S<=32; a near-prime S has no usable fold divisor and raises there).
        if _pairwise_fold_divisor(signals) < 8:
            return False
    if rank_tile is None:
        rank_tile = default_rank_tile(mode)
        if mode in MODE_BLOCK_ELEMS and window is not None and signals is not None:
            snapped = _snap_tile(mode, n_ranks, signals, window, rank_tile)
            if snapped is None:
                return False
            rank_tile = snapped
    tile = min(rank_tile, n_ranks)
    return tile > 0 and n_ranks % tile == 0


_KERNELS = {
    "loop": _median_weights_kernel,
    "pairwise": _median_weights_pairwise_kernel,
    "radix": _median_weights_radix_kernel,
}


@functools.partial(jax.jit, static_argnames=("rank_tile", "interpret", "mode"))
def fused_median_weights(
    data: jax.Array,
    counts: jax.Array,
    *,
    rank_tile: int | None = None,
    interpret: bool | None = None,
    mode: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """``(medians [R,S], weights [R,S])`` from windows ``data [R,S,W]``, ``counts [R,S]``.

    Tiled over the rank axis; each grid step holds a ``[rank_tile, S, W]`` block in
    VMEM. ``interpret`` defaults to True off-TPU so tests run on CPU. ``mode``:
    ``"loop"`` (W rank-counting passes, O(W²), rank_tile 32), ``"pairwise"``
    (one [RT, S, W, W] comparison block, rank_tile 8 for the quadratic VMEM
    temporaries), ``"radix"`` (64 bit-select passes, O(32·W) — scales to large
    windows), or ``None`` for the measured :func:`auto_mode` by window size.
    """
    r, s, w = data.shape
    if mode is None:
        mode = auto_mode(w)
    if mode not in _KERNELS:
        raise ValueError(f"unknown mode {mode!r}; one of {sorted(_KERNELS)}")
    kernel = _KERNELS[mode]
    if rank_tile is None:
        rank_tile = default_rank_tile(mode)
        if mode in MODE_BLOCK_ELEMS:
            snapped = _snap_tile(mode, r, s, w, rank_tile)
            if snapped is None:
                # Mirror the pairwise near-prime-S rejection: over-budget
                # blocks fail Mosaic, shattered grids silently run far
                # slower than the XLA sort — both fail loudly here.
                detail = (
                    f"a single rank-row ({s}x{w} elements) exceeds the VMEM "
                    f"block budget ({MODE_BLOCK_ELEMS[mode]})"
                    if s * w > MODE_BLOCK_ELEMS[mode]
                    else f"rank count {r} has no divisor near the budget "
                    f"tile {mode_rank_tile(mode, s, w)} (within "
                    f"{SNAP_SHATTER_FACTOR}x) — the grid would shatter"
                )
                raise ValueError(
                    f"{mode} mode at window {w}: {detail}; pass rank_tile "
                    f"explicitly or use the XLA path"
                )
            rank_tile = snapped
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rank_tile = min(rank_tile, r)
    if r % rank_tile != 0:
        raise ValueError(f"ranks {r} not divisible by rank_tile {rank_tile}")

    # Mosaic rejects pairwise's 4-D all-pairs block once S reaches 64 (fine at
    # S≤32, measured on v5e). The kernel is independent per (rank, signal), so
    # large-S inputs are folded — signal groups moved onto the rank axis with
    # plain XLA reshapes outside the kernel — and each block sees S'≤32.
    # (Tiling S inside the grid instead is illegal: 2-D operand blocks must
    # keep their last dim full or 128-divisible.)
    if mode == "pairwise" and s > 32:
        st = _pairwise_fold_divisor(s)
        if st < 8:
            # A near-prime S would degenerate to single-signal blocks — a
            # pathological grid far slower than the XLA sort. Fail loudly.
            raise ValueError(
                f"pairwise mode needs a signal count with a divisor in [8, 32] "
                f"to fold S={s} under Mosaic's S<=32 limit (best divisor: {st})"
            )
        fold = s // st
        med, wt = fused_median_weights(
            data.reshape(r * fold, st, w),
            counts.reshape(r * fold, st),
            rank_tile=rank_tile,
            interpret=interpret,
            mode=mode,
        )
        return med.reshape(r, s), wt.reshape(r, s)

    grid = (r // rank_tile,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rank_tile, s, w), lambda i: (i, 0, 0)),
            pl.BlockSpec((rank_tile, s), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rank_tile, s), lambda i: (i, 0)),
            pl.BlockSpec((rank_tile, s), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, s), data.dtype),
            jax.ShapeDtypeStruct((r, s), data.dtype),
        ],
        interpret=interpret,
    )(data, counts)
