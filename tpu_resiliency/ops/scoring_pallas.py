"""Pallas TPU kernel for the telemetry reduction stage: fused masked median + totals.

The hot part of a scoring round is reducing raw timing windows ``[R, S, W]`` to
per-(rank, signal) medians and time-weights — the work the reference does with Python
loops over per-kernel deques + ``torch`` stats on host (``straggler/straggler.py:172-197``,
``reporting.py``'s pack/unpack). Here it is one Pallas kernel, tiled over ranks, that:

1. masks invalid ring-buffer slots (slot index ≥ count) to +inf,
2. computes each element's *stable rank* within its window via W compare/accumulate
   passes on the VPU (no sort, no gather — selection by rank counting, which maps onto
   TPU vector units far better than a bitonic network),
3. selects the median as the mean of the ``(n-1)//2``-th and ``n//2``-th order
   statistics by masked summation,
4. computes the masked total (the weight) in the same pass over VMEM-resident data.

The downstream scoring math (cross-rank min, weighted perf score, robust-z, EWMA) is
plain ``jnp`` in ``telemetry/scoring.py`` — it is O(R·S) and XLA fuses it into a couple
of reductions.

Measured on v5e-1 (4096×64×32) by **on-device program duration** (the only trustworthy
methodology here — BASELINE.md "measurement-integrity note"): this kernel's scoring
round runs in **4.31 ms vs 8.43 ms** for XLA's sort-based ``masked_median`` lowering —
a 2.0× win, identical F1. It is therefore the **default window reduction on TPU** for
the mesh scoring path (``MeshTelemetry(use_pallas=None)`` auto-selects by backend and
shape via :func:`pallas_supported`); non-TPU backends use the XLA lowering. Earlier
rounds' conclusions ("loses 100×", then "parity") were wall-clock measurement
artifacts. Caveat: rank-counting is O(W²) — re-measure before large windows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _median_weights_kernel(data_ref, counts_ref, med_ref, weight_ref):
    data = data_ref[:]  # [RT, S, W] f32
    counts = counts_ref[:]  # [RT, S] i32
    rt, s, w = data.shape

    pos = jax.lax.broadcasted_iota(jnp.int32, (rt, s, w), dimension=2)
    valid = pos < counts[:, :, None]
    x = jnp.where(valid, data, jnp.inf)

    # Stable rank of each element within its window:
    #   rank_i = #{j : x_j < x_i} + #{j < i : x_j == x_i}
    # computed with W VPU compare passes in a fori_loop (bounded live temps — a static
    # unroll blows the VMEM stack). The j-th element is extracted with a positional
    # mask + reduction rather than dynamic_slice, which this Pallas lowering lacks.
    rank = jnp.zeros((rt, s, w), jnp.int32)

    def body(j, rank):
        sel = pos == j
        xj = jnp.sum(jnp.where(sel, x, 0.0), axis=2, keepdims=True)  # [RT, S, 1]
        xj = jnp.where(j < counts[:, :, None], xj, jnp.inf)  # invalid slot ⇒ +inf
        less = (xj < x).astype(jnp.int32)
        eq_before = ((xj == x) & (j < pos)).astype(jnp.int32)
        return rank + less + eq_before

    rank = jax.lax.fori_loop(0, w, body, rank)
    _write_median_and_weight(data, counts, valid, rank, med_ref, weight_ref)


def _write_median_and_weight(data, counts, valid, rank, med_ref, weight_ref):
    """Shared selection tail: median = mean of the (n-1)//2-th and n//2-th order
    statistics picked by rank equality; weight = masked total."""
    n = jnp.maximum(counts, 1)
    lo_idx = ((n - 1) // 2)[:, :, None]
    hi_idx = (n // 2)[:, :, None]
    x_finite = jnp.where(valid, data, 0.0)
    lo = jnp.sum(jnp.where(rank == lo_idx, x_finite, 0.0), axis=2)
    hi = jnp.sum(jnp.where(rank == hi_idx, x_finite, 0.0), axis=2)
    med = 0.5 * (lo + hi)
    med_ref[:] = jnp.where(counts > 0, med, jnp.inf)
    weight_ref[:] = jnp.sum(x_finite, axis=2)


def _median_weights_pairwise_kernel(data_ref, counts_ref, med_ref, weight_ref):
    """All-pairs variant: one [RT, S, W, W] comparison block instead of W
    sequential VPU passes — more VMEM (quadratic temporaries, so it runs at a
    smaller rank tile) but no serial loop. Which formulation wins is measured, not
    assumed: bench.py times both as separate variants on the real chip."""
    data = data_ref[:]  # [RT, S, W] f32
    counts = counts_ref[:]  # [RT, S] i32
    rt, s, w = data.shape

    pos = jax.lax.broadcasted_iota(jnp.int32, (rt, s, w), dimension=2)
    valid = pos < counts[:, :, None]
    x = jnp.where(valid, data, jnp.inf)

    xi = x[:, :, :, None]  # the element whose rank we compute
    xj = x[:, :, None, :]  # everything it is compared against
    pi = pos[:, :, :, None]
    pj = pos[:, :, None, :]
    rank = jnp.sum(
        (xj < xi).astype(jnp.int32) + ((xj == xi) & (pj < pi)).astype(jnp.int32),
        axis=3,
    )
    _write_median_and_weight(data, counts, valid, rank, med_ref, weight_ref)


#: Largest window the Pallas kernel auto-selects for. Rank-counting is O(W²)
#: against XLA's O(W log W) sort: from the measured W=32 point (4.31 ms Pallas
#: vs 8.43 ms XLA, device-true), the scaling model T_pallas∝W², T_xla∝W·logW
#: puts the crossover between 64 and 128 — so the default cap is 64, the
#: largest predicted-winning size. ``scripts/bench_pallas_sweep.py`` measures
#: the real crossover per device; operators encode its result via
#: ``$TPU_RESILIENCY_PALLAS_MAX_WINDOW``.
DEFAULT_MAX_WINDOW = 64
MAX_WINDOW_ENV = "TPU_RESILIENCY_PALLAS_MAX_WINDOW"


def max_auto_window() -> int:
    import os

    try:
        return int(os.environ.get(MAX_WINDOW_ENV, DEFAULT_MAX_WINDOW))
    except ValueError:
        return DEFAULT_MAX_WINDOW


def pallas_supported(
    n_ranks: int,
    rank_tile: int | None = None,
    mode: str = "loop",
    window: int | None = None,
) -> bool:
    """Shape gate for auto-selection: the kernel tiles the rank axis, so the
    per-shard rank count must be a whole number of tiles (or fit in one). Pass the
    same ``mode`` (and ``rank_tile``, if overridden) that will be given to
    :func:`fused_median_weights` — the modes default to different tiles.

    ``window``: when given, also gate on the measured/modeled O(W²) crossover
    (:data:`DEFAULT_MAX_WINDOW`, env-overridable) — beyond it the XLA sort
    lowering wins and auto-selection must not hand a W=128 user a silent
    quadratic blowup."""
    if window is not None and window > max_auto_window():
        return False
    if rank_tile is None:
        rank_tile = 32 if mode == "loop" else 8
    tile = min(rank_tile, n_ranks)
    return tile > 0 and n_ranks % tile == 0


@functools.partial(jax.jit, static_argnames=("rank_tile", "interpret", "mode"))
def fused_median_weights(
    data: jax.Array,
    counts: jax.Array,
    *,
    rank_tile: int | None = None,
    interpret: bool | None = None,
    mode: str = "loop",
) -> tuple[jax.Array, jax.Array]:
    """``(medians [R,S], weights [R,S])`` from windows ``data [R,S,W]``, ``counts [R,S]``.

    Tiled over the rank axis; each grid step holds a ``[rank_tile, S, W]`` block in
    VMEM. ``interpret`` defaults to True off-TPU so tests run on CPU. ``mode``:
    ``"loop"`` (W sequential rank-counting passes, rank_tile 32) or ``"pairwise"``
    (one [RT, S, W, W] comparison block, rank_tile 8 for the quadratic VMEM
    temporaries).
    """
    r, s, w = data.shape
    if mode not in ("loop", "pairwise"):
        raise ValueError(f"unknown mode {mode!r}")
    kernel = _median_weights_kernel if mode == "loop" else _median_weights_pairwise_kernel
    if rank_tile is None:
        rank_tile = 32 if mode == "loop" else 8
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rank_tile = min(rank_tile, r)
    if r % rank_tile != 0:
        raise ValueError(f"ranks {r} not divisible by rank_tile {rank_tile}")

    grid = (r // rank_tile,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rank_tile, s, w), lambda i: (i, 0, 0)),
            pl.BlockSpec((rank_tile, s), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rank_tile, s), lambda i: (i, 0)),
            pl.BlockSpec((rank_tile, s), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, s), data.dtype),
            jax.ShapeDtypeStruct((r, s), data.dtype),
        ],
        interpret=interpret,
    )(data, counts)
