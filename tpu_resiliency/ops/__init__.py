from tpu_resiliency.ops.scoring_pallas import fused_median_weights

__all__ = ["fused_median_weights"]
