"""``tpu-fleetd``: the standalone fleet aggregation daemon.

One fleetd watches one ``--fleet-dir`` (the shared directory launchers with
``--fleet-dir`` register their telemetry leases in), scrapes every live job
in parallel, and serves the merged fleet view:

- ``/fleet/metrics`` — merged Prometheus exposition (``job=`` labels +
  ``fleet:*`` cross-job totals + fleetd's own operational metrics);
- ``/fleet/goodput`` — the per-job goodput scoreboard;
- ``/fleet/slo`` — jobs ranked worst-first by time-in-restart;
- ``/fleet/incidents`` — the cross-job incident feed;
- ``/fleet/hangz`` — the fleet-wide hang census;
- ``/fleet/snapshot`` — the whole fold as one offline-renderable document.

Jobs appear when their lease lands, disappear when it is removed (clean
stop) or expires (crash — fleetd unlinks stale leases itself), all without a
fleetd restart. One crashed/hung job marks that job ``unreachable``; every
fleet endpoint keeps answering 200.

Usage::

    tpu-fleetd --fleet-dir /shared/fleet                  # serve forever
    tpu-fleetd --fleet-dir /shared/fleet --port 9400
    tpu-fleetd --fleet-dir /shared/fleet --snapshot fleet.json --once
    tpu-fleet scoreboard --snapshot fleet.json            # render offline
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time
from typing import Optional

from tpu_resiliency.fleet.aggregator import FleetAggregator
from tpu_resiliency.fleet.registry import DEFAULT_TTL_S
from tpu_resiliency.fleet.server import PORT_FILE_NAME, FleetServer
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-fleetd",
        description="Fleet federation daemon: scrape every registered job's "
        "telemetry endpoint and serve the merged fleet view.",
    )
    p.add_argument(
        "--fleet-dir", required=True,
        help="shared discovery directory the launchers register their "
        "telemetry leases in (launcher --fleet-dir)",
    )
    p.add_argument(
        "--port", type=int, default=0,
        help="fleet endpoint port (0 = ephemeral; the bound port lands in "
        "--port-file)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--port-file", default=None,
        help=f"port-file handshake path (default: <fleet-dir>/{PORT_FILE_NAME})",
    )
    p.add_argument(
        "--lease-ttl", type=float, default=DEFAULT_TTL_S,
        help="seconds after which a non-refreshed lease is a dead job "
        "(expired and unlinked by the scrape loop)",
    )
    p.add_argument(
        "--scrape-timeout", type=float, default=2.0,
        help="per-job HTTP timeout: one hung job costs this much once per "
        "scrape, never the fleet endpoint",
    )
    p.add_argument(
        "--scrape-interval", type=float, default=5.0,
        help="background scrape cadence; endpoint requests between beats "
        "serve the cached view (--scrape-ttl)",
    )
    p.add_argument(
        "--scrape-ttl", type=float, default=2.0,
        help="endpoint-triggered scrapes are collapsed to one fan-out per "
        "this many seconds",
    )
    p.add_argument(
        "--snapshot", default=None,
        help="also persist the fleet snapshot document here (atomic write) "
        "after every scrape — the tpu-fleet offline input",
    )
    p.add_argument(
        "--once", action="store_true",
        help="one scrape: print a one-line fleet summary (and write "
        "--snapshot), then exit — for scripts and smoke tests",
    )
    return p


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    fleet_dir = os.path.abspath(args.fleet_dir)
    os.makedirs(fleet_dir, exist_ok=True)
    aggregator = FleetAggregator(
        fleet_dir,
        lease_ttl=args.lease_ttl,
        timeout=args.scrape_timeout,
    )
    server = FleetServer(
        aggregator,
        port=args.port,
        host=args.host,
        port_file=args.port_file or os.path.join(fleet_dir, PORT_FILE_NAME),
        scrape_ttl=args.scrape_ttl,
    )
    if args.once:
        view = aggregator.scrape()
        doc = view.goodput_doc()
        fleet = doc["fleet"]
        print(
            f"fleet: {fleet['jobs']} job(s), {fleet['reachable']} reachable, "
            f"goodput_ratio={fleet['goodput_ratio']} "
            f"(scrape {view.scrape_s * 1e3:.1f} ms)"
        )
        for row in doc["jobs"]:
            ratio = row.get("goodput_ratio")
            print(
                f"  {row['job']}: {row['status']}"
                + (f" ratio={ratio}" if ratio is not None else "")
                + (f" ({row['error']})" if row.get("error") else "")
            )
        if args.snapshot:
            _write_snapshot(view, args.snapshot)
            print(f"wrote {args.snapshot}")
        return 0

    stop = threading.Event()

    def _stop(signum, frame):
        log.info(f"fleetd: signal {signum}, shutting down")
        stop.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    port = server.start()
    log.info(
        f"tpu-fleetd watching {fleet_dir} on http://{args.host}:{port} "
        f"(lease ttl {args.lease_ttl}s, scrape every {args.scrape_interval}s)"
    )
    try:
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                # The background beat drives the same TTL cache the endpoints
                # read, so dashboards see a view at most interval+ttl old
                # even when nobody scrapes fleetd itself.
                view = server.view(max_age=0.0)
                if view is not None and args.snapshot:
                    _write_snapshot(view, args.snapshot)
            except Exception:
                log.warning("fleetd scrape beat failed", exc_info=True)
            elapsed = time.monotonic() - t0
            stop.wait(max(0.1, args.scrape_interval - elapsed))
    finally:
        server.stop()
    return 0


def _write_snapshot(view, path: str) -> None:
    import json

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(view.snapshot_doc(), f, indent=2, default=repr)
        f.write("\n")
    os.replace(tmp, path)


if __name__ == "__main__":
    sys.exit(main())
