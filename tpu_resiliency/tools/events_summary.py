"""Human timeline over the structured event stream.

The framework narrates every resiliency decision to a JSONL stream
(``$TPU_RESILIENCY_EVENTS_FILE``, ``utils/events.py``): rendezvous rounds,
worker failures and warm-spare promotions, in-process restart iterations,
straggler reports, preemption sync points, FT milestones. This tool is the
consumer side — it renders one run's stream as a timeline plus a summary, the
post-mortem view the reference leaves to ad-hoc log grepping (its torchelastic
events/metrics streams have no bundled reader; its tests grep log lines,
``tests/straggler/func/check_log.py``).

Usage::

    python -m tpu_resiliency.tools.events_summary run_events.jsonl
    python -m tpu_resiliency.tools.events_summary run_events.jsonl --kind worker_failed
    # comma-separated kinds compose with the time/trace slicers; the footer
    # counts the filtered slice
    python -m tpu_resiliency.tools.events_summary ev.jsonl --kind hang_detected,kill_ladder,stack_dump
    python -m tpu_resiliency.tools.events_summary run_events.jsonl --no-timeline
    python -m tpu_resiliency.tools.events_summary run_events.jsonl --follow
    # slice to one incident: absolute epoch, ISO-8601, or stream-relative +SECS
    python -m tpu_resiliency.tools.events_summary ev.jsonl --since +42 --until +97
    python -m tpu_resiliency.tools.events_summary ev.jsonl --trace 4f2a91b0c3d4e5f6
    # slice a fleet-shared stream back to one job (launcher --fleet-dir stamps
    # the job identity onto every record)
    python -m tpu_resiliency.tools.events_summary ev.jsonl --job trainer-a
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter
from typing import Any, Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.utils.events import RESERVED_KEYS, read_events


def _payload(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in RESERVED_KEYS}


def parse_when(spec: str) -> tuple[float, bool]:
    """One ``--since``/``--until`` operand → ``(seconds, relative)``.

    Three spellings, matched to how operators actually hold timestamps:
    raw epoch seconds (what the JSONL carries), ISO-8601 (what an incident
    report or pager shows — naive stamps are LOCAL time, matching
    ``datetime.fromtimestamp`` output), and ``+SECS`` relative to the stream's
    first event (what the timeline itself prints as ``t+...s``)."""
    spec = spec.strip()
    if spec.startswith("+"):
        return float(spec[1:]), True
    try:
        return float(spec), False
    except ValueError:
        pass
    import datetime

    try:
        dt = datetime.datetime.fromisoformat(spec)
    except ValueError:
        raise ValueError(
            f"cannot parse time {spec!r}: want epoch seconds, ISO-8601, "
            f"or +SECS relative to stream start"
        ) from None
    return dt.timestamp(), False


def parse_kinds(spec: Optional[str]) -> Optional[frozenset]:
    """``--kind`` operand → kind set (comma-separated; None/empty → None)."""
    if spec is None:
        return None
    kinds = frozenset(k.strip() for k in spec.split(",") if k.strip())
    return kinds or None


def make_filter(
    since: Optional[str], until: Optional[str], trace: Optional[str], t0: float,
    kinds: Optional[frozenset] = None, job: Optional[str] = None,
):
    """Record predicate for the --since/--until/--trace/--kind/--job slicers;
    ``t0`` resolves relative (+SECS) bounds. The kind set composes with the
    time/trace bounds, so timeline AND footer reflect one slice. ``job``
    matches the envelope's fleet job identity ($TPU_RESILIENCY_JOB, stamped
    by launchers running under --fleet-dir) — the slicer that takes a stream
    several jobs share back to one job."""
    lo = hi = None
    if since is not None:
        s, rel = parse_when(since)
        lo = t0 + s if rel else s
    if until is not None:
        s, rel = parse_when(until)
        hi = t0 + s if rel else s

    def keep(rec: dict) -> bool:
        ts = rec.get("ts")
        if lo is not None and (not isinstance(ts, (int, float)) or ts < lo):
            return False
        if hi is not None and (not isinstance(ts, (int, float)) or ts > hi):
            return False
        if trace is not None and rec.get("trace_id") != trace:
            return False
        if job is not None and rec.get("job") != job:
            return False
        if kinds is not None and rec.get("kind") not in kinds:
            return False
        return True

    return keep


def _fmt_default(p: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in p.items())


def _fmt_rendezvous_round(p: dict) -> str:
    spares = f" spares={p['spares']}" if p.get("spares") else ""
    return (
        f"round {p.get('round')}: world={p.get('world_size')} "
        f"active={p.get('active')}{spares}"
    )


def _fmt_worker_failed(p: dict) -> str:
    return f"rank {p.get('global_rank')} failed: {p.get('detail', p.get('exitcode'))}"


def _fmt_worker_promoted(p: dict) -> str:
    outcome = p.get("outcome", "promoted")
    if outcome == "dead_at_promotion":
        return (
            f"warm spare DIED at promotion -> rank {p.get('global_rank')} "
            f"cold-spawned (round {p.get('round')})"
        )
    if outcome == "cold_fallback":
        return (
            f"no warm spare -> rank {p.get('global_rank')} cold-spawned "
            f"(round {p.get('round')})"
        )
    depth = f", depth {p['park_depth']}" if p.get("park_depth") else ""
    return (
        f"warm spare promoted -> rank {p.get('global_rank')} "
        f"(round {p.get('round')}, pid {p.get('worker_pid')}{depth})"
    )


def _fmt_straggler_report(p: dict) -> str:
    flagged = p.get("stragglers_by_perf") or []
    by_sec = p.get("stragglers_by_section") or {}
    if not flagged and not by_sec:
        return f"step {p.get('step')}: clean ({len(p.get('perf_scores') or {})} ranks)"
    parts = []
    if flagged:
        parts.append(f"by perf {flagged}")
    if by_sec:
        parts.append(f"by section {by_sec}")
    return f"step {p.get('step')}: STRAGGLERS " + ", ".join(parts)


def _fmt_restart_signalled(p: dict) -> str:
    return (
        f"iteration {p.get('iteration')} restarting "
        f"(initial_rank {p.get('initial_rank')})"
    )


def _fmt_delta(v) -> str:
    return f"{v:+.3f}s" if isinstance(v, (int, float)) else "?"


def _fmt_autoscale_decision(p: dict) -> str:
    victims = p.get("victims") or []
    target = f" ranks {victims}" if victims else ""
    return (
        f"#{p.get('decision_id')} {p.get('action')}{target}: predicted "
        f"{_fmt_delta(p.get('predicted_delta_s'))} "
        f"[{p.get('mode')}/{p.get('outcome')}] — {p.get('reason', '')}"
    )


def _fmt_autoscale_outcome(p: dict) -> str:
    return (
        f"#{p.get('decision_id')} {p.get('action')}: predicted "
        f"{_fmt_delta(p.get('predicted_delta_s'))} realized "
        f"{_fmt_delta(p.get('realized_delta_s'))} (error "
        f"{_fmt_delta(p.get('forecast_error_s'))})"
    )


def _fmt_preemption_rescinded(p: dict) -> str:
    return (
        f"notice from step {p.get('noticed_step')} withdrawn at step "
        f"{p.get('step')}; deferred drain/save cancelled"
    )


def _fmt_alert_fired(p: dict) -> str:
    detail = p.get("detail")
    return (
        f"rule={p.get('rule')} sev={p.get('severity')} FIRING"
        + (f": {detail}" if detail else "")
    )


def _fmt_alert_resolved(p: dict) -> str:
    dur = p.get("duration_s")
    held = f" for {dur:g}s" if isinstance(dur, (int, float)) else ""
    detail = p.get("detail")
    return (
        f"rule={p.get('rule')} sev={p.get('severity')} resolved{held}"
        + (f": {detail}" if detail else "")
    )


def _fmt_store_failover(p: dict) -> str:
    ep = p.get("endpoint")
    return (
        f"shard {p.get('shard')}{f' ({ep})' if ep else ''} {p.get('op')}: "
        f"{p.get('outcome')} → successor shard {p.get('successor')}"
    )


def _fmt_shard_epoch(p: dict) -> str:
    mig = p.get("migrated")
    return (
        f"epoch {p.get('epoch')} ({p.get('nshards')} shards): "
        f"{p.get('outcome')}"
        + (f", {mig} keys migrated" if isinstance(mig, int) else "")
    )


_FORMATTERS = {
    "rendezvous_round": _fmt_rendezvous_round,
    "worker_failed": _fmt_worker_failed,
    "worker_promoted": _fmt_worker_promoted,
    "straggler_report": _fmt_straggler_report,
    "restart_signalled": _fmt_restart_signalled,
    "autoscale_decision": _fmt_autoscale_decision,
    "autoscale_outcome": _fmt_autoscale_outcome,
    "preemption_rescinded": _fmt_preemption_rescinded,
    "alert_fired": _fmt_alert_fired,
    "alert_resolved": _fmt_alert_resolved,
    "store_failover": _fmt_store_failover,
    "shard_epoch": _fmt_shard_epoch,
}

#: Kinds counted in the footer under friendlier names.
_SUMMARY_LINES = (
    ("rendezvous_round", "rendezvous rounds"),
    ("worker_failed", "worker failures"),
    ("worker_promoted", "warm-spare promotions"),
    ("restart_requested", "in-job restart requests"),
    ("restart_signalled", "in-process restarts"),
    ("fn_exception", "in-process fn exceptions"),
    ("rank_terminated", "ranks terminated"),
    ("straggler_report", "straggler reports"),
    ("degraded_set", "degraded-set updates"),
    ("preemption_sync_point", "preemption sync points"),
    ("preemption_rescinded", "preemption notices rescinded"),
    ("autoscale_decision", "autoscale decisions"),
    ("alert_fired", "watchtower alerts fired"),
    ("alert_resolved", "watchtower alerts resolved"),
    ("store_failover", "store shard failovers"),
    ("shard_epoch", "store shard-map epoch transitions"),
    ("timeouts_calculated", "FT timeout calibrations"),
    ("training_finished", "training finished"),
    ("budget_exhausted", "restart budget exhausted"),
)


def summarize(
    records: list[dict[str, Any]],
    out=None,
    kind: Optional[str] = None,
    timeline: bool = True,
    keep=None,
) -> None:
    """``keep``: optional record predicate (the --since/--until/--trace slice).
    ``kind``: comma-separated kind filter, part of the same slice — timeline
    AND footer reflect it (counting kinds the filter excluded would make the
    footer disagree with the timeline it summarizes). Sliced records drive
    both, but ``t+`` offsets stay anchored to the FULL stream's first event,
    so a sliced view's timestamps line up with the unsliced one."""
    out = sys.stdout if out is None else out  # resolved at call time, not import
    records = [r for r in records if "ts" in r and "kind" in r]
    if not records:
        print("no events", file=out)
        return
    records.sort(key=lambda r: r["ts"])
    t0 = records[0]["ts"]
    kinds = parse_kinds(kind)
    if keep is not None or kinds is not None:
        records = [
            r for r in records
            if (keep is None or keep(r)) and (kinds is None or r["kind"] in kinds)
        ]
        if not records:
            print("no events in the selected slice", file=out)
            return
    if timeline:
        for r in records:
            print(format_line(r, t0), file=out)
    _footer(
        Counter(r["kind"] for r in records),
        n_events=len(records),
        n_pids=len({r.get("pid") for r in records}),
        span=records[-1]["ts"] - t0,
        out=out,
    )


def _footer(counts: Counter, n_events: int, n_pids: int, span: float, out) -> None:
    print(
        f"\n{n_events} events over {span:.1f}s from {n_pids} processes",
        file=out,
    )
    for k, label in _SUMMARY_LINES:
        if counts.get(k):
            print(f"  {label}: {counts[k]}", file=out)
    leftover = {
        k: n for k, n in counts.items() if k not in {k for k, _ in _SUMMARY_LINES}
    }
    if leftover:
        print(f"  other: {dict(sorted(leftover.items()))}", file=out)


def format_line(rec: dict, t0: float) -> str:
    """One timeline line (shared by the batch and --follow paths)."""
    p = _payload(rec)
    line = _FORMATTERS.get(rec["kind"], _fmt_default)(p)
    rank = f" r{rec['rank']}" if rec.get("rank") is not None else ""
    return (
        f"t+{rec['ts'] - t0:9.3f}s [{rec.get('source', '?')}{rank}] "
        f"{rec['kind']}: {line}"
    )


def iter_new_records(path: str, poll: float = 0.5, stop=None):
    """Yield records as writers append them (tail -f over the JSONL stream).

    Binary-mode reads with byte offsets (a character-count offset would
    corrupt the resume position on multi-byte content from non-framework
    producers); torn trailing lines are retried whole on the next poll
    (JSONL writes are single atomic appends, so a partial line only means we
    raced the writer mid-write). Replacement detection is ``tail -F``:
    the file's identity (``st_ino``/``st_dev``) is tracked alongside its
    size, so a recreated events file from a NEW launcher run restarts the
    offset at zero even when the new file has already grown past the old
    offset by the next poll — size-shrink alone would resume mid-file at an
    arbitrary byte. A missing file is the wait state — the launcher may not
    have started — but any other OSError (directory, permission)
    propagates: an unrecoverable path must fail visibly, not hang silently.
    ``stop``: optional ``threading.Event``-like; checked each poll so tests
    (and signal handlers) can end the loop."""
    import json
    import time as _time

    offset = 0
    buf = b""
    file_id = None  # (st_ino, st_dev) of the file the offset belongs to
    while stop is None or not stop.is_set():
        try:
            with open(path, "rb") as f:
                st = os.fstat(f.fileno())
                if file_id is not None and (st.st_ino, st.st_dev) != file_id:
                    # Recreated under the same name (a new launcher run):
                    # the old offset describes a different file entirely.
                    offset = 0
                    buf = b""
                file_id = (st.st_ino, st.st_dev)
                if f.seek(0, 2) < offset:
                    # Truncated in place: restart from the top like tail -f
                    # on shrink.
                    offset = 0
                    buf = b""
                f.seek(offset)
                chunk = f.read()
        except FileNotFoundError:
            chunk = b""
        if chunk:
            offset += len(chunk)
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    yield json.loads(line.decode("utf-8"))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue
        else:
            _time.sleep(poll)


class _StdoutGone:
    """Stop-condition for --follow: fires when stdout's consumer disappears.

    A follower writing into a dead pipe exits via EPIPE on its next print —
    but a follower that is IDLE (quiet stream) never writes again and would
    linger forever after ``| head`` exits. Polling the stdout fd for
    POLLERR/POLLHUP catches the closed pipe without writing; on a terminal
    the poll simply never fires."""

    def __init__(self) -> None:
        import select

        self._poll = None
        try:
            fd = sys.stdout.fileno()
        except Exception:
            # Wrapped/captured stdout (pytest, io wrappers) has no fd: no
            # consumer-death detection, but the follower must still run.
            return
        self._poll = select.poll()
        self._poll.register(fd, select.POLLERR | select.POLLHUP)

    def is_set(self) -> bool:
        if self._poll is None:
            return False
        try:
            return bool(self._poll.poll(0))
        except OSError:
            return True


def _follow(
    path: str,
    kind: Optional[str],
    since: Optional[str] = None,
    until: Optional[str] = None,
    trace: Optional[str] = None,
    job: Optional[str] = None,
) -> int:
    # Incremental footer state, not a record list: a multi-day follow on a
    # chatty job must not grow RSS one dict per event.
    counts: Counter = Counter()
    pids: set = set()
    t0: Optional[float] = None
    last_ts = 0.0
    keep = None  # built once t0 is known (relative bounds need it)
    kinds = parse_kinds(kind)

    def emit() -> None:
        nonlocal t0, last_ts, keep
        try:
            for rec in iter_new_records(path, stop=_StdoutGone()):
                if "ts" not in rec or "kind" not in rec:
                    continue
                if t0 is None:
                    t0 = rec["ts"]
                    keep = make_filter(
                        since, until, trace, t0, kinds=kinds, job=job
                    )
                if not keep(rec):
                    continue
                counts[rec["kind"]] += 1
                pids.add(rec.get("pid"))
                last_ts = max(last_ts, rec["ts"])
                print(format_line(rec, t0), flush=True)
        except KeyboardInterrupt:
            pass
        if counts:
            _footer(
                counts,
                n_events=sum(counts.values()),
                n_pids=len(pids),
                span=last_ts - (t0 or last_ts),
                out=sys.stdout,
            )

    try:
        # `--follow | head` must exit clean like batch mode — but as 141, so a
        # script can tell the follow was cut short rather than complete.
        if pipe_safe(emit):
            return SIGPIPE_EXIT
    except OSError as e:
        print(f"cannot follow events file: {e}", file=sys.stderr)
        return 1
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render a tpu-resiliency structured event stream as a timeline"
    )
    ap.add_argument("events_file")
    ap.add_argument(
        "--kind",
        help="show only these event kinds (comma-separated); composes with "
        "--since/--until/--trace, and the footer counts the filtered slice",
    )
    ap.add_argument(
        "--since",
        help="drop records before this time: epoch seconds, ISO-8601, or "
        "+SECS relative to the stream's first event (matches the timeline's "
        "t+ offsets) — slice the stream to one incident without grep",
    )
    ap.add_argument(
        "--until",
        help="drop records after this time (same formats as --since)",
    )
    ap.add_argument(
        "--trace",
        help="show only records carrying this trace id (one run on a stream "
        "shared by several)",
    )
    ap.add_argument(
        "--job",
        help="show only records stamped with this fleet job identity "
        "($TPU_RESILIENCY_JOB, the launcher's --rdzv-id under --fleet-dir) — "
        "slice a fleet-merged stream back to one job post-hoc; composes with "
        "the other slicers",
    )
    ap.add_argument(
        "--no-timeline", action="store_true", help="print only the summary footer"
    )
    ap.add_argument(
        "--follow",
        action="store_true",
        help="tail the stream live (Ctrl-C prints the summary); the file may "
        "not exist yet — a launcher that hasn't started still gets watched",
    )
    args = ap.parse_args(argv)
    try:
        # Validate the time specs up front — a typo'd --since must fail the
        # invocation, not silently show the whole stream.
        for spec in (args.since, args.until):
            if spec is not None:
                parse_when(spec)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.follow:
        return _follow(
            args.events_file, args.kind,
            since=args.since, until=args.until, trace=args.trace, job=args.job,
        )
    # read_events tolerates unreadable files (shared-stream readers race the
    # first writer); a CLI invocation on a missing/denied/directory path must
    # fail visibly, not report an empty-but-successful run.
    try:
        with open(args.events_file):
            pass
    except OSError as e:
        print(f"cannot read events file: {e}", file=sys.stderr)
        return 1
    records = read_events(args.events_file)
    keep = None
    if args.since or args.until or args.trace or args.job:
        tss = [r["ts"] for r in records if isinstance(r.get("ts"), (int, float))]
        keep = make_filter(
            args.since, args.until, args.trace, min(tss) if tss else 0.0,
            job=args.job,
        )
    if pipe_safe(
        lambda: summarize(
            records, kind=args.kind, timeline=not args.no_timeline, keep=keep
        )
    ):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
