"""Human timeline over the structured event stream.

The framework narrates every resiliency decision to a JSONL stream
(``$TPU_RESILIENCY_EVENTS_FILE``, ``utils/events.py``): rendezvous rounds,
worker failures and warm-spare promotions, in-process restart iterations,
straggler reports, preemption sync points, FT milestones. This tool is the
consumer side — it renders one run's stream as a timeline plus a summary, the
post-mortem view the reference leaves to ad-hoc log grepping (its torchelastic
events/metrics streams have no bundled reader; its tests grep log lines,
``tests/straggler/func/check_log.py``).

Usage::

    python -m tpu_resiliency.tools.events_summary run_events.jsonl
    python -m tpu_resiliency.tools.events_summary run_events.jsonl --kind worker_failed
    python -m tpu_resiliency.tools.events_summary run_events.jsonl --no-timeline
    python -m tpu_resiliency.tools.events_summary run_events.jsonl --follow
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter
from typing import Any, Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.utils.events import RESERVED_KEYS, read_events


def _payload(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in RESERVED_KEYS}


def _fmt_default(p: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in p.items())


def _fmt_rendezvous_round(p: dict) -> str:
    spares = f" spares={p['spares']}" if p.get("spares") else ""
    return (
        f"round {p.get('round')}: world={p.get('world_size')} "
        f"active={p.get('active')}{spares}"
    )


def _fmt_worker_failed(p: dict) -> str:
    return f"rank {p.get('global_rank')} failed: {p.get('detail', p.get('exitcode'))}"


def _fmt_worker_promoted(p: dict) -> str:
    return (
        f"warm spare promoted -> rank {p.get('global_rank')} "
        f"(round {p.get('round')}, pid {p.get('worker_pid')})"
    )


def _fmt_straggler_report(p: dict) -> str:
    flagged = p.get("stragglers_by_perf") or []
    by_sec = p.get("stragglers_by_section") or {}
    if not flagged and not by_sec:
        return f"step {p.get('step')}: clean ({len(p.get('perf_scores') or {})} ranks)"
    parts = []
    if flagged:
        parts.append(f"by perf {flagged}")
    if by_sec:
        parts.append(f"by section {by_sec}")
    return f"step {p.get('step')}: STRAGGLERS " + ", ".join(parts)


def _fmt_restart_signalled(p: dict) -> str:
    return (
        f"iteration {p.get('iteration')} restarting "
        f"(initial_rank {p.get('initial_rank')})"
    )


_FORMATTERS = {
    "rendezvous_round": _fmt_rendezvous_round,
    "worker_failed": _fmt_worker_failed,
    "worker_promoted": _fmt_worker_promoted,
    "straggler_report": _fmt_straggler_report,
    "restart_signalled": _fmt_restart_signalled,
}

#: Kinds counted in the footer under friendlier names.
_SUMMARY_LINES = (
    ("rendezvous_round", "rendezvous rounds"),
    ("worker_failed", "worker failures"),
    ("worker_promoted", "warm-spare promotions"),
    ("restart_requested", "in-job restart requests"),
    ("restart_signalled", "in-process restarts"),
    ("fn_exception", "in-process fn exceptions"),
    ("rank_terminated", "ranks terminated"),
    ("straggler_report", "straggler reports"),
    ("degraded_set", "degraded-set updates"),
    ("preemption_sync_point", "preemption sync points"),
    ("timeouts_calculated", "FT timeout calibrations"),
    ("training_finished", "training finished"),
    ("budget_exhausted", "restart budget exhausted"),
)


def summarize(
    records: list[dict[str, Any]],
    out=None,
    kind: Optional[str] = None,
    timeline: bool = True,
) -> None:
    out = sys.stdout if out is None else out  # resolved at call time, not import
    records = [r for r in records if "ts" in r and "kind" in r]
    if not records:
        print("no events", file=out)
        return
    records.sort(key=lambda r: r["ts"])
    t0 = records[0]["ts"]
    shown = [r for r in records if kind is None or r["kind"] == kind]
    if timeline:
        for r in shown:
            print(format_line(r, t0), file=out)
    _footer(
        Counter(r["kind"] for r in records),
        n_events=len(records),
        n_pids=len({r.get("pid") for r in records}),
        span=records[-1]["ts"] - t0,
        out=out,
    )


def _footer(counts: Counter, n_events: int, n_pids: int, span: float, out) -> None:
    print(
        f"\n{n_events} events over {span:.1f}s from {n_pids} processes",
        file=out,
    )
    for k, label in _SUMMARY_LINES:
        if counts.get(k):
            print(f"  {label}: {counts[k]}", file=out)
    leftover = {
        k: n for k, n in counts.items() if k not in {k for k, _ in _SUMMARY_LINES}
    }
    if leftover:
        print(f"  other: {dict(sorted(leftover.items()))}", file=out)


def format_line(rec: dict, t0: float) -> str:
    """One timeline line (shared by the batch and --follow paths)."""
    p = _payload(rec)
    line = _FORMATTERS.get(rec["kind"], _fmt_default)(p)
    rank = f" r{rec['rank']}" if rec.get("rank") is not None else ""
    return (
        f"t+{rec['ts'] - t0:9.3f}s [{rec.get('source', '?')}{rank}] "
        f"{rec['kind']}: {line}"
    )


def iter_new_records(path: str, poll: float = 0.5, stop=None):
    """Yield records as writers append them (tail -f over the JSONL stream).

    Binary-mode reads with byte offsets (a character-count offset would
    corrupt the resume position on multi-byte content from non-framework
    producers); torn trailing lines are retried whole on the next poll
    (JSONL writes are single atomic appends, so a partial line only means we
    raced the writer mid-write). Replacement detection is ``tail -F``:
    the file's identity (``st_ino``/``st_dev``) is tracked alongside its
    size, so a recreated events file from a NEW launcher run restarts the
    offset at zero even when the new file has already grown past the old
    offset by the next poll — size-shrink alone would resume mid-file at an
    arbitrary byte. A missing file is the wait state — the launcher may not
    have started — but any other OSError (directory, permission)
    propagates: an unrecoverable path must fail visibly, not hang silently.
    ``stop``: optional ``threading.Event``-like; checked each poll so tests
    (and signal handlers) can end the loop."""
    import json
    import time as _time

    offset = 0
    buf = b""
    file_id = None  # (st_ino, st_dev) of the file the offset belongs to
    while stop is None or not stop.is_set():
        try:
            with open(path, "rb") as f:
                st = os.fstat(f.fileno())
                if file_id is not None and (st.st_ino, st.st_dev) != file_id:
                    # Recreated under the same name (a new launcher run):
                    # the old offset describes a different file entirely.
                    offset = 0
                    buf = b""
                file_id = (st.st_ino, st.st_dev)
                if f.seek(0, 2) < offset:
                    # Truncated in place: restart from the top like tail -f
                    # on shrink.
                    offset = 0
                    buf = b""
                f.seek(offset)
                chunk = f.read()
        except FileNotFoundError:
            chunk = b""
        if chunk:
            offset += len(chunk)
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    yield json.loads(line.decode("utf-8"))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue
        else:
            _time.sleep(poll)


class _StdoutGone:
    """Stop-condition for --follow: fires when stdout's consumer disappears.

    A follower writing into a dead pipe exits via EPIPE on its next print —
    but a follower that is IDLE (quiet stream) never writes again and would
    linger forever after ``| head`` exits. Polling the stdout fd for
    POLLERR/POLLHUP catches the closed pipe without writing; on a terminal
    the poll simply never fires."""

    def __init__(self) -> None:
        import select

        self._poll = None
        try:
            fd = sys.stdout.fileno()
        except Exception:
            # Wrapped/captured stdout (pytest, io wrappers) has no fd: no
            # consumer-death detection, but the follower must still run.
            return
        self._poll = select.poll()
        self._poll.register(fd, select.POLLERR | select.POLLHUP)

    def is_set(self) -> bool:
        if self._poll is None:
            return False
        try:
            return bool(self._poll.poll(0))
        except OSError:
            return True


def _follow(path: str, kind: Optional[str]) -> int:
    # Incremental footer state, not a record list: a multi-day follow on a
    # chatty job must not grow RSS one dict per event.
    counts: Counter = Counter()
    pids: set = set()
    t0: Optional[float] = None
    last_ts = 0.0

    def emit() -> None:
        nonlocal t0, last_ts
        try:
            for rec in iter_new_records(path, stop=_StdoutGone()):
                if "ts" not in rec or "kind" not in rec:
                    continue
                counts[rec["kind"]] += 1
                pids.add(rec.get("pid"))
                if t0 is None:
                    t0 = rec["ts"]
                last_ts = max(last_ts, rec["ts"])
                if kind is None or rec["kind"] == kind:
                    print(format_line(rec, t0), flush=True)
        except KeyboardInterrupt:
            pass
        if counts:
            _footer(
                counts,
                n_events=sum(counts.values()),
                n_pids=len(pids),
                span=last_ts - (t0 or last_ts),
                out=sys.stdout,
            )

    try:
        # `--follow | head` must exit clean like batch mode — but as 141, so a
        # script can tell the follow was cut short rather than complete.
        if pipe_safe(emit):
            return SIGPIPE_EXIT
    except OSError as e:
        print(f"cannot follow events file: {e}", file=sys.stderr)
        return 1
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render a tpu-resiliency structured event stream as a timeline"
    )
    ap.add_argument("events_file")
    ap.add_argument("--kind", help="show only this event kind in the timeline")
    ap.add_argument(
        "--no-timeline", action="store_true", help="print only the summary footer"
    )
    ap.add_argument(
        "--follow",
        action="store_true",
        help="tail the stream live (Ctrl-C prints the summary); the file may "
        "not exist yet — a launcher that hasn't started still gets watched",
    )
    args = ap.parse_args(argv)
    if args.follow:
        return _follow(args.events_file, args.kind)
    # read_events tolerates unreadable files (shared-stream readers race the
    # first writer); a CLI invocation on a missing/denied/directory path must
    # fail visibly, not report an empty-but-successful run.
    try:
        with open(args.events_file):
            pass
    except OSError as e:
        print(f"cannot read events file: {e}", file=sys.stderr)
        return 1
    records = read_events(args.events_file)
    if pipe_safe(
        lambda: summarize(records, kind=args.kind, timeline=not args.no_timeline)
    ):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
