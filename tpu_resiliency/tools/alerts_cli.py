"""``tpu-alerts``: render watchtower alert state — live, or by offline replay.

Three modes over one engine (``telemetry/watchtower.py``):

- ``tpu-alerts --url http://host:port`` fetches the live ``GET /alerts``
  document (``tpu-alerts-1``) and renders the rule table, active alerts, and
  recent fire/resolve history.
- ``tpu-alerts events.jsonl`` replays a finished events stream through the
  same engine offline. The watchtower runs on stream time, so the replayed
  (rule, fire_ts, resolve_ts) sequence is byte-identical to what the live run
  emitted — a postmortem needs no running job. ``--json`` prints the sequence
  as one JSON object per line (sorted keys), the byte-comparison surface the
  chaos campaign and the smoke check diff against the live record.
- ``tpu-alerts --rules`` renders the effective rule table (built-ins with any
  ``$TPU_RESILIENCY_ALERT_RULES`` overrides applied) without a job at all.

Usage::

    tpu-alerts --url http://127.0.0.1:9300
    tpu-alerts run/events.jsonl
    tpu-alerts run/events.jsonl --json | diff - expected.jsonl
    tpu-alerts --rules
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import List, Optional

from tpu_resiliency.telemetry.watchtower import (
    ALERTS_SCHEMA,
    default_rules,
    load_rule_overrides,
    replay,
)
from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe


def _fmt_ts(v) -> str:
    return f"{v:.3f}" if isinstance(v, (int, float)) else "-"


def _table(rows: list, header: list, out) -> None:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    line = "  ".join(str(h).ljust(w) for h, w in zip(header, widths))
    print(line, file=out)
    print("-" * len(line), file=out)
    for r in rows:
        print("  ".join(str(c).ljust(w) for c, w in zip(r, widths)), file=out)


def load_events(path: str) -> List[dict]:
    """The events JSONL, torn-tail tolerant: a half-written last line (the
    writer died mid-record) is skipped, not fatal — postmortem streams end
    however the job ended."""
    records: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def render_doc(doc: dict, out=None) -> None:
    out = sys.stdout if out is None else out
    clock = doc.get("clock") or {}
    print(
        f"watchtower{' job=' + doc['job'] if doc.get('job') else ''}: "
        f"hwm={_fmt_ts(clock.get('hwm'))} evals={clock.get('evals', 0)} "
        f"interval={clock.get('eval_interval', '-')}s",
        file=out,
    )
    if doc.get("config_error"):
        print(f"config error: {doc['config_error']}", file=out)
    rows = []
    for r in doc.get("rules") or []:
        rows.append([
            r.get("name", "?"), r.get("severity", "?"), r.get("state", "?"),
            f"{r.get('for_s', 0):g}s", r.get("fired_total", 0),
            r.get("error") or r.get("detail") or "",
        ])
    if rows:
        _table(rows, ["rule", "severity", "state", "for", "fired", "detail"], out)
    active = doc.get("active") or []
    print(f"{len(active)} active alert(s)", file=out)
    for a in active:
        print(
            f"  [{a.get('severity', '?')}] {a.get('rule', '?')} since "
            f"{_fmt_ts(a.get('fire_ts'))}: {a.get('detail')}",
            file=out,
        )
    history = doc.get("history") or []
    if history:
        print(f"last {len(history)} transition(s):", file=out)
        for tr in history:
            print("  " + transition_phrase(tr), file=out)


def transition_phrase(tr: dict) -> str:
    kind = tr.get("kind", "?")
    base = (
        f"{kind} rule={tr.get('rule', '?')} sev={tr.get('severity', '?')} "
        f"at {_fmt_ts(tr.get('fire_ts') if kind == 'alert_fired' else tr.get('resolve_ts'))}"
    )
    if kind == "alert_resolved":
        base += f" after {tr.get('duration_s', '?')}s"
    detail = tr.get("detail")
    return base + (f": {detail}" if detail else "")


def render_rules(out=None) -> None:
    out = sys.stdout if out is None else out
    overrides, err = load_rule_overrides()
    if err:
        print(f"override file error (built-ins apply): {err}", file=out)
    rows = [
        [r.name, r.severity, f"{r.for_s:g}s",
         json.dumps(r.params, sort_keys=True)]
        for r in default_rules(overrides)
    ]
    _table(rows, ["rule", "severity", "for", "params"], out)


def fetch_doc(url: str) -> dict:
    with urllib.request.urlopen(f"{url.rstrip('/')}/alerts", timeout=10) as r:
        doc = json.load(r)
    if not isinstance(doc, dict) or doc.get("schema") != ALERTS_SCHEMA:
        raise ValueError(
            f"not a {ALERTS_SCHEMA} document "
            f"(got schema {doc.get('schema') if isinstance(doc, dict) else None!r})"
        )
    return doc


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-alerts",
        description="Render watchtower alerts from a live job's /alerts "
        "endpoint, or reproduce the exact fire/resolve sequence offline by "
        "replaying an events JSONL through the same engine.",
    )
    ap.add_argument(
        "events", nargs="?", default=None,
        help="events JSONL to replay offline (the run's shared stream)",
    )
    ap.add_argument(
        "--url", default=None,
        help="live telemetry base URL (fetches /alerts instead of replaying)",
    )
    ap.add_argument(
        "--rules", action="store_true",
        help="render the effective rule table (built-ins + "
        "$TPU_RESILIENCY_ALERT_RULES overrides) and exit",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="machine output: the raw /alerts document (--url), or the "
        "replayed transition sequence as one sorted-key JSON object per "
        "line (events replay — the byte-comparison surface)",
    )
    ap.add_argument(
        "--eval-interval", type=float, default=5.0,
        help="replay stream-clock boundary spacing in seconds (default 5.0; "
        "must match the live run's for sequences to compare equal)",
    )
    args = ap.parse_args(argv)
    if args.rules:
        return SIGPIPE_EXIT if pipe_safe(render_rules) else 0
    if bool(args.events) == bool(args.url):
        print("exactly one of <events.jsonl> / --url is required "
              "(or --rules)", file=sys.stderr)
        return 2

    if args.url:
        try:
            doc = fetch_doc(args.url)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"cannot fetch /alerts: {e}", file=sys.stderr)
            return 1

        def emit() -> None:
            if args.json:
                json.dump(doc, sys.stdout, indent=2, sort_keys=True)
                print()
            else:
                render_doc(doc)

        return SIGPIPE_EXIT if pipe_safe(emit) else 0

    try:
        records = load_events(args.events)
    except OSError as e:
        print(f"cannot read events: {e}", file=sys.stderr)
        return 1
    tower, sequence = replay(records, eval_interval=args.eval_interval)

    def emit() -> None:
        if args.json:
            for tr in sequence:
                print(json.dumps(tr, sort_keys=True))
        else:
            print(
                f"replayed {len(records)} record(s): "
                f"{len(sequence)} transition(s)"
            )
            for tr in sequence:
                print("  " + transition_phrase(tr))
            render_doc(tower.status())

    return SIGPIPE_EXIT if pipe_safe(emit) else 0


if __name__ == "__main__":
    sys.exit(main())
