"""Span critical-path analysis over the structured event stream.

Every BENCH script so far re-implemented phase decomposition by hand over the
events JSONL (``bench_restart.py`` walked ``failure_detected`` /
``restart_requested`` / ``rendezvous_round`` timestamps itself;
``bench_reshard.py`` had its own stopwatch). This module is the ONE code path
both the benchmarks and the operator tooling use: it builds the span DAG of a
restart / save / reshard episode from the events JSONL (parenting already
env-propagated by ``utils/tracing.py``), computes the **dominant chain** — the
sequence of spans that actually gates the episode's wall clock — with
per-segment self-time vs overlap, and renders an operator table plus a
Chrome-trace export with the critical path highlighted
(``tools/trace_export.py`` colors the chain's spans distinctly).

Three layers of answer, cheapest first:

- **milestone decomposition** (:func:`restart_decomposition`): the published
  detect / teardown / rendezvous / promote / first-step-ready split, computed
  from the same milestone events ``BENCH_restart.json`` is built from — the
  benchmarks now *consume this function*, so the operator tool and the
  committed numbers can never drift;
- **dominant chain** (:func:`dominant_chain`): walk backward from the episode
  end, at each instant charging the wall clock to the most specific span
  covering it — the restart's critical path reads
  ``launcher.round → rendezvous.round → worker.spawn`` instead of "812 ms";
- **self-time** (:func:`self_time`): a chain span's duration minus its
  children's overlap — the part only THAT span can explain, which is where an
  optimization must land to move the episode.

Usage::

    tpu-critpath run_events.jsonl                       # auto: every episode
    tpu-critpath run_events.jsonl --format json
    tpu-critpath run_events.jsonl --trace run.trace.json  # highlighted trace
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Iterable, Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.utils.events import read_events
from tpu_resiliency.utils.goodput import (
    RESTART_EVIDENCE,
    merge_intervals,
    subtract_intervals,
    total_seconds,
)

SCHEMA = "tpu-critpath-1"


@dataclasses.dataclass
class Span:
    name: str
    source: str
    pid: int
    span_id: Optional[str]
    parent_id: Optional[str]
    t0: float
    t1: float
    finished: bool
    args: dict


def collect_spans(records: Iterable[dict]) -> list[Span]:
    """Pair ``span_begin``/``span_end`` records into :class:`Span` objects.

    Unmatched begins (the process died mid-span — the interesting case)
    become unfinished spans running to end-of-stream, same convention as
    ``trace_export``. Ends without begins are dropped here (they carry no
    interval)."""
    recs = [
        r for r in records
        if isinstance(r.get("ts"), (int, float)) and isinstance(r.get("kind"), str)
    ]
    recs.sort(key=lambda r: r["ts"])
    if not recs:
        return []
    t_last = recs[-1]["ts"]
    open_spans: dict[tuple, dict] = {}
    out: list[Span] = []
    for rec in recs:
        kind = rec["kind"]
        sid = rec.get("span_id")
        if kind == "span_begin" and sid:
            open_spans[(rec.get("pid"), sid)] = rec
        elif kind == "span_end" and sid:
            begin = open_spans.pop((rec.get("pid"), sid), None)
            if begin is None:
                continue
            out.append(Span(
                name=str(begin.get("span", "span")),
                source=str(begin.get("source", "?")),
                pid=begin.get("pid", 0),
                span_id=sid,
                parent_id=begin.get("parent_id"),
                t0=begin["ts"],
                t1=rec["ts"],
                finished=True,
                args={k: v for k, v in begin.items()
                      if k not in ("ts", "kind", "span", "pid", "source",
                                   "span_id", "parent_id", "trace_id")},
            ))
    for (pid, sid), begin in open_spans.items():
        out.append(Span(
            name=str(begin.get("span", "span")),
            source=str(begin.get("source", "?")),
            pid=pid or 0,
            span_id=sid,
            parent_id=begin.get("parent_id"),
            t0=begin["ts"],
            t1=t_last,
            finished=False,
            args={},
        ))
    out.sort(key=lambda s: (s.t0, s.t1))
    return out


def self_time(span: Span, spans: list[Span]) -> float:
    """Span duration minus the union of its children's overlap — the seconds
    only this span's own code can explain."""
    children = [
        (max(c.t0, span.t0), min(c.t1, span.t1))
        for c in spans
        if c.parent_id is not None and c.parent_id == span.span_id
        and c.t1 > span.t0 and c.t0 < span.t1
    ]
    if not children:
        return max(0.0, span.t1 - span.t0)
    own = subtract_intervals(
        merge_intervals([(span.t0, span.t1)]), merge_intervals(children)
    )
    return total_seconds(own)


# -- milestone decomposition ---------------------------------------------------


def _first_ts(recs: list[dict], kind: str, after: float = float("-inf"),
              pred=None) -> Optional[float]:
    for r in recs:
        if r.get("kind") == kind and r["ts"] >= after and (
            pred is None or pred(r)
        ):
            return r["ts"]
    return None


def find_restart_episodes(records: Iterable[dict]) -> list[dict]:
    """Every restart episode in the stream: fault evidence → training
    resumed, decomposed at the launcher's own milestone events. The segment
    arithmetic is the ONE definition ``bench_restart.py`` publishes."""
    recs = [
        r for r in records
        if isinstance(r.get("ts"), (int, float)) and isinstance(r.get("kind"), str)
    ]
    recs.sort(key=lambda r: r["ts"])
    episodes: list[dict] = []
    cursor = float("-inf")
    while True:
        t_fault = next(
            (r["ts"] for r in recs
             if r["kind"] in RESTART_EVIDENCE and r["ts"] > cursor),
            None,
        )
        if t_fault is None:
            return episodes
        ep = _decompose(recs, t_fault)
        episodes.append(ep)
        cursor = ep["t_end"]


def _decompose(
    recs: list[dict],
    t_fault: float,
    resume_ts: Optional[float] = None,
) -> dict:
    t_detect = _first_ts(recs, "failure_detected", t_fault)
    t_request = _first_ts(recs, "restart_requested", t_detect or t_fault)
    t_round = (
        _first_ts(recs, "rendezvous_round", t_request)
        if t_request is not None else None
    )
    t_promote = (
        _first_ts(
            recs, "worker_promoted", t_round,
            pred=lambda r: r.get("outcome", "promoted") == "promoted",
        )
        if t_round is not None else None
    )
    if resume_ts is None and t_round is not None:
        resume_ts = _first_ts(recs, "iteration_start", t_round)
    fast_path = t_request is not None and any(
        r.get("kind") == "rendezvous_fast_path" and r.get("outcome") == "reused"
        and r["ts"] >= t_request for r in recs
    )
    segments: list[dict] = []

    def seg(name: str, start: Optional[float], end: Optional[float]) -> None:
        # Clamped at zero: a milestone pair can invert by a fraction of a
        # millisecond (a promoted shim's first statement beating the
        # launcher's own promote stamp) — that is a 0-length segment, not a
        # missing one.
        if start is not None and end is not None:
            segments.append({
                "name": name, "start": start, "end": max(start, end),
                "duration_ms": round(max(0.0, end - start) * 1e3, 3),
            })

    seg("detect", t_fault, t_detect)
    seg("teardown", t_detect, t_request)
    seg("rendezvous", t_request, t_round)
    if t_promote is not None:
        seg("promote", t_round, t_promote)
        seg("first_step_ready", t_promote, resume_ts)
    else:
        seg("spawn_and_startup", t_round, resume_ts)
    t_end = next(
        (t for t in (resume_ts, t_promote, t_round, t_request, t_detect)
         if t is not None),
        t_fault,
    )
    return {
        "kind": "restart",
        "t_fault": t_fault,
        "t_detect": t_detect,
        "t_request": t_request,
        "t_round": t_round,
        "t_promote": t_promote,
        "t_resume": resume_ts,
        "t_end": t_end,
        "total_ms": round((t_end - t_fault) * 1e3, 3),
        "fast_path": fast_path,
        "promoted": t_promote is not None,
        "segments": segments,
    }


def restart_decomposition(
    records: Iterable[dict],
    *,
    fault_ts: Optional[float] = None,
    resume_ts: Optional[float] = None,
) -> Optional[dict]:
    """The first restart episode's decomposition, with optional external
    anchors: a benchmark that knows the exact fault/resume instants (worker
    stamp files, on the same wall clock as the stream) passes them so the
    published numbers and the pure-events view share one arithmetic."""
    recs = [
        r for r in records
        if isinstance(r.get("ts"), (int, float)) and isinstance(r.get("kind"), str)
    ]
    recs.sort(key=lambda r: r["ts"])
    if fault_ts is None:
        fault_ts = next(
            (r["ts"] for r in recs if r["kind"] in RESTART_EVIDENCE), None
        )
    if fault_ts is None:
        return None
    return _decompose(recs, fault_ts, resume_ts=resume_ts)


def reshard_decomposition(records: Iterable[dict]) -> dict:
    """Phase split of a resharded resume from its own spans/events: plan
    build, ranged peer fetch (wall + bytes), local slice bytes — the
    decomposition ``bench_reshard.py`` publishes."""
    recs = [r for r in records if isinstance(r, dict)]
    spans = collect_spans(recs)
    plan_s = sum(s.t1 - s.t0 for s in spans if s.name == "reshard.plan")
    fetch_spans = [s for s in spans if s.name == "reshard.fetch"]
    fetch_s = total_seconds(
        merge_intervals([(s.t0, s.t1) for s in fetch_spans])
    )
    local = peer = fetches = 0
    for r in recs:
        if r.get("kind") != "reshard_fetch":
            continue
        nbytes = r.get("bytes")
        if not isinstance(nbytes, (int, float)):
            continue
        if r.get("via") == "peer":
            peer += int(nbytes)
            fetches += 1
        else:
            local += int(nbytes)
    return {
        "plan_s": round(plan_s, 6),
        "fetch_s": round(fetch_s, 6),
        "local_bytes": local,
        "peer_bytes": peer,
        "peer_fetches": fetches,
    }


# -- dominant chain ------------------------------------------------------------


def dominant_chain(
    spans: list[Span], t0: float, t1: float, eps: float = 1e-9
) -> list[dict]:
    """The critical chain through ``[t0, t1]``: walking backward from the
    end, each instant is charged to the **most specific** span covering it
    (latest start wins — ``rendezvous.round`` beats the ``launcher.round``
    that contains it), then the walk jumps to that span's start. Instants no
    span covers become explicit ``(gap)`` segments — unexplained wall clock
    is a finding, not something to render around."""
    cands = [s for s in spans if s.t1 > t0 + eps and s.t0 < t1 - eps]
    chain: list[dict] = []
    cursor = t1
    while cursor > t0 + eps:
        covering = [s for s in cands if s.t0 < cursor - eps and s.t1 >= cursor - eps]
        if covering:
            pick = max(covering, key=lambda s: (s.t0, s.t1))
            # Charge `pick` only back to the latest end of a more specific
            # span inside its window — the walk then descends into THAT span
            # (the classic critical-path hop), instead of letting a parent
            # slice swallow its children's structure.
            inner_end = max(
                (s.t1 for s in cands
                 if s is not pick and pick.t0 + eps < s.t1 < cursor - eps
                 and s.t0 > pick.t0 - eps),
                default=pick.t0,
            )
            start = max(inner_end, pick.t0, t0)
            chain.append({
                "span": pick.name,
                "source": pick.source,
                "pid": pick.pid,
                "span_id": pick.span_id,
                "start": start,
                "end": cursor,
                "duration_ms": round((cursor - start) * 1e3, 3),
                "span_duration_ms": round((pick.t1 - pick.t0) * 1e3, 3),
                "self_time_ms": round(self_time(pick, spans) * 1e3, 3),
                "unfinished": not pick.finished,
            })
            cursor = start
        else:
            ended = [s for s in cands if s.t1 < cursor - eps]
            gap_start = max((s.t1 for s in ended), default=t0)
            gap_start = max(gap_start, t0)
            chain.append({
                "span": "(gap)", "source": "-", "pid": None, "span_id": None,
                "start": gap_start, "end": cursor,
                "duration_ms": round((cursor - gap_start) * 1e3, 3),
                "span_duration_ms": None, "self_time_ms": None,
                "unfinished": False,
            })
            cursor = gap_start
    chain.reverse()
    return chain


def analyze(records: Iterable[dict], episode: str = "auto") -> dict:
    """The full document (schema ``tpu-critpath-1``): every detected
    episode's milestone segments + dominant chain; when the stream holds no
    restart episode (or ``episode='window'``), one whole-window chain."""
    recs = [
        r for r in records
        if isinstance(r.get("ts"), (int, float)) and isinstance(r.get("kind"), str)
    ]
    recs.sort(key=lambda r: r["ts"])
    spans = collect_spans(recs)
    doc: dict = {"schema": SCHEMA, "episodes": []}
    if not recs:
        return doc
    lo, hi = recs[0]["ts"], recs[-1]["ts"]
    doc["window"] = [lo, hi]
    episodes = find_restart_episodes(recs) if episode in ("auto", "restart") else []
    if episode == "restart" and not episodes:
        return doc
    if not episodes:
        episodes = [{
            "kind": "window", "t_fault": lo, "t_end": hi,
            "total_ms": round((hi - lo) * 1e3, 3), "segments": [],
        }]
    for ep in episodes:
        start, end = ep["t_fault"], ep["t_end"]
        if end > start:
            ep["chain"] = dominant_chain(spans, start, end)
        else:
            ep["chain"] = []
        doc["episodes"].append(ep)
    return doc


def critical_span_ids(doc: dict) -> set[str]:
    """Every span id on any episode's dominant chain — what
    ``trace_export`` highlights."""
    out: set[str] = set()
    for ep in doc.get("episodes") or []:
        for seg in ep.get("chain") or []:
            if seg.get("span_id"):
                out.add(seg["span_id"])
    return out


def render(doc: dict, out=None) -> None:
    out = sys.stdout if out is None else out
    episodes = doc.get("episodes") or []
    if not episodes:
        print("no episodes found", file=out)
        return
    for i, ep in enumerate(episodes):
        head = f"{ep.get('kind', '?')} episode {i}: total {ep.get('total_ms', 0):.1f} ms"
        extras = []
        if ep.get("fast_path"):
            extras.append("fast-path rendezvous")
        if ep.get("promoted"):
            extras.append("warm-spare promotion")
        if extras:
            head += f" ({', '.join(extras)})"
        print(head, file=out)
        segments = ep.get("segments") or []
        total = ep.get("total_ms") or 0.0
        if segments:
            print("  segments:", file=out)
            for s in segments:
                share = 100.0 * s["duration_ms"] / total if total else 0.0
                print(
                    f"    {s['name']:<18} {s['duration_ms']:>10.1f} ms "
                    f"{share:5.1f}%",
                    file=out,
                )
        chain = ep.get("chain") or []
        if chain:
            print("  critical path (dominant chain):", file=out)
            for seg in chain:
                label = f"[{seg['source']}] {seg['span']}"
                line = f"    {label:<38} {seg['duration_ms']:>10.1f} ms"
                if seg.get("self_time_ms") is not None:
                    line += f"  (self {seg['self_time_ms']:.1f} ms)"
                if seg.get("unfinished"):
                    line += "  UNFINISHED"
                print(line, file=out)


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Critical-path analysis of restart/save/reshard episodes "
        "in a tpu-resiliency events JSONL: milestone decomposition + the "
        "dominant span chain, with optional highlighted Chrome-trace export"
    )
    ap.add_argument("events_file")
    ap.add_argument(
        "--episode", choices=("auto", "restart", "window"), default="auto",
        help="auto: restart episodes when present, else the whole window; "
        "restart: restart episodes only (exit 1 when none); window: one "
        "chain over the whole stream",
    )
    ap.add_argument(
        "--format", choices=("table", "json"), default="table",
    )
    ap.add_argument(
        "--trace", default=None, metavar="OUT",
        help="also write a Chrome trace with the critical-path spans "
        "highlighted (distinct color + critical_path arg; load in "
        "ui.perfetto.dev)",
    )
    ap.add_argument("-o", "--output", default=None)
    args = ap.parse_args(argv)
    try:
        with open(args.events_file):
            pass
    except OSError as e:
        print(f"cannot read events file: {e}", file=sys.stderr)
        return 1
    records = read_events(args.events_file)
    doc = analyze(records, episode=args.episode)
    if not doc.get("episodes"):
        print("no episodes found in the stream", file=sys.stderr)
        return 1
    if args.trace:
        from tpu_resiliency.tools import trace_export

        trace = trace_export.to_chrome_trace(
            records, critical_ids=critical_span_ids(doc)
        )
        with open(args.trace, "w") as f:
            f.write(json.dumps(trace, default=repr) + "\n")
        n_crit = sum(
            1 for e in trace["traceEvents"]
            if e.get("args", {}).get("critical_path")
        )
        print(
            f"wrote {args.trace}: {n_crit} critical-path spans highlighted",
            file=sys.stderr,
        )

    def emit() -> None:
        if args.format == "json":
            json.dump(doc, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            render(doc)

    if args.output:
        with open(args.output, "w") as f:
            old, sys.stdout = sys.stdout, f
            try:
                emit()
            finally:
                sys.stdout = old
        print(f"wrote {args.output}")
        return 0
    if pipe_safe(emit):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
