"""Render an incident artifact as a human-readable postmortem timeline.

The incident engine (``launcher/incident.py``) writes one
``incident-<ts>.json`` per fault: the causally-ordered event window, the
detect → decide → act → recover milestone chain, SLO timings, and the involved
processes' flight-recorder dumps. This tool is the reader — the postmortem an
operator would otherwise assemble from raw JSONL by hand:

    python -m tpu_resiliency.tools.incident_report incidents/incident-...json
    python -m tpu_resiliency.tools.incident_report incidents/            # newest
    python -m tpu_resiliency.tools.incident_report incidents/ --list
    python -m tpu_resiliency.tools.incident_report ... --events   # full window
    python -m tpu_resiliency.tools.incident_report ... --flight   # ring dumps

Exit 0 on a rendered artifact, 1 on a missing/invalid one — CI smoke legs
assert the exit code (``scripts/smoke_observability.sh``).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.launcher.incident import read_incident

_PHASE_TAG = {
    "detect": "DETECT ",
    "decide": "DECIDE ",
    "act": "ACT    ",
    "recover": "RECOVER",
}


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "n/a"


def resolve_artifact(path: str) -> str:
    """A file path is used as-is; a directory resolves to its newest
    ``incident-*.json`` (the artifact an operator usually wants)."""
    if os.path.isdir(path):
        candidates = sorted(
            n for n in os.listdir(path)
            if n.startswith("incident-") and n.endswith(".json")
        )
        if not candidates:
            raise FileNotFoundError(f"no incident-*.json under {path!r}")
        return os.path.join(path, candidates[-1])
    return path


def render(doc: dict, out, show_events: bool = False, show_flight: bool = False) -> None:
    slo = doc.get("slo", {})
    t0 = doc.get("fault_ts") or doc.get("opened_ts") or 0.0
    dur = (doc.get("closed_ts") or t0) - t0
    print(f"incident {doc['id']}  [{doc.get('outcome', '?')}]", file=out)
    print(
        f"  trigger: {doc['trigger']}"
        + (f" — {doc['detail']}" if doc.get("detail") else ""),
        file=out,
    )
    if doc.get("node_id"):
        print(f"  node: {doc['node_id']}", file=out)
    if doc.get("ranks"):
        print(f"  ranks: {doc['ranks']}", file=out)
    if doc.get("trace_id"):
        print(f"  trace: {doc['trace_id']}", file=out)
    print(f"  duration: {dur:.3f}s", file=out)
    print(
        "  slo: detect=" + _fmt_s(slo.get("time_to_detect_s"))
        + " decide=" + _fmt_s(slo.get("time_to_decide_s"))
        + " act=" + _fmt_s(slo.get("time_to_act_s"))
        + " recover=" + _fmt_s(slo.get("time_to_recover_s"))
        + " steps_lost=" + str(slo.get("steps_lost")),
        file=out,
    )

    chain = doc.get("chain", [])
    print(f"\ncausal chain ({len(chain)} milestones):", file=out)
    for m in chain:
        ts = m.get("ts")
        rel = f"t+{ts - t0:8.3f}s" if isinstance(ts, (int, float)) else " " * 11
        rank = f" r{m['rank']}" if m.get("rank") is not None else ""
        print(
            f"  {rel} {_PHASE_TAG.get(m.get('phase'), '?      ')} "
            f"[{m.get('source', '?')}{rank}] {m.get('kind')}: "
            f"{m.get('summary', '')}",
            file=out,
        )
    if not chain:
        print("  (none classified)", file=out)

    flights = doc.get("flight") or {}
    if flights:
        print(f"\nflight recorders ({len(flights)} process(es)):", file=out)
        for ident, records in sorted(flights.items()):
            reasons = [
                r.get("reason") for r in records if r.get("kind") == "flight_flush"
            ]
            span = ""
            tss = [r["ts"] for r in records if isinstance(r.get("ts"), (int, float))]
            if tss:
                span = f", {max(tss) - min(tss):.1f}s window"
            print(
                f"  flight-{ident}: {len(records)} records{span}"
                + (f", flushes: {reasons}" if reasons else " (segments only — "
                   "process died without a flush, e.g. SIGKILL)"),
                file=out,
            )
            if show_flight:
                for r in records:
                    ts = r.get("ts")
                    rel = (
                        f"t+{ts - t0:8.3f}s"
                        if isinstance(ts, (int, float)) else " " * 11
                    )
                    extras = {
                        k: v for k, v in r.items()
                        if k not in ("ts", "source", "kind", "pid", "rank",
                                     "trace_id", "span_id")
                    }
                    print(
                        f"      {rel} [{r.get('source', '?')}] "
                        f"{r.get('kind')} "
                        + " ".join(f"{k}={v}" for k, v in extras.items()),
                        file=out,
                    )

    if show_events:
        from tpu_resiliency.tools.events_summary import format_line

        evs = doc.get("events", [])
        print(f"\nevent window ({len(evs)} records):", file=out)
        for r in evs:
            if isinstance(r.get("ts"), (int, float)) and r.get("kind"):
                print("  " + format_line(r, t0), file=out)


def _list(directory: str, out) -> int:
    rows = []
    for n in sorted(os.listdir(directory)):
        if not (n.startswith("incident-") and n.endswith(".json")):
            continue
        try:
            doc = read_incident(os.path.join(directory, n))
        except (OSError, ValueError) as e:
            rows.append((n, f"INVALID: {e}"))
            continue
        slo = doc.get("slo", {})
        rows.append((
            n,
            f"{doc.get('trigger')} [{doc.get('outcome')}] "
            f"detect={_fmt_s(slo.get('time_to_detect_s'))} "
            f"recover={_fmt_s(slo.get('time_to_recover_s'))}",
        ))
    if not rows:
        print(f"no incidents under {directory}", file=sys.stderr)
        return 1
    for name, desc in rows:
        print(f"{name}  {desc}", file=out)
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render a tpu-resiliency incident artifact as a "
        "postmortem timeline"
    )
    ap.add_argument(
        "artifact",
        help="incident-<ts>.json file, or a directory (newest artifact; "
        "--list shows all)",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="list every artifact in the directory with one-line verdicts",
    )
    ap.add_argument(
        "--events", action="store_true",
        help="also print the full event window",
    )
    ap.add_argument(
        "--flight", action="store_true",
        help="also print each flight-recorder dump line by line",
    )
    args = ap.parse_args(argv)
    if args.list:
        if not os.path.isdir(args.artifact):
            print(f"--list needs a directory, got {args.artifact!r}", file=sys.stderr)
            return 1
        return _list(args.artifact, sys.stdout)
    try:
        path = resolve_artifact(args.artifact)
        doc = read_incident(path)
    except (OSError, ValueError) as e:
        print(f"cannot read incident artifact: {e}", file=sys.stderr)
        return 1
    if pipe_safe(
        lambda: render(
            doc, sys.stdout, show_events=args.events, show_flight=args.flight
        )
    ):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
