"""Render an incident artifact as a human-readable postmortem timeline.

The incident engine (``launcher/incident.py``) writes one
``incident-<ts>.json`` per fault: the causally-ordered event window, the
detect → decide → act → recover milestone chain, SLO timings, and the involved
processes' flight-recorder dumps. This tool is the reader — the postmortem an
operator would otherwise assemble from raw JSONL by hand:

    python -m tpu_resiliency.tools.incident_report incidents/incident-...json
    python -m tpu_resiliency.tools.incident_report incidents/            # newest
    python -m tpu_resiliency.tools.incident_report incidents/ --list
    python -m tpu_resiliency.tools.incident_report ... --events   # full window
    python -m tpu_resiliency.tools.incident_report ... --flight   # ring dumps

Exit 0 on a rendered artifact, 1 on a missing/invalid one — CI smoke legs
assert the exit code (``scripts/smoke_observability.sh``).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from tpu_resiliency.tools import SIGPIPE_EXIT, pipe_safe
from tpu_resiliency.launcher.incident import read_incident

_PHASE_TAG = {
    "detect": "DETECT ",
    "decide": "DECIDE ",
    "act": "ACT    ",
    "recover": "RECOVER",
}


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "n/a"


def resolve_artifact(path: str) -> str:
    """A file path is used as-is; a directory resolves to its newest
    ``incident-*.json`` (the artifact an operator usually wants)."""
    if os.path.isdir(path):
        candidates = sorted(
            n for n in os.listdir(path)
            if n.startswith("incident-") and n.endswith(".json")
        )
        if not candidates:
            raise FileNotFoundError(f"no incident-*.json under {path!r}")
        return os.path.join(path, candidates[-1])
    return path


def render(doc: dict, out, show_events: bool = False, show_flight: bool = False) -> None:
    slo = doc.get("slo", {})
    t0 = doc.get("fault_ts") or doc.get("opened_ts") or 0.0
    dur = (doc.get("closed_ts") or t0) - t0
    print(f"incident {doc['id']}  [{doc.get('outcome', '?')}]", file=out)
    print(
        f"  trigger: {doc['trigger']}"
        + (f" — {doc['detail']}" if doc.get("detail") else ""),
        file=out,
    )
    if doc.get("node_id"):
        print(f"  node: {doc['node_id']}", file=out)
    if doc.get("ranks"):
        print(f"  ranks: {doc['ranks']}", file=out)
    if doc.get("trace_id"):
        print(f"  trace: {doc['trace_id']}", file=out)
    print(f"  duration: {dur:.3f}s", file=out)
    print(
        "  slo: detect=" + _fmt_s(slo.get("time_to_detect_s"))
        + " decide=" + _fmt_s(slo.get("time_to_decide_s"))
        + " act=" + _fmt_s(slo.get("time_to_act_s"))
        + " recover=" + _fmt_s(slo.get("time_to_recover_s"))
        + " steps_lost=" + str(slo.get("steps_lost")),
        file=out,
    )

    census = doc.get("census")
    if isinstance(census, dict):
        _render_census(census, out)

    ev_dumps = [
        r for r in doc.get("events", []) if r.get("kind") == "stack_dump"
    ]
    if ev_dumps:
        by_rank = {}
        for d in ev_dumps:
            by_rank.setdefault(d.get("rank"), []).append(d)
        print(
            f"\nstack dumps in window: {len(ev_dumps)} "
            + ", ".join(
                f"rank {r}: {len(ds)} ({ds[-1].get('reason')})"
                for r, ds in sorted(by_rank.items(), key=lambda kv: str(kv[0]))
            ),
            file=out,
        )

    chain = doc.get("chain", [])
    print(f"\ncausal chain ({len(chain)} milestones):", file=out)
    for m in chain:
        ts = m.get("ts")
        rel = f"t+{ts - t0:8.3f}s" if isinstance(ts, (int, float)) else " " * 11
        rank = f" r{m['rank']}" if m.get("rank") is not None else ""
        print(
            f"  {rel} {_PHASE_TAG.get(m.get('phase'), '?      ')} "
            f"[{m.get('source', '?')}{rank}] {m.get('kind')}: "
            f"{m.get('summary', '')}",
            file=out,
        )
    if not chain:
        print("  (none classified)", file=out)

    flights = doc.get("flight") or {}
    if flights:
        print(f"\nflight recorders ({len(flights)} process(es)):", file=out)
        for ident, records in sorted(flights.items()):
            reasons = [
                r.get("reason") for r in records if r.get("kind") == "flight_flush"
            ]
            dumps = [r for r in records if r.get("kind") == "stack_dump"]
            if dumps:
                n_threads = sum(
                    d.get("thread_count") or len(d.get("threads") or [])
                    for d in dumps
                )
                print(
                    f"  flight-{ident}: {len(dumps)} stack dump(s) "
                    f"({n_threads} thread stacks) — reasons "
                    f"{[d.get('reason') for d in dumps]}",
                    file=out,
                )
            span = ""
            tss = [r["ts"] for r in records if isinstance(r.get("ts"), (int, float))]
            if tss:
                span = f", {max(tss) - min(tss):.1f}s window"
            print(
                f"  flight-{ident}: {len(records)} records{span}"
                + (f", flushes: {reasons}" if reasons else " (segments only — "
                   "process died without a flush, e.g. SIGKILL)"),
                file=out,
            )
            if show_flight:
                for r in records:
                    ts = r.get("ts")
                    rel = (
                        f"t+{ts - t0:8.3f}s"
                        if isinstance(ts, (int, float)) else " " * 11
                    )
                    extras = {
                        k: v for k, v in r.items()
                        if k not in ("ts", "source", "kind", "pid", "rank",
                                     "trace_id", "span_id")
                    }
                    print(
                        f"      {rel} [{r.get('source', '?')}] "
                        f"{r.get('kind')} "
                        + " ".join(f"{k}={v}" for k, v in extras.items()),
                        file=out,
                    )

    if show_events:
        from tpu_resiliency.tools.events_summary import format_line

        evs = doc.get("events", [])
        print(f"\nevent window ({len(evs)} records):", file=out)
        for r in evs:
            if isinstance(r.get("ts"), (int, float)) and r.get("kind"):
                print("  " + format_line(r, t0), file=out)


def _render_census(census: dict, out) -> None:
    """The hang-census table: who was stuck where, who never arrived."""
    ranks = census.get("ranks") or []
    barriers = census.get("barriers") or []
    suspects = census.get("suspects") or []
    print(f"\nhang census ({len(ranks)} rank(s), "
          f"{len(barriers)} open barrier(s)):", file=out)
    for r in ranks:
        stuck = r.get("stuck_s")
        stuck_s = f"{stuck:.1f}s" if isinstance(stuck, (int, float)) else "?"
        flags = []
        if r.get("kill_pending"):
            flags.append("KILL-PENDING")
        if r.get("terminated"):
            flags.append("TERMINATED")
        print(
            f"  rank {r.get('rank')} (pid {r.get('pid')}): stuck {stuck_s}"
            + (f" — {r['where']}" if r.get("where") else "")
            + (f" [{' '.join(flags)}]" if flags else ""),
            file=out,
        )
    for b in barriers:
        arrived = b.get("arrived") or {}
        waiters = ", ".join(
            f"r{k}({v:.0f}s)" if isinstance(v, (int, float)) else f"r{k}"
            for k, v in sorted(arrived.items(), key=lambda kv: str(kv[0]))
        )
        print(
            f"  barrier {b.get('name')}: {len(arrived)}/{b.get('world_size')} "
            f"arrived [{waiters}]"
            + (f", never arrived {b['missing']}" if b.get("missing") else "")
            + (f", absent {b['absent']}" if b.get("absent") else ""),
            file=out,
        )
    if suspects:
        print("  suspects:", file=out)
        for s in suspects:
            why = "; ".join(s.get("reasons") or [])
            print(f"    rank {s.get('rank')} (score {s.get('score')}): {why}",
                  file=out)


def _list(directory: str, out) -> int:
    rows = []
    for n in sorted(os.listdir(directory)):
        if not (n.startswith("incident-") and n.endswith(".json")):
            continue
        try:
            doc = read_incident(os.path.join(directory, n))
        except (OSError, ValueError) as e:
            rows.append((n, f"INVALID: {e}"))
            continue
        slo = doc.get("slo", {})
        rows.append((
            n,
            f"{doc.get('trigger')} [{doc.get('outcome')}] "
            f"detect={_fmt_s(slo.get('time_to_detect_s'))} "
            f"recover={_fmt_s(slo.get('time_to_recover_s'))}",
        ))
    if not rows:
        print(f"no incidents under {directory}", file=sys.stderr)
        return 1
    for name, desc in rows:
        print(f"{name}  {desc}", file=out)
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render a tpu-resiliency incident artifact as a "
        "postmortem timeline"
    )
    ap.add_argument(
        "artifact",
        help="incident-<ts>.json file, or a directory (newest artifact; "
        "--list shows all)",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="list every artifact in the directory with one-line verdicts",
    )
    ap.add_argument(
        "--events", action="store_true",
        help="also print the full event window",
    )
    ap.add_argument(
        "--flight", action="store_true",
        help="also print each flight-recorder dump line by line",
    )
    args = ap.parse_args(argv)
    if args.list:
        if not os.path.isdir(args.artifact):
            print(f"--list needs a directory, got {args.artifact!r}", file=sys.stderr)
            return 1
        return _list(args.artifact, sys.stdout)
    try:
        path = resolve_artifact(args.artifact)
        doc = read_incident(path)
    except (OSError, ValueError) as e:
        print(f"cannot read incident artifact: {e}", file=sys.stderr)
        return 1
    if pipe_safe(
        lambda: render(
            doc, sys.stdout, show_events=args.events, show_flight=args.flight
        )
    ):
        return SIGPIPE_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
